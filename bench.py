"""Benchmark: federated training throughput of the flagship workload.

Measures the ABCD-sex-classification federated simulation — AlexNet3D_Dropout
(bf16 compute, rematerialized conv blocks) over full-size 121x145x121
volumes, 4 simulated site-clients, batch 16, torch-parity SGD with
post-round weighted FedAvg aggregation — with MULTIPLE federated rounds
compiled into one XLA program (``lax.scan`` over rounds), the TPU-native
shape of the whole framework. Reports samples/second of federated local SGD
(forward + backward + optimizer + aggregation).

``vs_baseline`` compares against the reference's single-V100 sequential
simulation. The reference publishes NO numbers (BASELINE.md), so the
baseline constant below is an engineering estimate of AlexNet3D_Dropout
training throughput on one V100 (torch 1.12, batch 16, 121^3 volumes,
~0.25 s/step incl. HDF5 reads => ~64 samples/s). The north-star target in
BASELINE.json is >= 8x on multi-chip; this bench runs on however many chips
are visible (1 in the current harness).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

V100_BASELINE_SAMPLES_PER_SEC = 64.0  # documented estimate, see module docstring


def main() -> None:
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.config import OptimConfig
    from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer
    from neuroimagedisttraining_tpu.models import AlexNet3D_Dropout
    from neuroimagedisttraining_tpu.utils.pytree import tree_weighted_mean

    n_clients = 4          # simulated clients per chip
    batch = 16             # reference canonical batch (BASELINE.md)
    steps = 4              # local steps per client per round
    rounds_per_call = 4    # federated rounds fused into one XLA program
    shape = (121, 145, 121)
    n_local = 64           # device-resident samples per client (uint8)

    model = AlexNet3D_Dropout(num_classes=1, dtype=jnp.bfloat16)
    trainer = LocalTrainer(model, OptimConfig(batch_size=batch, epochs=1),
                           num_classes=1)

    cs0 = trainer.init_client_state(jax.random.key(0),
                                    jnp.zeros((1,) + shape, jnp.float32))
    X = jax.random.randint(jax.random.key(2),
                           (n_clients, n_local) + shape, 0, 255,
                           dtype=jnp.int32).astype(jnp.uint8)
    y = jax.random.randint(jax.random.key(3), (n_clients, n_local), 0, 2,
                           dtype=jnp.int32)
    n_valid = jnp.full((n_clients,), n_local, jnp.int32)
    max_samples = steps * batch

    def bcast(t):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_clients,) + x.shape), t)

    @jax.jit
    def simulate(params, bstats, X, y, n_valid, rng):
        w = n_valid.astype(jnp.float32)
        def round_body(carry, r):
            params, bstats, rng = carry
            rng, sub = jax.random.split(rng)
            cs = ClientState(params=bcast(params), batch_stats=bcast(bstats),
                             opt_state=bcast(trainer.opt.init(params)),
                             rng=jax.random.split(sub, n_clients))

            def local(cs_c, Xc, yc, nc):
                return trainer.local_train(cs_c, Xc, yc, nc,
                                           jnp.float32(1e-3), epochs=1,
                                           batch_size=batch,
                                           max_samples=max_samples)

            cs, losses = jax.vmap(local)(cs, X, y, n_valid)
            params = tree_weighted_mean(cs.params, w)
            bstats = tree_weighted_mean(cs.batch_stats, w)
            return (params, bstats, rng), jnp.mean(losses)

        (params, bstats, _), losses = jax.lax.scan(
            round_body, (params, bstats, rng), jnp.arange(rounds_per_call))
        return params, bstats, jnp.mean(losses)

    params, bstats = cs0.params, cs0.batch_stats
    # compile + warmup (first call includes compilation)
    params, bstats, loss = simulate(params, bstats, X, y, n_valid,
                                    jax.random.key(7))
    float(loss)  # hard sync through the host

    n_calls = 3
    t0 = time.perf_counter()
    for i in range(n_calls):
        params, bstats, loss = simulate(params, bstats, X, y, n_valid,
                                        jax.random.key(i))
    float(loss)  # hard sync
    dt = time.perf_counter() - t0

    samples = n_calls * rounds_per_call * n_clients * steps * batch
    sps = samples / dt
    print(json.dumps({
        "metric": "abcd_fedavg_train_samples_per_sec",
        "value": round(sps, 2),
        "unit": "samples/s (AlexNet3D 121x145x121, b16, 4 clients, "
                "4 rounds/program)",
        "vs_baseline": round(sps / V100_BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
