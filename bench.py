"""Benchmark: federated training throughput of the flagship workload,
measured on the SHIPPED engine path.

Phase 1 — FedAvg rounds: times ``FedAvgEngine._round_jit`` (the exact
program ``engine.train()`` runs: gather sampled clients -> vmapped local SGD
-> weighted-mean aggregation) on AlexNet3D_Dropout over full-size
121x145x121 volumes in the flagship DEPLOYMENT layout: ONE client per
chip (the multi-chip design shards the client axis, one site per core),
batch 128, 512-sample resident shard. Batch 128 is the measured
single-chip sweet spot (round-3 sweep, PROFILE.md): it fills the MXU's
batch/sublane dimensions that the reference-canonical b16 leaves idle —
b16 measured 3.5% MFU in the same session window where b128 measured
10.0%. A V100 cannot hold b128 of this model's activations at all; using
HBM for large-batch compute is the point of the TPU-first design. The
reference-parity cell (4 clients x b16) stays measurable via
``BENCH_CLIENTS=4 BENCH_BATCH=16 BENCH_LOCAL=64`` and is recorded by
scripts/run_bench_matrix.sh.

Phase 2 — SalientGrads mask: times the one-shot federated SNIP mask
pipeline (per-client saliency scores -> mean -> global top-k), giving the
Pallas histogram-select kernel (ops/topk.py) real TPU executions, and
asserts its threshold equals the XLA fallback's on-device.

Reported extras: analytic GFLOP/sample (ops/flops.py), sustained TFLOP/s,
and MFU against the visible chip's bf16 peak (device-kind table; "mfu" is
null when the chip is unknown).

Wire-codec cell (ISSUE 3): encodes a real client upload from the shipped
round program with the cross-silo wire codec — fedavg delta+quant and
masked sparse+quant against the phase-2 SNIP mask — reporting frame
bytes vs the dense msgpack wire, encode/decode ms, and the overhead as a
fraction of the measured round wall time (acceptance: < 10%).

Phase 3 — one-round timings for every other engine program, now including
the flagship's steady-state MASKED round (salientgrads phase 2), ditto
(dual-track: ~2x compute/sample), fedprox, local, and turboaggregate
(with the MPC aggregation stage — device-jitted by default — also timed
alone).

``vs_baseline`` compares against the reference's single-V100 sequential
simulation. The reference publishes NO numbers (BASELINE.md), so the
denominator is an ANALYTIC {low=48, mid=64, high=96} samples/s bound
derived in BASELINE.md ("Derived V100 throughput bound": 22.36
GFLOP/sample x V100 fp32 roofline x assumed Conv3d MFU range);
``vs_baseline`` divides by mid and ``vs_baseline_range`` carries the
[value/high, value/low] spread. North star: >= 8x (BASELINE.json).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Fused-dispatch cell (ISSUE 4): ``rounds_per_dispatch`` times K
sequential single-round dispatches against ONE K-round ``lax.scan``
program (the ``--rounds_per_dispatch`` driver mode; bitwise equality of
the two is pinned in tests/test_dispatch.py) and reports the speedup —
the dispatch-amortization win PROFILE.md round 2 measured at 2.4x.

Env knobs: BENCH_BATCH (default 128), BENCH_CLIENTS (1), BENCH_LOCAL
(512), BENCH_ROUNDS (3), BENCH_REPS (3 — best-of-N timed repeats; the
harness chip is time-shared, PROFILE.md round 2), BENCH_DISPATCH_K
(4; <= 1 skips the fused-dispatch cell), NIDT_COMPILE_CACHE (persistent
compile cache dir; off by default for the bench), BENCH_SHAPE /
BENCH_MODEL (CPU smoke runs of the harness itself).
"""

from __future__ import annotations

import json
import os
import time

# {low, mid, high} analytic V100 throughput bound — derivation with MFU
# assumptions in BASELINE.md ("Derived V100 throughput bound").
# vs_baseline divides by MID; vs_baseline_range spans [value/high, value/low].
V100_BASELINE_LOW = 48.0
V100_BASELINE_SAMPLES_PER_SEC = 64.0   # mid
V100_BASELINE_HIGH = 96.0

# per-chip bf16 peak FLOP/s by device kind substring
_PEAK_TFLOPS = {
    "v2": 45.0, "v3": 123.0, "v4": 275.0,
    "v5e": 197.0, "v5 lite": 197.0, "v5p": 459.0,
    "v6e": 918.0, "trillium": 918.0,
}


def _chip_peak_tflops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in sorted(_PEAK_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return peak
    return None


def cohort_sharding_cell(n_devices: int) -> dict:
    """Cohort-sharding bench cell (ISSUE 6): per-round wall time vs C for
    the sequential C-loop (the reference's client-at-a-time simulation as
    ONE ``lax.map`` program), the cohort-SHARDED program
    (parallel/cohort.py), and the shipped vmapped unsharded round —
    plus the flagship 21-site fedavg + salientgrads cells, the K=4
    fused-window compile-count pin (one compiled program, one dispatch
    per window), and ``salientgrads_mask_ms`` under the sharded phase-1
    driver (PROFILE.md round 7 / ROADMAP item 4 reconciliation).

    Env: BENCH_COHORT_DEVICES=D arms this cell (main() then prints ONLY
    it); BENCH_COHORT_VIRTUAL=1 provisions D virtual CPU devices first
    (the committed bench_matrix/cohort_sharding.json artifact runs this
    way on the 2-core harness — treat the SLOPES and the one-dispatch
    pin as the stable claims there; the absolute speedup is a
    TPU-session measurement). BENCH_COHORT_CLIENTS overrides the C
    sweep."""
    if os.environ.get("BENCH_COHORT_VIRTUAL", "0") == "1":
        from neuroimagedisttraining_tpu.parallel.mesh import (
            provision_virtual_devices,
        )
        provision_virtual_devices(n_devices)

    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import FederatedData
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    D = n_devices
    batch = int(os.environ.get("BENCH_BATCH", 8))
    n_local = int(os.environ.get("BENCH_LOCAL", 16))
    reps = int(os.environ.get("BENCH_REPS", 3))
    shape = tuple(int(s) for s in
                  os.environ.get("BENCH_SHAPE", "12,14,12").split(","))
    model_name = os.environ.get("BENCH_MODEL", "3dcnn_tiny")
    c_env = os.environ.get("BENCH_COHORT_CLIENTS", "")
    Cs = ([int(c) for c in c_env.split(",")] if c_env
          else sorted({D, 2 * D, 21, 3 * D}))

    mesh = make_mesh(num_devices=D)
    log = ExperimentLogger("/tmp/nidt_bench", "synthetic", "cohort_cell",
                           console=False)

    def make_fed(C: int, pad_to: int | None, sharded: bool):
        P = C if pad_to is None else pad_to
        kx, ky = jax.random.split(jax.random.key(4))
        X = jax.random.randint(kx, (P, n_local) + shape, 0, 255,
                               dtype=jnp.int32).astype(jnp.uint8)
        y = jax.random.randint(ky, (P, n_local), 0, 2, dtype=jnp.int32)
        n = jnp.asarray([n_local] * C + [0] * (P - C), jnp.int32)
        fed = FederatedData(X_train=X, y_train=y, n_train=n,
                            X_test=X[:, :4], y_test=y[:, :4],
                            n_test=jnp.where(n > 0, 4, 0))
        if sharded:
            from neuroimagedisttraining_tpu.parallel.mesh import (
                shard_federation,
            )
            fed = shard_federation(fed, mesh)
        return fed

    def engine_for(C: int, mode: str, algorithm: str = "fedavg"):
        """mode: 'sharded' | 'sequential' (C-loop reference) |
        'vmapped' (the shipped unsharded default)."""
        pad = ((C + D - 1) // D) * D
        cfg = ExperimentConfig(
            model=model_name, num_classes=1, algorithm=algorithm,
            data=DataConfig(dataset="synthetic"),
            optim=OptimConfig(lr=1e-3, batch_size=batch, epochs=1),
            fed=FedConfig(client_num_in_total=C, comm_round=3,
                          frequency_of_the_test=10 ** 9,
                          client_mesh=D if mode != "vmapped" else 0),
            log_dir="/tmp/nidt_bench", tag=f"cohort-{mode}-{C}")
        trainer = LocalTrainer(create_model(model_name, num_classes=1),
                               cfg.optim, num_classes=1)
        use_mesh = None if mode == "vmapped" else mesh
        fed = make_fed(C, None if mode == "vmapped" else pad,
                       sharded=mode != "vmapped")
        eng = create_engine(algorithm, cfg, fed, trainer, mesh=use_mesh,
                            logger=log)
        eng._donate = False
        if mode == "sequential":
            eng._cohort_sequential = True
        return eng

    def bestof(fn):
        fn()  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            best = min(best, time.perf_counter() - t0)
        return best

    cells: dict[str, dict] = {}
    for C in Cs:
        row: dict[str, float] = {}
        for mode in ("sequential", "sharded", "vmapped"):
            eng = engine_for(C, mode)
            gs = eng.init_global_state()
            sampled = eng.client_sampling(0)
            if mode == "vmapped":
                rngs = eng.per_client_rngs(0, sampled)
                fn = lambda e=eng, g=gs, s=sampled, r=rngs: e._round_jit(
                    g.params, g.batch_stats, e.data, jnp.asarray(s), r,
                    e.round_lr(0))
            else:
                ids, n_real = eng._cohort_pad(sampled)
                rngs = eng.per_client_rngs(0, ids)
                row["n_pad"] = len(ids)
                fn = lambda e=eng, g=gs, i=ids, r=rngs, nr=n_real: \
                    e._sharded_round_jit(nr)(
                        g.params, g.batch_stats, e.data, jnp.asarray(i),
                        r, e.round_lr(0))
            key = {"sequential": "sequential_loop_s",
                   "sharded": "sharded_s",
                   "vmapped": "vmapped_unsharded_s"}[mode]
            row[key] = round(bestof(fn), 4)
        row["speedup_vs_sequential_loop"] = round(
            row["sequential_loop_s"] / row["sharded_s"], 3)
        cells[str(C)] = row

    # slopes (s per client) from a least-squares fit over the C sweep —
    # the stable claim on a noisy shared host
    xs = np.asarray(Cs, np.float64)
    slope = {}
    for key in ("sequential_loop_s", "sharded_s", "vmapped_unsharded_s"):
        ys = np.asarray([cells[str(C)][key] for C in Cs])
        slope[key] = float(np.polyfit(xs, ys, 1)[0])
    slope["sharded_over_sequential"] = round(
        slope["sharded_s"] / max(slope["sequential_loop_s"], 1e-12), 4)
    slope = {k: round(v, 6) for k, v in slope.items()}

    # flagship 21-site salientgrads: sharded masked round + mask pipeline
    sg_sh = engine_for(21, "sharded", "salientgrads")
    sg_un = engine_for(21, "vmapped", "salientgrads")
    gs = sg_sh.init_global_state()
    mask_sync = lambda m: float(sum(jnp.sum(x)
                                    for x in jax.tree.leaves(m)))
    t_mask = {}
    for name, e in (("cohort_sharded", sg_sh), ("unsharded", sg_un)):
        e.generate_global_mask(gs.params, gs.batch_stats)  # compile+warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            m, _ = e.generate_global_mask(gs.params, gs.batch_stats)
            mask_sync(m)
            best = min(best, time.perf_counter() - t0)
        t_mask[name] = round(best * 1e3, 1)
    masks, _ = sg_sh.generate_global_mask(gs.params, gs.batch_stats)
    per = sg_sh.broadcast_states(gs, sg_sh.num_clients)
    sampled = sg_sh.client_sampling(0)
    ids, n_real = sg_sh._cohort_pad(sampled)
    rngs = sg_sh.per_client_rngs(0, ids)
    sg_round_s = bestof(lambda: sg_sh._sharded_round_jit(n_real)(
        gs.params, gs.batch_stats, per.params, per.batch_stats,
        sg_sh.data, masks, jnp.asarray(ids), rngs, sg_sh.round_lr(0)))

    # K=4 fused window: ONE compiled program, ONE dispatch per window
    fz = engine_for(21, "sharded")
    fz.cfg = dataclasses.replace(
        fz.cfg, fed=dataclasses.replace(fz.cfg.fed, comm_round=4,
                                        rounds_per_dispatch=4))
    gsf = fz.init_global_state()
    w_s = bestof(lambda: fz._run_fused_window(
        jax.tree.map(jnp.copy, gsf.params),
        jax.tree.map(jnp.copy, gsf.batch_stats), 0, 4)[2])
    fused_cache = len(fz.__dict__.get("_fused_round_jit_cache", {}))

    return {
        "metric": "cohort_sharding",
        "devices": D,
        "device_kind": getattr(jax.devices()[0], "device_kind",
                               "unknown"),
        "model": model_name, "shape": "x".join(map(str, shape)),
        "batch": batch, "n_local": n_local,
        "cells_per_round_s": cells,
        "slope_s_per_client": slope,
        "flagship_21_salientgrads": {
            "sharded_round_s": round(sg_round_s, 4),
            "mask_ms": t_mask,
        },
        "fused_k4_window": {
            "window_s": round(w_s, 4),
            "per_round_s": round(w_s / 4, 4),
            "compiled_programs": fused_cache,
            "dispatches_per_window": 1,
        },
        "timing": f"best of {reps} repeats",
        "caveat": ("virtual-CPU-mesh numbers when BENCH_COHORT_VIRTUAL=1 "
                   "(2-core harness): the slope ratio and the one-"
                   "dispatch pin are the stable claims; the absolute "
                   "sharded speedup is a TPU-session measurement"),
    }


def obs_overhead_cell() -> dict:
    """Obs overhead guard (ISSUE 9, extended by ISSUE 14): the SAME
    smoke round loop timed with the telemetry plane disarmed (tracer
    off, registry disabled) and armed (tracer writing spans, registry
    enabled, stat_info published per round — a HARSHER cadence than the
    shipped driver, which publishes at eval boundaries only). Since
    ISSUE 14 every dispatch ALSO feeds the compute-plane profiler
    (obs/compute.py: two clock reads + a nidt_dispatch_ms observe per
    dispatch, an MFU boundary close per publish) — the armed leg
    exercises the full dispatch-boundary instrumentation, so this cell
    IS the profiler-armed overhead acceptance. Because instrumentation
    sits only at host dispatch boundaries, the per-round cost is a few
    microseconds against a multi-millisecond round — acceptance:
    overhead <= 2% (bench_matrix/obs_overhead.json).

    Env: BENCH_OBS_OVERHEAD=1 arms this cell (main() prints ONLY it);
    BENCH_OBS_ROUNDS / BENCH_REPS size the loop."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import FederatedData
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
    from neuroimagedisttraining_tpu.obs import trace as obs_trace
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    batch = int(os.environ.get("BENCH_BATCH", 8))
    n_local = int(os.environ.get("BENCH_LOCAL", 16))
    n_clients = 4
    # floor of 1: zero rounds/reps would leave the timed legs undefined
    rounds = max(1, int(os.environ.get("BENCH_OBS_ROUNDS", 6)))
    reps = max(1, int(os.environ.get("BENCH_REPS", 5)))
    shape = tuple(int(s) for s in
                  os.environ.get("BENCH_SHAPE", "12,14,12").split(","))
    model_name = os.environ.get("BENCH_MODEL", "3dcnn_tiny")

    cfg = ExperimentConfig(
        model=model_name, num_classes=1, algorithm="fedavg",
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(lr=1e-3, batch_size=batch, epochs=1),
        fed=FedConfig(client_num_in_total=n_clients, comm_round=rounds,
                      frequency_of_the_test=10 ** 9),
        log_dir="/tmp/nidt_bench", tag="obs-overhead")
    kx, ky = jax.random.split(jax.random.key(7))
    X = jax.random.randint(kx, (n_clients, n_local) + shape, 0, 255,
                           dtype=jnp.int32).astype(jnp.uint8)
    y = jax.random.randint(ky, (n_clients, n_local), 0, 2,
                           dtype=jnp.int32)
    n = jnp.full((n_clients,), n_local, jnp.int32)
    fed = FederatedData(X_train=X, y_train=y, n_train=n,
                        X_test=X[:, :4], y_test=y[:, :4],
                        n_test=jnp.full((n_clients,), 4, jnp.int32))
    trainer = LocalTrainer(create_model(model_name, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger("/tmp/nidt_bench", "synthetic",
                           "obs_overhead_cell", console=False)
    engine = create_engine("fedavg", cfg, fed, trainer, logger=log)
    engine._donate = False  # the legs replay one state through the jit
    gs = engine.init_global_state()
    sampled = jnp.asarray(engine.client_sampling(0))

    def run_rounds(armed: bool) -> float:
        p, b = gs.params, gs.batch_stats
        for r in range(rounds):
            rngs = engine.per_client_rngs(r, np.arange(n_clients))
            with obs_trace.span("round", round=r):
                p, b, loss, _ = engine._round_jit(
                    p, b, fed, sampled, rngs, engine.round_lr(r))
            if armed:
                # harsher-than-shipped publish cadence: every round
                engine.stat_info["sum_training_flops"] += 1.0
                engine.publish_stat_info(r)
        return float(loss)  # full sync closes the timed region

    def set_leg(armed: bool) -> None:
        if armed:
            obs_metrics.enable()
            obs_trace.arm("/tmp/nidt_bench/obs_overhead_trace.json",
                          tags={"bench": "obs_overhead"})
        else:
            obs_metrics.disable()
            obs_trace.disarm()

    run_rounds(False)  # compile + warm
    legs = {"disarmed": float("inf"), "armed": float("inf")}
    ratios = []
    # legs INTERLEAVED per repeat: the shared-box load drifts on the
    # seconds scale, and back-to-back leg blocks would alias that drift
    # into a fake (even negative) "overhead". The estimator is the
    # MEDIAN of per-repeat armed/disarmed ratios — each repeat's pair
    # runs temporally adjacent, so low-frequency drift cancels WITHIN
    # the pair, where a best-of-each-leg quotient compares two
    # different load windows and can swing past the ±2% bound on a
    # drifty box (measured: best-of quotients ranged −5.7%..+17.8% on
    # an idle sandbox while paired medians sit at the noise floor).
    for _ in range(reps):
        pair = {}
        for name, armed in (("disarmed", False), ("armed", True)):
            set_leg(armed)
            t0 = time.perf_counter()
            run_rounds(armed)
            pair[name] = time.perf_counter() - t0
            legs[name] = min(legs[name], pair[name])
        ratios.append(pair["armed"] / pair["disarmed"])
    obs_metrics.enable()
    obs_trace.disarm()
    overhead = float(np.median(ratios)) - 1.0
    return {
        "metric": "obs_overhead",
        "model": model_name, "shape": "x".join(map(str, shape)),
        "batch": batch, "clients": n_clients, "rounds_per_leg": rounds,
        "disarmed_s": round(legs["disarmed"], 4),
        "armed_s": round(legs["armed"], 4),
        "per_rep_ratios": [round(r, 4) for r in ratios],
        "overhead_frac": round(overhead, 4),
        "acceptance": "overhead_frac <= 0.02 (armed = span per round + "
                      "stat_info publish per round + tracer buffering + "
                      "the ISSUE 14 dispatch profiler: nidt_dispatch_ms "
                      "observe per dispatch, MFU boundary per publish)",
        "timing": f"median of {reps} paired-repeat ratios x {rounds} "
                  "rounds (legs best-of for reference)",
    }


def precision_cell() -> dict:
    """Precision/fused-update bench cell (ISSUE 10): the SAME shipped
    FedAvg round program timed under three train-step configurations —
    ``fp32`` (the legacy tree bitwise), ``bf16_mixed`` (bf16 compute +
    activations, f32 master weights — core/optim.py), and ``bf16_mixed``
    with the fused mask/clip/momentum/update tail
    (``--fused_update``, ops/fused_update.py) — plus a compile-time
    peak-memory estimate per leg (XLA's ``memory_analysis`` temp/argument
    bytes: the activation working set the remat policy trades against)
    and the parity numbers the tolerance pins state (bf16-vs-fp32 loss
    delta; fused-vs-unfused bitwise flag on this backend).

    Env: BENCH_PRECISION=1 arms this cell (main() prints ONLY it);
    BENCH_BATCH / BENCH_LOCAL / BENCH_SHAPE / BENCH_MODEL / BENCH_REMAT /
    BENCH_REPS size it. On the CPU harness the WALL numbers are smoke —
    the honest caveat rides the payload; the real fp32-vs-bf16 step
    ratio and the fused kernel's on-chip win are next-TPU-session
    measurements (scripts/run_precision_bench.sh is the entry point)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.optim import compute_dtype
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import FederatedData
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    batch = int(os.environ.get("BENCH_BATCH", 8))
    n_local = int(os.environ.get("BENCH_LOCAL", 16))
    n_clients = int(os.environ.get("BENCH_CLIENTS", 2))
    reps = max(1, int(os.environ.get("BENCH_REPS", 3)))
    shape = tuple(int(s) for s in
                  os.environ.get("BENCH_SHAPE", "12,14,12").split(","))
    model_name = os.environ.get("BENCH_MODEL", "3dcnn_tiny")
    remat_env = os.environ.get("BENCH_REMAT", "0")
    remat: bool | str | None = {"0": False, "1": True}.get(remat_env,
                                                           remat_env)
    steps = -(-n_local // batch)

    kx, ky = jax.random.split(jax.random.key(11))
    X = jax.random.randint(kx, (n_clients, n_local) + shape, 0, 255,
                           dtype=jnp.int32).astype(jnp.uint8)
    y = jax.random.randint(ky, (n_clients, n_local), 0, 2, dtype=jnp.int32)
    n = jnp.full((n_clients,), n_local, jnp.int32)
    fed = FederatedData(X_train=X, y_train=y, n_train=n,
                        X_test=X[:, :4], y_test=y[:, :4],
                        n_test=jnp.full((n_clients,), 4, jnp.int32))
    log = ExperimentLogger("/tmp/nidt_bench", "synthetic", "precision_cell",
                           console=False)

    LEGS = (("fp32", "fp32", False),
            ("bf16_mixed", "bf16_mixed", False),
            ("bf16_mixed_fused", "bf16_mixed", True),
            ("fp32_fused", "fp32", True))

    legs: dict[str, dict] = {}
    end_params: dict[str, object] = {}
    end_loss: dict[str, float] = {}
    for leg_name, precision, fused in LEGS:
        optim = OptimConfig(lr=1e-3, batch_size=batch, epochs=1,
                            precision=precision, fused_update=fused)
        cfg = ExperimentConfig(
            model=model_name, num_classes=1, algorithm="fedavg",
            data=DataConfig(dataset="synthetic"), optim=optim,
            fed=FedConfig(client_num_in_total=n_clients, comm_round=1,
                          frequency_of_the_test=10 ** 9),
            log_dir="/tmp/nidt_bench", tag=f"prec-{leg_name}")
        trainer = LocalTrainer(
            create_model(model_name, num_classes=1,
                         dtype=compute_dtype(precision), remat=remat),
            optim, num_classes=1)
        eng = create_engine("fedavg", cfg, fed, trainer, logger=log)
        eng._donate = False  # legs replay one state through the program
        gs = eng.init_global_state()
        sampled = jnp.asarray(eng.client_sampling(0))
        rngs = eng.per_client_rngs(0, np.arange(n_clients))
        lr = eng.round_lr(0)

        def run(e=eng, g=gs, s=sampled, r=rngs, lr=lr):
            out = e._round_jit(g.params, g.batch_stats, e.data, s, r, lr)
            jax.block_until_ready(out[0])
            return out

        out = run()  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = run()
            best = min(best, time.perf_counter() - t0)
        end_params[leg_name] = out[0]
        end_loss[leg_name] = float(out[2])

        # compile-time peak-memory estimate: XLA's own accounting of the
        # program's temp (activation working set) + argument bytes — the
        # number the remat policy trades against; device memory_stats()
        # replaces it with a MEASURED peak on TPU sessions
        mem = None
        try:
            compiled = eng._round_jit.lower(
                gs.params, gs.batch_stats, eng.data, sampled, rngs,
                lr).compile()
            ma = compiled.memory_analysis()
            mem = {
                "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0)),
                "output_bytes": int(
                    getattr(ma, "output_size_in_bytes", 0)),
            }
        except Exception:  # memory_analysis is backend-best-effort
            mem = None
        samples = n_clients * steps * batch
        legs[leg_name] = {
            "round_s": round(best, 4),
            "samples_per_sec": round(samples / best, 2),
            "memory_analysis": mem,
        }

    bitwise = lambda a, b: bool(all(
        np.array_equal(np.asarray(x), np.asarray(yv))
        for x, yv in zip(jax.tree.leaves(a), jax.tree.leaves(b))))
    max_delta = lambda a, b: float(max(
        float(jnp.max(jnp.abs(x - yv)))
        for x, yv in zip(jax.tree.leaves(a), jax.tree.leaves(b))))
    return {
        "metric": "precision_bench",
        "model": model_name, "shape": "x".join(map(str, shape)),
        "batch": batch, "clients": n_clients, "n_local": n_local,
        "remat": str(remat),
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "legs": legs,
        "parity": {
            "fp32_fused_bitwise_equals_fp32": bitwise(
                end_params["fp32"], end_params["fp32_fused"]),
            "bf16_fused_bitwise_equals_bf16": bitwise(
                end_params["bf16_mixed"], end_params["bf16_mixed_fused"]),
            "bf16_vs_fp32_loss_abs_delta": round(
                abs(end_loss["bf16_mixed"] - end_loss["fp32"]), 6),
            "bf16_vs_fp32_param_max_abs_delta": round(max_delta(
                end_params["fp32"], end_params["bf16_mixed"]), 8),
        },
        "timing": f"best of {reps} repeats, one shipped FedAvg round",
        "caveat": ("CPU-harness smoke numbers when run off-TPU: the "
                   "parity columns and the memory_analysis estimates are "
                   "the stable claims; the fp32-vs-bf16 step ratio, the "
                   "fused kernel's HBM win, and the measured peak-HBM "
                   "are TPU-session measurements "
                   "(scripts/run_precision_bench.sh)"),
    }


def round_program_cell() -> dict:
    """Round-program builder bench cell (ISSUE 11): per-engine dispatch
    counts and per-round wall, fused (K=4 windows through
    engines/program.py) vs the K=1 per-round loop — including the
    engines the builder put on the fused path for the FIRST time (ditto,
    dpsgd, subavg) and a fallback reference (fedfomo: per-dispatch count
    unchanged, the logged + counted reason fires). The dispatch counts
    are exact (program.dispatches / program.built); on this CPU harness
    the WALL delta is dominated by host Python + dispatch overhead — the
    per-dispatch latency a TPU tunnel multiplies (PROFILE.md round 2) —
    so treat counts and the one-compiled-program-per-window pin as the
    stable claims and the wall ratio as harness-local.

    Env: BENCH_ROUND_PROGRAM=1 arms this cell (main() prints ONLY it);
    BENCH_RP_ROUNDS (default 8), BENCH_RP_ENGINES, BENCH_BATCH /
    BENCH_LOCAL / BENCH_SHAPE / BENCH_MODEL size it."""
    import time

    import jax

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.data.synthetic import (
        generate_synthetic_abcd,
    )
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    batch = int(os.environ.get("BENCH_BATCH", 8))
    n_local = int(os.environ.get("BENCH_LOCAL", 16))
    rounds = int(os.environ.get("BENCH_RP_ROUNDS", 8))
    shape = tuple(int(s) for s in
                  os.environ.get("BENCH_SHAPE", "12,14,12").split(","))
    model_name = os.environ.get("BENCH_MODEL", "3dcnn_tiny")
    names = os.environ.get(
        "BENCH_RP_ENGINES", "fedavg,ditto,dpsgd,subavg,fedfomo").split(",")

    cohort = generate_synthetic_abcd(
        num_subjects=4 * n_local, shape=shape, num_sites=4, seed=0)

    def run(algorithm: str, K: int):
        cfg = ExperimentConfig(
            model=model_name, num_classes=1, algorithm=algorithm,
            data=DataConfig(dataset="synthetic", partition_method="site",
                            val_fraction=0.25 if algorithm == "fedfomo"
                            else 0.0),
            optim=OptimConfig(lr=1e-3, batch_size=batch, epochs=1),
            fed=FedConfig(client_num_in_total=4, comm_round=rounds,
                          frequency_of_the_test=10 ** 9,
                          rounds_per_dispatch=K),
            log_dir="/tmp/nidt_bench", tag=f"rp-{algorithm}-{K}")
        mesh = make_mesh()
        trainer = LocalTrainer(create_model(model_name, num_classes=1),
                               cfg.optim, num_classes=1)
        log = ExperimentLogger("/tmp/nidt_bench", "synthetic",
                               cfg.identity(), console=False)
        fed, _ = federate_cohort(
            cohort, partition_method="site", mesh=mesh,
            val_fraction=cfg.data.val_fraction)
        eng = create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                            logger=log)
        t0 = time.perf_counter()
        eng.train()
        wall = time.perf_counter() - t0
        prog = eng.program
        return {
            "wall_s": round(wall, 3),
            "wall_per_round_ms": round(1e3 * wall / rounds, 2),
            # engines without declared stages (fedfomo here) drive their
            # own per-round jits — the builder counters don't see them,
            # but their dispatch count IS one-per-round by construction
            "dispatches": (prog.dispatches if prog.stages is not None
                           else rounds),
            "programs_built": (prog.built if prog.stages is not None
                               else None),
            "fused": eng.fused_fallback_reason() is None,
            "fallback_reason": eng.fused_fallback_key(),
        }

    engines = {}
    for algorithm in names:
        k1 = run(algorithm, 1)
        k4 = run(algorithm, 4)
        engines[algorithm] = {
            "k1": k1, "k4": k4,
            "dispatch_reduction": (
                round(k1["dispatches"] / k4["dispatches"], 2)
                if k4["dispatches"] else None),
            "wall_ratio_k1_over_k4": round(
                k1["wall_s"] / max(k4["wall_s"], 1e-9), 3),
        }
    return {
        "metric": "round_program",
        "model": model_name, "shape": "x".join(map(str, shape)),
        "batch": batch, "n_local": n_local, "rounds": rounds,
        "device_kind": getattr(jax.devices()[0], "device_kind",
                               "unknown"),
        "engines": engines,
        "notes": ("dispatches counts compiled-program invocations "
                  "(engines/program.py RoundProgram.dispatches; train "
                  "rounds only — eval/fine-tune jits are separate). "
                  "K=4 windows collapse ~rounds dispatches toward "
                  "rounds/4 + boundary singles for every engine whose "
                  "stages are declared; fedfomo stays per-round with "
                  "the counted fallback reason. CPU-harness wall "
                  "numbers INCLUDE compile (the K=4 leg compiles one "
                  "program per distinct window length, so it reads "
                  "SLOWER here); the dispatch counts are the stable "
                  "claim — the amortized wall win is per-dispatch "
                  "latency x dispatches saved (TPU tunnel, PROFILE.md "
                  "round 2)."),
    }


def main() -> None:
    if os.environ.get("BENCH_ROUND_PROGRAM", "0") == "1":
        # standalone cell (ISSUE 11): one JSON line, no flagship phases
        print(json.dumps(round_program_cell()))
        return
    if os.environ.get("BENCH_PRECISION", "0") == "1":
        # standalone cell (ISSUE 10): one JSON line, no flagship phases
        print(json.dumps(precision_cell()))
        return
    if os.environ.get("BENCH_OBS_OVERHEAD", "0") == "1":
        # standalone cell (ISSUE 9): one JSON line, no flagship phases
        print(json.dumps(obs_overhead_cell()))
        return
    cohort_devices = int(os.environ.get("BENCH_COHORT_DEVICES", "0"))
    if cohort_devices > 1:
        # standalone cell: provisions (optionally virtual) devices before
        # any backend touch, prints ONE JSON line, skips the flagship
        # phases (scripts/run_cohort_bench.sh -> bench_matrix/)
        print(json.dumps(cohort_sharding_cell(cohort_devices)))
        return

    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig, SparsityConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import FederatedData
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.ops import flops as flops_ops
    from neuroimagedisttraining_tpu.ops.topk import kth_largest
    from neuroimagedisttraining_tpu.utils.compile_cache import (
        enable_compile_cache,
    )
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    # NIDT_COMPILE_CACHE: reuse compiled round programs across bench
    # invocations (the ~30 s 3D-CNN compile is paid once per machine);
    # opt-in for the bench — warmup already excludes compile from the
    # timed region, so the cache only speeds startup
    enable_compile_cache(None, default="")

    batch = int(os.environ.get("BENCH_BATCH", 128))
    n_clients = int(os.environ.get("BENCH_CLIENTS", 1))
    n_rounds = int(os.environ.get("BENCH_ROUNDS", 3))
    n_local = int(os.environ.get("BENCH_LOCAL", 512))
    # BENCH_SHAPE="12,14,12" shrinks volumes for CPU smoke runs of the
    # bench harness itself; real numbers use the default ABCD shape
    shape = tuple(int(s) for s in
                  os.environ.get("BENCH_SHAPE", "121,145,121").split(","))
    epochs = 1
    steps = -(-n_local // batch)  # ceil: local steps per client per epoch

    cfg = ExperimentConfig(
        model="3DCNN", num_classes=1, algorithm="fedavg",
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(lr=1e-3, batch_size=batch, epochs=epochs),
        fed=FedConfig(client_num_in_total=n_clients, comm_round=n_rounds,
                      frequency_of_the_test=10**9),
        sparsity=SparsityConfig(dense_ratio=0.5, itersnip_iterations=1),
        log_dir="/tmp/nidt_bench")

    # device-resident synthetic federation at real ABCD shapes
    kx, ky = jax.random.split(jax.random.key(2))
    X = jax.random.randint(kx, (n_clients, n_local) + shape, 0, 255,
                           dtype=jnp.int32).astype(jnp.uint8)
    y = jax.random.randint(ky, (n_clients, n_local), 0, 2, dtype=jnp.int32)
    n = jnp.full((n_clients,), n_local, jnp.int32)
    fed = FederatedData(X_train=X, y_train=y, n_train=n,
                        X_test=X[:, :8], y_test=y[:, :8],
                        n_test=jnp.full((n_clients,), 8, jnp.int32))

    remat_env = os.environ.get("BENCH_REMAT", "0")
    remat: bool | str = {"0": False, "1": True}.get(remat_env, remat_env)
    # BENCH_DTYPE: the flagship cell's historical default is bf16 compute
    # (the TPU-native posture since round 1); fp32 makes the cell the
    # precision bench's control leg. Recorded in the payload alongside
    # remat/fused_update so artifacts from different precision configs
    # are no longer indistinguishable (ISSUE 10 satellite).
    bench_dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    fused_env = os.environ.get("BENCH_FUSED", "0") == "1"
    if fused_env:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, optim=_dc.replace(cfg.optim,
                                                 fused_update=True))
    _dtypes = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}
    if bench_dtype not in _dtypes:
        raise SystemExit(f"BENCH_DTYPE={bench_dtype!r}: choose one of "
                         f"{sorted(_dtypes)}")
    model = create_model(os.environ.get("BENCH_MODEL", "3DCNN"),
                         num_classes=1, dtype=_dtypes[bench_dtype],
                         remat=remat)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger("/tmp/nidt_bench", "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("fedavg", cfg, fed, trainer, logger=log)

    gs = engine.init_global_state()
    params, bstats = gs.params, gs.batch_stats
    sampled = jnp.asarray(engine.client_sampling(0))

    def one_round(params, bstats, r):
        rngs = engine.per_client_rngs(r, np.arange(n_clients))
        return engine._round_jit(params, bstats, fed, sampled, rngs,
                                 engine.round_lr(r))

    # compile + warmup (value sync: block_until_ready proved unreliable
    # through the remote-TPU tunnel — see PROFILE.md finding 3)
    params, bstats, loss, _ = one_round(params, bstats, 0)
    float(loss)

    # best-of-N timed repeats: the harness TPU is time-shared and the
    # same binary has measured 32 vs 237 samples/s in different windows
    # (PROFILE.md round 2); the max over repeats is the least-contended
    # estimate of the program's own speed
    reps = int(os.environ.get("BENCH_REPS", 3))
    samples = n_rounds * n_clients * epochs * steps * batch
    sps = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        for r in range(n_rounds):
            params, bstats, loss, _ = one_round(params, bstats, r + 1)
        # the final loss depends on the final params chain => full sync
        float(loss)
        sps = max(sps, samples / (time.perf_counter() - t0))

    # analytic cost + MFU
    sample_in = trainer._prep(jnp.zeros((1,) + shape, jnp.float32))
    flops_per_sample = flops_ops.count_training_flops_per_sample(
        model, params, sample_in, batch_stats=bstats)
    sustained = sps * flops_per_sample
    peak = _chip_peak_tflops(jax.devices()[0])
    mfu = (sustained / (peak * 1e12)) if peak else None

    # ---- fused multi-round dispatch cell (ISSUE 4) ----
    # K single-round dispatches (the shipped K=1 loop) vs ONE K-round
    # lax.scan program (--rounds_per_dispatch K), same host-precomputed
    # sampling/rng/lr per round — the bitwise-equality of the two is
    # pinned in tests/test_dispatch.py; this cell measures the dispatch
    # amortization. Donation is live on both paths, so every timed rep
    # consumes fresh copies of the starting state (the copy is µs against
    # a multi-second round).
    K_disp = int(os.environ.get("BENCH_DISPATCH_K", 4))
    dispatch_cell = None
    if K_disp > 1:
        copy_tree = lambda t: jax.tree.map(jnp.copy, t)
        samp_list = [engine.client_sampling(r) for r in range(K_disp)]
        rngs_list = [engine.per_client_rngs(r, s)
                     for r, s in enumerate(samp_list)]
        lrs_list = [engine.round_lr(r) for r in range(K_disp)]
        k_samples = K_disp * n_clients * epochs * steps * batch

        def seq_chain(p, b):
            for r in range(K_disp):
                p, b, l, _ = engine._round_jit(
                    p, b, fed, jnp.asarray(samp_list[r]), rngs_list[r],
                    lrs_list[r])
            return float(l)

        seq_chain(copy_tree(params), copy_tree(bstats))  # warm
        seq_best = float("inf")
        for _ in range(reps):
            p, b = copy_tree(params), copy_tree(bstats)
            t0 = time.perf_counter()
            seq_chain(p, b)
            seq_best = min(seq_best, time.perf_counter() - t0)

        fused = engine._fused_round_jit(K_disp)
        samp_k = jnp.asarray(np.stack(samp_list))
        rngs_k = jnp.stack(rngs_list)
        lrs_k = jnp.asarray(lrs_list, jnp.float32)

        def fused_chain(p, b):
            p, b, losses, _ = fused(p, b, fed, samp_k, rngs_k, lrs_k)
            return float(losses[-1])

        fused_chain(copy_tree(params), copy_tree(bstats))  # compile+warm
        fused_best = float("inf")
        for _ in range(reps):
            p, b = copy_tree(params), copy_tree(bstats)
            t0 = time.perf_counter()
            fused_chain(p, b)
            fused_best = min(fused_best, time.perf_counter() - t0)
        dispatch_cell = {
            "k": K_disp,
            "sequential_samples_per_sec": round(k_samples / seq_best, 2),
            "fused_samples_per_sec": round(k_samples / fused_best, 2),
            "speedup_x": round(seq_best / fused_best, 3),
        }

    # ---- phase 2: SalientGrads mask pipeline + Pallas/XLA agreement ----
    # (phase-2/3 engines replay the SAME {params, bstats, per-client}
    # buffers through their round programs across timed repeats, so
    # donation is disabled on them — it affects memory residency, not
    # the round math being timed; the donated path is what the phase-1
    # loop above and the dispatch cell measure)
    sg = create_engine("salientgrads", cfg, fed, trainer, logger=log)
    sg._donate = False

    def _mask_sync(masks):
        # value-sync through EVERY mask leaf (the threshold alone completes
        # before the downstream per-leaf comparisons do)
        return float(sum(jnp.sum(m) for m in jax.tree.leaves(masks)))

    masks, thr = sg.generate_global_mask(params, bstats)  # compile + warmup
    _mask_sync(masks)
    t0 = time.perf_counter()
    masks, thr = sg.generate_global_mask(params, bstats)
    _mask_sync(masks)
    mask_ms = (time.perf_counter() - t0) * 1e3

    # ---- phase 3: one-round TPU timings for the remaining engine
    # programs (VERDICT r2 next-step #4: einsum-consensus, sort-based
    # percentile prune, pair-list fomo weights had no recorded numbers).
    # Best-of-REPS wall time for ONE round at the flagship shape.
    algo_round_s: dict[str, float] = {}
    if os.environ.get("BENCH_ALGO_PHASES", "1") != "0":
        import dataclasses

        from neuroimagedisttraining_tpu.utils import pytree as pt

        def _sync(*arrs):
            return sum(float(jnp.sum(a.astype(jnp.float32))
                             if hasattr(a, "astype") else 0.0)
                       for a in arrs)

        def _bestof(fn):
            fn()  # compile + warmup
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            return best

        C = n_clients
        rngs_all = engine.per_client_rngs(1, np.arange(C))
        lr = engine.round_lr(1)

        # DisPFL: masked einsum consensus + local train + fire/regrow
        dp = create_engine("dispfl", dataclasses.replace(
            cfg, algorithm="dispfl"), fed, trainer, logger=log)
        dp._donate = False
        m_local, _ = dp.init_masks_all(params)
        dper = dp.broadcast_states(
            gs.__class__(params=params, batch_stats=bstats, opt_state=None,
                         rng=None), C)
        dpp = jax.tree.map(jnp.multiply, dper.params, m_local)
        A_dp = jnp.asarray(dp.adjacency(1, dp.active_draw(1)))

        def dispfl_round():
            out = dp._round_jit(dpp, dper.batch_stats, m_local, m_local,
                                fed, A_dp, rngs_all, lr, jnp.float32(1),
                                {})
            _sync(out[-1], jax.tree.leaves(out[0])[0])

        algo_round_s["dispfl"] = _bestof(dispfl_round)

        # D-PSGD: gossip mixing-matrix consensus + local train
        dg = create_engine("dpsgd", dataclasses.replace(
            cfg, algorithm="dpsgd"), fed, trainer, logger=log)
        dg._donate = False
        M_mix = jnp.asarray(dg.mixing_matrix(1))

        def dpsgd_round():
            out = dg._round_jit(dper.params, dper.batch_stats, fed, M_mix,
                                rngs_all, lr, {})
            _sync(out[-1], jax.tree.leaves(out[0])[0])

        algo_round_s["dpsgd"] = _bestof(dpsgd_round)

        # SubAvg: masked train + per-client full-sort percentile prune +
        # overlap-count aggregation
        sa = create_engine("subavg", dataclasses.replace(
            cfg, algorithm="subavg"), fed, trainer, logger=log)
        sa._donate = False
        from neuroimagedisttraining_tpu.ops.masks import ones_mask

        sa_masks = sa.broadcast_states(ones_mask(params), C)

        def subavg_round():
            out = sa._round_jit(params, bstats, sa_masks, fed, sampled,
                                rngs_all[: len(sampled)], lr)
            _sync(out[3], jax.tree.leaves(out[0])[0])

        algo_round_s["subavg"] = _bestof(subavg_round)

        # FedFomo: local train + pair-list val-loss/distance weights +
        # delta aggregation (needs a val split)
        fed_val = dataclasses.replace(
            fed, X_val=fed.X_test, y_val=fed.y_test, n_val=fed.n_test)
        fo = create_engine("fedfomo", dataclasses.replace(
            cfg, algorithm="fedfomo"), fed_val, trainer, logger=log)
        fo._donate = False
        A_fo = np.zeros((C, C), np.float32)
        for c in range(fo.real_clients):
            A_fo[c, np.unique(fo.benefit_choose(1, c, np.ones(C)))] = 1.0
        pc_, pn_, _np = fo.pairs_from_adjacency(A_fo)
        W0 = jnp.full((C, C), 1.0 / C, jnp.float32)
        P0 = jnp.ones((C, C), jnp.float32)

        def fedfomo_round():
            out = fo._round_jit(dper.params, dper.batch_stats, W0, P0,
                                jnp.asarray(A_fo), jnp.asarray(pc_),
                                jnp.asarray(pn_), fed_val, rngs_all, lr)
            _sync(out[-1], jax.tree.leaves(out[0])[0])

        algo_round_s["fedfomo"] = _bestof(fedfomo_round)

        # SalientGrads phase-2 MASKED round — the flagship's steady-state
        # hot loop (per-step mask multiplies on top of the FedAvg shape);
        # masks come from the phase-2 pipeline above
        rngs_s = rngs_all[: len(sampled)]

        def salientgrads_round():
            out = sg._round_jit(params, bstats, dper.params,
                                dper.batch_stats, fed, masks, sampled,
                                rngs_s, lr)
            _sync(out[-2], jax.tree.leaves(out[0])[0])

        algo_round_s["salientgrads_masked"] = _bestof(salientgrads_round)

        # FedProx: the FedAvg round + per-step proximal pull toward the
        # incoming global (engines/fedprox.py; BASELINE.json configs[3])
        fp = create_engine("fedprox", dataclasses.replace(
            cfg, algorithm="fedprox"), fed, trainer, logger=log)
        fp._donate = False

        def fedprox_round():
            out = fp._round_jit(params, bstats, fed, sampled, rngs_s, lr)
            _sync(out[-2], jax.tree.leaves(out[0])[0])

        algo_round_s["fedprox"] = _bestof(fedprox_round)

        # Ditto: dual-track round (global step + proximal personal step —
        # ~2x the FedAvg compute per sample by construction)
        dt = create_engine("ditto", dataclasses.replace(
            cfg, algorithm="ditto"), fed, trainer, logger=log)
        dt._donate = False

        def ditto_round():
            out = dt._round_jit(params, bstats, dper.params,
                                dper.batch_stats, fed, sampled, rngs_s, lr)
            _sync(out[-1], jax.tree.leaves(out[0])[0])

        algo_round_s["ditto"] = _bestof(ditto_round)

        # Local-only: vmapped per-client training, no aggregation
        lo = create_engine("local", dataclasses.replace(
            cfg, algorithm="local"), fed, trainer, logger=log)
        lo._donate = False

        def local_round():
            out = lo._round_jit(dper.params, dper.batch_stats, fed,
                                rngs_all, lr)
            _sync(out[-1], jax.tree.leaves(out[0])[0])

        algo_round_s["local"] = _bestof(local_round)

        # TurboAggregate: jitted train stage + MPC aggregation (default
        # backend "device": the quantize -> share -> slot-major sum ->
        # dequantize pipeline as jitted uint32 mod-p ops on the VPU,
        # ops/mpc_device.py; VERDICT r4 weak #3); the MPC stage is also
        # timed alone
        ta = create_engine("turboaggregate", dataclasses.replace(
            cfg, algorithm="turboaggregate"), fed, trainer, logger=log)
        ta._donate = False

        def turbo_round():
            out = ta._round_jit(params, bstats, fed, sampled, rngs_s, lr)
            _sync(out[-2], jax.tree.leaves(out[0])[0])

        algo_round_s["turboaggregate"] = _bestof(turbo_round)
        weighted, _, _, _ = ta._train_only_jit(params, bstats, fed, sampled,
                                               rngs_s, lr)
        _sync(jax.tree.leaves(weighted)[0])
        jax.block_until_ready(ta.secure_aggregate(weighted, 0))  # warm
        t0 = time.perf_counter()
        jax.block_until_ready(ta.secure_aggregate(weighted, 1))
        turbo_mpc_ms = (time.perf_counter() - t0) * 1e3
    else:
        turbo_mpc_ms = None

    # ---- wire codec cell (ISSUE 3): bytes/round + encode/decode ms ----
    # Encodes a REAL client upload — one more shipped-engine round from
    # the current params (BENCH_CLIENTS=1 => the round output IS the
    # client's trained model) — as the cross-silo wire would ship it:
    # fedavg delta+quant (dense engine) and the flagship's masked
    # sparse+quant against the phase-2 SNIP mask (mask handoff, no
    # bitmap). Reports true frame bytes vs the dense msgpack wire and
    # host encode/decode wall time; overhead_frac relates encode+decode
    # to the measured round wall time (acceptance: < 10%).
    from neuroimagedisttraining_tpu.codec import (
        decode_update, encode_update, frame_nbytes, parse_wire_spec,
    )

    ref_host = {"params": jax.tree.map(np.asarray, params),
                "batch_stats": jax.tree.map(np.asarray, bstats)}
    p2, b2, loss2, _ = one_round(params, bstats, n_rounds + 1)
    float(loss2)
    upd_host = {"params": jax.tree.map(np.asarray, p2),
                "batch_stats": jax.tree.map(np.asarray, b2)}
    masks_host = {"params": jax.tree.map(np.asarray, masks),
                  "batch_stats": jax.tree.map(
                      lambda x: np.ones_like(np.asarray(x)),
                      bstats)}
    dense_bytes = frame_nbytes(upd_host)
    round_s = samples / (n_rounds * max(sps, 1e-9))  # one round's wall time
    codec_cell = {"dense_bytes": dense_bytes}
    for key, spec_str, m in (
            ("fedavg_delta_quant", "delta+quant", None),
            ("salientgrads_mask_sparse_quant", "delta+sparse+quant",
             masks_host)):
        spec = parse_wire_spec(spec_str)
        t0 = time.perf_counter()
        frame, _ = encode_update(spec, upd_host, reference=ref_host,
                                 masks=m, mask_on_wire=False)
        enc_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        decode_update(frame, like=upd_host, reference=ref_host, masks=m)
        dec_s = time.perf_counter() - t0
        nbytes = frame_nbytes(frame)
        codec_cell[key] = {
            "bytes": nbytes,
            "reduction_x": round(dense_bytes / nbytes, 2),
            "encode_ms": round(enc_s * 1e3, 1),
            "decode_ms": round(dec_s * 1e3, 1),
            "overhead_frac_of_round": round((enc_s + dec_s) / round_s, 4),
        }

    scores = jax.random.uniform(jax.random.key(5), (1 << 22,))
    on_tpu = jax.default_backend() == "tpu"
    thr_pallas = kth_largest(scores, 1 << 21, use_pallas=on_tpu)
    thr_xla = kth_largest(scores, 1 << 21, use_pallas=False)
    pallas_ok = bool(jnp.equal(thr_pallas, thr_xla))
    if on_tpu:
        t0 = time.perf_counter()
        float(kth_largest(scores, 1 << 21, use_pallas=True))
        topk_ms = (time.perf_counter() - t0) * 1e3
    else:
        topk_ms = None

    print(json.dumps({
        "metric": "abcd_fedavg_train_samples_per_sec",
        "value": round(sps, 2),
        "unit": f"samples/s ({os.environ.get('BENCH_MODEL', '3DCNN')} "
                f"{'x'.join(map(str, shape))}, b{batch}, "
                f"{n_clients} clients, shipped FedAvgEngine round program)",
        "vs_baseline": round(sps / V100_BASELINE_SAMPLES_PER_SEC, 3),
        "vs_baseline_range": [round(sps / V100_BASELINE_HIGH, 3),
                              round(sps / V100_BASELINE_LOW, 3)],
        "gflops_per_sample": round(flops_per_sample / 1e9, 2),
        "sustained_tflops": round(sustained / 1e12, 2),
        # precision provenance (ISSUE 10 satellite): artifacts from
        # different precision configs must be distinguishable
        "dtype": bench_dtype,
        "remat": str(remat),
        "fused_update": fused_env,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "peak_tflops_assumed": peak,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "salientgrads_mask_ms": round(mask_ms, 1),
        "rounds_per_dispatch": dispatch_cell,
        "algo_round_s": {k: round(v, 3) for k, v in algo_round_s.items()}
        or None,
        "algo_round_samples_per_sec": {
            k: round(n_clients * epochs * steps * batch / v, 1)
            for k, v in algo_round_s.items()} or None,
        "turboaggregate_mpc_ms": (round(turbo_mpc_ms, 1)
                                  if turbo_mpc_ms is not None else None),
        "wire_codec": codec_cell,
        "pallas_topk_ms_4m": round(topk_ms, 1) if topk_ms else None,
        "pallas_threshold_matches_xla": pallas_ok,
        "timing": f"best of {reps} repeats (shared-chip noise, PROFILE.md)",
    }))


if __name__ == "__main__":
    main()
