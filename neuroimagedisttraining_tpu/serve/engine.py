"""Jitted micro-batched inference engine (ISSUE 17 tentpole part 2).

Compile-once/dispatch-many (Frostig 2018, PAPERS.md) applied to the
serving path: ONE jitted batched forward program per (model, bucket)
pair, so steady-state traffic NEVER traces. Requests queue per routed
model; a single batcher thread collects up to the largest declared
bucket (or until the oldest request has waited ``max_queue_ms``), pads
to the smallest bucket that fits, dispatches once, de-pads, and
resolves the waiters.

Instrumentation rides the EXISTING compute-plane seam: every program
build goes through ``obs.compute.note_compile`` and every invocation
through ``note_dispatch`` (compile-vs-execute phases in
``nidt_dispatch_ms``), so the recompile tripwire and the
``compiles_total`` pins work unchanged for serving. Per-request stage
latencies land in ``nidt_serve_latency_ms{stage=queue|batch|dispatch|
reply}`` (the reply stage is observed by the HTTP worker) plus a
batch-occupancy gauge and a queue-depth gauge — all names declared in
obs/names.py. HOST-BOUNDARY RULE: all metrics fire on the host side of
the dispatch, never inside the jitted body.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.models import create_model, primary_logits
from neuroimagedisttraining_tpu.obs import compute as obs_compute
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as N
from neuroimagedisttraining_tpu.obs import rules as obs_rules
from neuroimagedisttraining_tpu.serve.bundle import ServeBundle

#: engine label on the shared compute-plane series
#: (``nidt_compiles_total{engine="serve"}`` etc.)
ENGINE_LABEL = "serve"

#: per-request stage latency edges (ms) — wider than the upload stage
#: buckets because a cold compile rides the first dispatch
SERVE_LATENCY_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                            50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
                            5000.0, 10000.0)

#: rule-engine boundary cadence: snapshotting the registry per dispatch
#: would dominate tiny-model serving, so health rules are evaluated
#: every N dispatches
_RULE_BOUNDARY_EVERY = 32


def serve_latency_histogram(registry: obs_metrics.MetricsRegistry | None
                            = None):
    reg = registry if registry is not None else obs_metrics.REGISTRY
    return reg.histogram(
        N.SERVE_LATENCY_MS,
        "per-request serving latency by stage: queue (enqueue→batch "
        "collect), batch (pad/stack), dispatch (compiled forward incl. "
        "device sync), reply (result→bytes on the wire; observed by "
        "serve/worker.py)",
        labelnames=("stage",), buckets=SERVE_LATENCY_BUCKETS_MS)


class _Pending:
    """One queued request: numpy input + the waiter's event."""

    __slots__ = ("x", "event", "result", "error", "t_enq")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.event = threading.Event()
        self.result: np.ndarray | None = None
        self.error: BaseException | None = None
        self.t_enq = time.perf_counter()


class ServeEngine:
    """Micro-batching inference over a loaded :class:`ServeBundle`.

    ``batch_buckets`` declares the ONLY batch shapes that may compile;
    ``max_queue_ms`` bounds how long the oldest request waits for
    batch-mates. ``precision`` "" serves the bundle's stored precision;
    "bf16"/"fp32" re-cast at load (the fp32 escape hatch)."""

    def __init__(self, bundle: ServeBundle,
                 batch_buckets: tuple[int, ...] = (1, 2, 4, 8),
                 max_queue_ms: float = 2.0, precision: str = "",
                 registry: obs_metrics.MetricsRegistry | None = None):
        buckets = sorted({int(b) for b in batch_buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(
                f"batch_buckets must be positive ints, got "
                f"{batch_buckets!r}")
        if precision not in ("", "bf16", "fp32"):
            raise ValueError(
                f"precision must be ''|bf16|fp32, got {precision!r}")
        self.bundle = bundle
        self.buckets = tuple(buckets)
        self._max_bucket = buckets[-1]
        self._max_queue_s = max(0.0, float(max_queue_ms)) / 1e3
        self.precision = precision or bundle.precision
        dtype = jnp.bfloat16 if self.precision == "bf16" else jnp.float32
        self._model = create_model(bundle.model_name,
                                   num_classes=bundle.num_classes,
                                   dtype=dtype)
        self._input_rank = getattr(self._model, "input_rank", None)
        self.input_shape = bundle.input_shape

        def load(tree):
            def leaf(x):
                x = jnp.asarray(x)
                if (self.precision != bundle.precision
                        and jnp.issubdtype(x.dtype, jnp.floating)):
                    x = x.astype(dtype)
                return x
            return jax.tree.map(leaf, tree)

        self._weights = {
            key: (load(entry["params"]), load(entry["batch_stats"]))
            for key, entry in bundle.models.items()}

        reg = registry if registry is not None else obs_metrics.REGISTRY
        self._lat = serve_latency_histogram(reg)
        self._occupancy = reg.gauge(
            N.SERVE_BATCH_OCCUPANCY,
            "real requests / bucket slots of the latest dispatch "
            "(serve/engine.py); chronically low means the declared "
            "buckets are too coarse for the offered load")
        self._depth = reg.gauge(
            N.SERVE_QUEUE_DEPTH,
            "requests queued behind the batcher after the latest "
            "collect (serve/engine.py)")

        # one jitted program per (model_key, bucket); only the batcher
        # thread touches these
        self._programs: dict[tuple[str, int], object] = {}
        self._sigs: dict[tuple[str, int], tuple] = {}
        self._recompiles = 0

        self._cv = threading.Condition()
        self._queues: dict[str, deque[_Pending]] = {
            key: deque() for key in self._weights}
        self._open = True
        self._dispatches = 0
        self._batches: dict[int, int] = {}
        self._real_total = 0
        self._slot_total = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    # ---------- the jitted forward ----------

    def _forward(self, params, bstats, x):
        """Pure batched forward — mirrors LocalTrainer._prep/_apply so
        served predictions match training-eval semantics."""
        x = x.astype(jnp.float32)  # nidt: allow[precision-upcast] -- serving ingests raw client arrays at the same uint8/f32 quantization boundary as training (core/trainer.py _prep); the model re-casts to its compute dtype
        if (self._input_rank is not None
                and x.ndim == self._input_rank - 1):
            x = x[..., None]
        variables = {"params": params}
        if jax.tree.leaves(bstats):
            variables["batch_stats"] = bstats
        out = self._model.apply(variables, x, train=False)
        return primary_logits(out)

    # ---------- request side ----------

    def submit(self, site: str | None, x) -> tuple[_Pending, str]:
        """Validate + enqueue one request; returns (pending, model_key).
        Shape validation here is the bucket-misconfiguration fence: a
        non-conforming array would otherwise mint a fresh program."""
        x = np.asarray(x, dtype=np.float32)
        if tuple(x.shape) != self.input_shape:
            raise ValueError(
                f"input shape {tuple(x.shape)} != bundle input_shape "
                f"{self.input_shape}")
        model_key = self.bundle.route(site)
        pending = _Pending(x)
        with self._cv:
            if not self._open:
                raise RuntimeError("serve engine is closed")
            self._queues[model_key].append(pending)
            self._cv.notify()
        return pending, model_key

    def predict(self, site: str | None, x, timeout: float = 30.0
                ) -> tuple[np.ndarray, str]:
        """Blocking single prediction: (logits row, routed model key)."""
        pending, model_key = self.submit(site, x)
        if not pending.event.wait(timeout):
            raise TimeoutError(
                f"no dispatch within {timeout}s (queue depth "
                f"{self.queue_depth()})")
        if pending.error is not None:
            raise pending.error
        return pending.result, model_key

    def queue_depth(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    # ---------- batcher thread ----------

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._open and not any(self._queues.values()):
                    self._cv.wait(0.05)
                if not self._open:
                    leftovers = [p for q in self._queues.values()
                                 for p in q]
                    for q in self._queues.values():
                        q.clear()
                    break
                # oldest head request picks the model; one batcher
                # serializes dispatches (one device) so per-model
                # fairness is head-age order
                model_key = min(
                    (q[0].t_enq, k)
                    for k, q in self._queues.items() if q)[1]
                queue = self._queues[model_key]
                deadline = queue[0].t_enq + self._max_queue_s
                while (self._open and len(queue) < self._max_bucket):
                    remain = deadline - time.perf_counter()
                    if remain <= 0:
                        break
                    self._cv.wait(remain)
                n = min(len(queue), self._max_bucket)
                batch = [queue.popleft() for _ in range(n)]
                depth = sum(len(q) for q in self._queues.values())
            self._depth.set(depth)
            if batch:
                try:
                    self._dispatch(model_key, batch)
                except BaseException as e:  # resolve waiters, keep serving
                    for p in batch:
                        p.error = e
                        p.event.set()
        for p in leftovers:
            p.error = RuntimeError("serve engine closed")
            p.event.set()

    def _dispatch(self, model_key: str, batch: list[_Pending]) -> None:
        t_collect = time.perf_counter()
        queue_obs = self._lat.labels(stage="queue")
        for p in batch:
            queue_obs.observe((t_collect - p.t_enq) * 1e3)
        n = len(batch)
        bucket = next(b for b in self.buckets if b >= n)
        xb = np.zeros((bucket, *self.input_shape), dtype=np.float32)
        for i, p in enumerate(batch):
            xb[i] = p.x
        t_pad = time.perf_counter()
        batch_obs = self._lat.labels(stage="batch")
        for _ in batch:
            batch_obs.observe((t_pad - t_collect) * 1e3)

        key = (model_key, bucket)
        program = f"{model_key}/b{bucket}"
        sig = (xb.shape, str(xb.dtype))
        fresh = key not in self._programs
        recompile = (not fresh) and self._sigs[key] != sig
        if fresh or recompile:
            # the tripwire: a second build of the SAME (model, bucket)
            # key means the declared-bucket fence leaked a shape
            self._programs[key] = jax.jit(self._forward)
            self._sigs[key] = sig
            if recompile:
                self._recompiles += 1
            obs_compute.note_compile(ENGINE_LABEL, program,
                                     recompile=recompile)
        phase = "compile" if (fresh or recompile) else "execute"
        params, bstats = self._weights[model_key]
        t0 = time.perf_counter()
        y = jax.block_until_ready(self._programs[key](params, bstats, xb))
        dur = time.perf_counter() - t0
        obs_compute.note_dispatch(ENGINE_LABEL, program, dur, rounds=1,
                                  phase=phase)
        self._occupancy.set(n / bucket)
        self._dispatches += 1
        self._batches[bucket] = self._batches.get(bucket, 0) + 1
        self._real_total += n
        self._slot_total += bucket

        y_np = np.asarray(jnp.asarray(y, jnp.float32))
        t_done = time.perf_counter()
        dispatch_obs = self._lat.labels(stage="dispatch")
        for i, p in enumerate(batch):
            p.result = y_np[i]
            dispatch_obs.observe((t_done - t_pad) * 1e3)
            p.event.set()
        if self._dispatches % _RULE_BOUNDARY_EVERY == 0:
            obs_rules.observe_boundary(self._dispatches)

    # ---------- lifecycle / introspection ----------

    def stats(self) -> dict:
        """Bookkeeping the worker ships home in its bye message; the
        bench compile pin reads ``compiled``/``recompiles``."""
        return {
            "dispatches": self._dispatches,
            "batches": {str(b): c for b, c in sorted(self._batches.items())},
            "occupancy_mean": (self._real_total / self._slot_total
                               if self._slot_total else 0.0),
            "requests_dispatched": self._real_total,
            "compiled": sorted(f"{mk}/b{b}" for mk, b in self._programs),
            "compiles": len(self._programs),
            "recompiles": self._recompiles,
        }

    def close(self) -> None:
        with self._cv:
            self._open = False
            self._cv.notify_all()
        self._thread.join(timeout=10.0)
