"""Operator CLI for the serving plane (ISSUE 17 tentpole part 4).

``python -m neuroimagedisttraining_tpu.serve --bundle DIR --port N
--serve_workers K --batch_buckets 1,2,4,8 --max_queue_ms 2
--precision bf16`` serves a built bundle; ``--from_checkpoint DIR``
builds the bundle first (``--build_only`` stops there — the
checkpoint→bundle conversion step regional distribution scripts call).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def _parse_buckets(text: str) -> tuple[int, ...]:
    try:
        buckets = tuple(int(b) for b in text.split(",") if b.strip())
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"batch_buckets must be comma-separated ints, got {text!r}")
    if not buckets or min(buckets) < 1:
        raise argparse.ArgumentTypeError(
            f"batch_buckets must be positive, got {text!r}")
    return buckets


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m neuroimagedisttraining_tpu.serve",
        description="serve a deployment bundle over SO_REUSEPORT HTTP "
                    "workers with jitted micro-batched inference")
    p.add_argument("--bundle", required=True,
                   help="bundle directory (manifest.json + "
                        "weights.msgpack); created when "
                        "--from_checkpoint is given")
    p.add_argument("--port", type=int, default=0,
                   help="shared SO_REUSEPORT port (0 = ephemeral, "
                        "printed at startup)")
    p.add_argument("--serve_workers", type=int, default=2,
                   help="HTTP worker processes on the shared port")
    p.add_argument("--batch_buckets", type=_parse_buckets,
                   default=(1, 2, 4, 8),
                   help="declared batch sizes; ONE compiled program "
                        "per (model, bucket) — e.g. 1,2,4,8")
    p.add_argument("--max_queue_ms", type=float, default=2.0,
                   help="max wait of the oldest queued request for "
                        "batch-mates before dispatch")
    p.add_argument("--precision", default="",
                   choices=("", "bf16", "fp32"),
                   help="serving precision override ('' = as stored; "
                        "fp32 is the full-precision escape hatch)")
    p.add_argument("--metrics_port", type=int, default=0,
                   help="root port for the MERGED /metrics + /healthz "
                        "(0 = off)")
    p.add_argument("--run_seconds", type=float, default=0.0,
                   help="serve for N seconds then exit cleanly "
                        "(0 = until SIGINT/SIGTERM)")
    p.add_argument("--trace_out", default="",
                   help="merged chrome-trace path (workers write "
                        ".wN-suffixed secondaries)")
    p.add_argument("--flight_out", default="",
                   help="merged flight-recorder dump path")
    # ---- bundle building ----
    p.add_argument("--from_checkpoint", default="",
                   help="build --bundle from this training checkpoint "
                        "dir before serving")
    p.add_argument("--model", default="",
                   help="model name for --from_checkpoint (e.g. "
                        "3dcnn_tiny, alexnet3d)")
    p.add_argument("--num_classes", type=int, default=1)
    p.add_argument("--input_shape", default="",
                   help="comma-separated per-request input shape for "
                        "--from_checkpoint, e.g. 12,14,12")
    p.add_argument("--source_round", type=int, default=-1,
                   help="checkpoint round to bundle (-1 = latest)")
    p.add_argument("--bundle_precision", default="bf16",
                   choices=("bf16", "fp32"),
                   help="stored weight precision for --from_checkpoint")
    p.add_argument("--build_only", action="store_true",
                   help="build the bundle and exit without serving")
    return p


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.build_only and not args.from_checkpoint:
        parser.error("--build_only requires --from_checkpoint")
    if args.from_checkpoint:
        if not args.model or not args.input_shape:
            parser.error("--from_checkpoint requires --model and "
                         "--input_shape")
        from neuroimagedisttraining_tpu.serve.bundle import build_bundle
        manifest = build_bundle(
            args.from_checkpoint, args.bundle, model=args.model,
            num_classes=args.num_classes,
            input_shape=tuple(int(d) for d in
                              args.input_shape.split(",") if d),
            precision=args.bundle_precision,
            round_idx=None if args.source_round < 0
            else args.source_round)
        print(json.dumps({"bundle": args.bundle,
                          "flavor": manifest["flavor"],
                          "source_round": manifest["source_round"],
                          "sites": len(manifest["sites"]),
                          "precision": manifest["precision"],
                          "sparse_nnz": manifest["sparse_nnz"]},
                         indent=1, sort_keys=True))
        if args.build_only:
            return 0

    from neuroimagedisttraining_tpu.obs.http import start_metrics_server
    from neuroimagedisttraining_tpu.serve.server import ShardedServeServer

    server = ShardedServeServer(
        args.bundle, port=args.port, serve_workers=args.serve_workers,
        batch_buckets=args.batch_buckets,
        max_queue_ms=args.max_queue_ms, precision=args.precision,
        trace_out=args.trace_out, flight_out=args.flight_out)
    msrv = start_metrics_server(args.metrics_port,
                                registry=server.metrics_view(),
                                health_probe=server.health)
    print(json.dumps({"port": server.port,
                      "workers": server.serve_workers,
                      "metrics_port": msrv.port if msrv else 0,
                      "model": server.manifest["model"],
                      "model_version": server.manifest["source_round"]},
                     sort_keys=True), flush=True)
    done = threading.Event()

    def _sig(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    done.wait(args.run_seconds if args.run_seconds > 0 else None)
    audit = server.stop()
    if msrv is not None:
        msrv.close()
    print(json.dumps({"audit": audit}, indent=1, sort_keys=True))
    return 0 if audit["reconciled"] else 1


if __name__ == "__main__":
    sys.exit(main())
