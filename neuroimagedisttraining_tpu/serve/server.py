"""Root of the sharded serving plane (ISSUE 17 tentpole part 3).

Spawns ``serve_workers`` HTTP worker processes on ONE ``SO_REUSEPORT``
port (the exact spawn/ready/clock handshake of
``asyncfl.ingest.ShardedIngestServer``), accumulates their batched
admission-verdict events into per-worker root counters, fans their
telemetry into one merged ``/metrics`` exposition + trace/flight
artifacts via ``obs/fanin.py``, and audits at shutdown: each live
worker's bye totals must equal the root's accumulated verdict batches —
a request can land ONLY in a verdict or in a client-observed transport
error, never silently vanish. A SIGKILLed worker is marked dead, its
unflushed tail is bounded by the flush cadence and reported as
``lost_with_worker`` rather than pretending reconciliation.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import socket
import threading
import time

from neuroimagedisttraining_tpu.obs import fanin as obs_fanin
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.serve.bundle import read_manifest
from neuroimagedisttraining_tpu.serve.worker import (
    MAX_BODY_BYTES,
    VERDICTS,
    _serve_worker_main,
)

log = logging.getLogger("neuroimagedisttraining_tpu.serve")


class ShardedServeServer:
    """N SO_REUSEPORT HTTP workers + the auditing, fanning-in root."""

    def __init__(self, bundle_path: str, *, port: int = 0,
                 serve_workers: int = 2,
                 batch_buckets: tuple[int, ...] = (1, 2, 4, 8),
                 max_queue_ms: float = 2.0, precision: str = "",
                 max_body: int = MAX_BODY_BYTES,
                 spawn_timeout: float = 180.0, trace_out: str = "",
                 flight_out: str = ""):
        if serve_workers < 1:
            raise ValueError(
                f"serve_workers must be >= 1, got {serve_workers}")
        # fail fast on a broken bundle at the ROOT (schema/version
        # checks); each worker then does the full sha256/digest
        # verification on its own load
        self.manifest = read_manifest(bundle_path)
        self.serve_workers = int(serve_workers)
        self.trace_out = trace_out
        self.flight_out = flight_out
        self._lock = threading.Lock()
        self._verdicts: dict[str, int] = {}
        self._obs_workers_live = obs_metrics.gauge(
            obs_names.SERVE_WORKERS_LIVE,
            "serve worker processes alive")
        self._obs_worker_requests = obs_metrics.counter(
            obs_names.SERVE_WORKER_REQUESTS,
            "per-worker admission verdict events at the serve root",
            labelnames=("worker", "outcome"))
        self.fanin = obs_fanin.TelemetryFanIn()
        self._obs_dumped = False

        # reserve the shared port: bound (never listening) with
        # SO_REUSEPORT so the workers can bind+listen the same number;
        # a non-listening TCP socket receives no connections
        self._port_holder = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
        self._port_holder.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEPORT, 1)
        self._port_holder.bind(("0.0.0.0", int(port or 0)))
        self.port = self._port_holder.getsockname()[1]

        ctx = mp.get_context("spawn")
        wcfg = {
            "bundle": os.path.abspath(bundle_path),
            "port": self.port,
            "batch_buckets": tuple(int(b) for b in batch_buckets),
            "max_queue_ms": float(max_queue_ms),
            "precision": precision,
            "max_body": int(max_body),
            "obs": {"trace": bool(trace_out) or obs_trace.TRACER.armed,
                    "trace_path": trace_out,
                    "flight_path": flight_out,
                    "flight_capacity": obs_flight.FLIGHT.capacity},
        }
        self._workers: dict[int, dict] = {}
        for wid in range(self.serve_workers):
            parent, child = ctx.Pipe(duplex=True)
            proc = ctx.Process(target=_serve_worker_main,
                               args=(wid, child, wcfg), daemon=True,
                               name=f"nidt-serve-w{wid}")
            proc.start()
            child.close()
            self._workers[wid] = {
                "proc": proc, "conn": parent, "alive": True,
                "verdicts": {}, "bye": None,
            }
        deadline = time.monotonic() + spawn_timeout
        ready: set[int] = set()
        while len(ready) < self.serve_workers:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._kill_workers()
                raise RuntimeError(
                    f"serve workers not ready within {spawn_timeout}s "
                    f"({sorted(ready)} of {self.serve_workers})")
            for wid, w in self._workers.items():
                if wid in ready:
                    continue
                try:
                    if w["conn"].poll(0.05):
                        msg = w["conn"].recv()
                        if msg[0] == "ready":
                            ready.add(wid)
                        else:
                            self._handle_event(wid, msg)
                except (EOFError, OSError) as e:
                    # a worker dead during spawn (bundle drift, bind
                    # failure, import error) is a NAMED startup
                    # failure, with no orphan siblings left running
                    self._kill_workers()
                    raise RuntimeError(
                        f"serve worker {wid} died during startup "
                        f"({type(e).__name__}); see its log output"
                    ) from e
        self._obs_workers_live.set(self.serve_workers)
        # spawn-time clock handshake (obs/fanin.py): collected HERE so
        # a reply aging in the pipe never inflates the offset estimate
        for wid, w in self._workers.items():
            self.fanin.register_worker(wid)
            try:
                w["conn"].send(("clock", time.perf_counter_ns()))  # nidt: allow[lock-send] -- ctor is single-threaded: the drain thread does not exist yet
            except (BrokenPipeError, OSError):
                pass
        pending = set(self._workers)
        clock_deadline = time.monotonic() + 2.0
        while pending and time.monotonic() < clock_deadline:
            for wid in sorted(pending):
                w = self._workers[wid]
                try:
                    while w["conn"].poll(0.02):
                        ev = w["conn"].recv()
                        self._handle_event(wid, ev)
                        if ev[0] == "clock_reply":
                            pending.discard(wid)
                            break
                except (EOFError, OSError):
                    pending.discard(wid)  # death surfaces in the drain
        if pending:
            log.warning("serve root: no clock reply from workers %s "
                        "within 2s; their merged-trace timelines fall "
                        "back to offset 0", sorted(pending))
        self._stop = threading.Event()
        self._drain_thread = threading.Thread(target=self._drain_loop,
                                              daemon=True,
                                              name="serve-root-drain")
        self._drain_thread.start()
        log.info("serve root: %d workers ready on port %d (model %s "
                 "round %d, %d site models)", self.serve_workers,
                 self.port, self.manifest["model"],
                 self.manifest["source_round"],
                 len(self.manifest["sites"]))

    # ---- pipe events ----

    def _handle_event(self, wid: int, ev: tuple) -> None:
        kind = ev[0]
        w = self._workers[wid]
        if kind == "vb":
            counts = ev[2]
            with self._lock:
                for outcome, n in counts.items():
                    w["verdicts"][outcome] = \
                        w["verdicts"].get(outcome, 0) + n
                    self._verdicts[outcome] = \
                        self._verdicts.get(outcome, 0) + n
            for outcome, n in counts.items():
                self._obs_worker_requests.labels(
                    worker=str(wid), outcome=outcome).inc(n)
        elif kind == "obs":
            self.fanin.ingest(wid, ev[2])
        elif kind == "clock_reply":
            self.fanin.note_clock(wid, ev[2], ev[3],
                                  time.perf_counter_ns())
        elif kind == "bye":
            with self._lock:
                w["bye"] = ev[2]

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            busy = False
            for wid, w in self._workers.items():
                if not w["alive"]:
                    continue
                try:
                    while w["conn"].poll(0):
                        self._handle_event(wid, w["conn"].recv())
                        busy = True
                except (EOFError, OSError):
                    self._mark_dead(wid)
            if not busy:
                time.sleep(0.02)

    def _mark_dead(self, wid: int) -> None:
        w = self._workers[wid]
        if not w["alive"]:
            return
        w["alive"] = False
        self.fanin.mark_dead(wid)
        self._obs_workers_live.set(len(self.live_workers()))
        obs_flight.record("serve_worker_dead", worker=wid)
        log.warning("serve root: worker %d died (pipe closed); "
                    "%d listeners remain on port %d", wid,
                    len(self.live_workers()), self.port)

    # ---- introspection (loadgen / tests) ----

    @property
    def worker_pids(self) -> list[int]:
        return [w["proc"].pid for w in self._workers.values()]

    def live_workers(self) -> list[int]:
        return [wid for wid, w in self._workers.items() if w["alive"]]

    def total(self, outcome: str) -> int:
        with self._lock:
            return self._verdicts.get(outcome, 0)

    def metrics_view(self):
        """The MERGED registry view for the root ``--metrics_port``
        exposition: root samples + worker samples (``worker`` label) +
        snapshot-staleness gauges (obs/fanin.py)."""
        return self.fanin.metrics_view()

    def health(self) -> dict:
        """Root ``/healthz`` probe payload (obs.http.MetricsServer)."""
        live = self.live_workers()
        with self._lock:
            verdicts = dict(self._verdicts)
        return {
            "ok": bool(live),
            "serve": {
                "model": self.manifest["model"],
                "model_version": self.manifest["source_round"],
                "bundle_sha256": self.manifest["weights_sha256"],
                "sites": len(self.manifest["sites"]),
                "workers_live": len(live),
                "workers": self.serve_workers,
                "port": self.port,
                "verdicts": verdicts,
            },
            "fanin": self.fanin.summary(),
        }

    def audit(self) -> dict:
        """Shutdown reconciliation: per live worker, the bye totals
        must EQUAL the root's accumulated verdict batches (the pipe
        lost nothing); a dead worker's tail since its last flush is
        unknowable and reported, not hidden."""
        with self._lock:
            per_worker = {}
            reconciled = True
            lost_with_worker = 0
            for wid, w in self._workers.items():
                bye = w["bye"]
                root_counts = {k: v for k, v in w["verdicts"].items()
                               if v}
                if bye is not None:
                    bye_counts = {k: v for k, v in bye.items()
                                  if k != "engine" and v}
                    ok = bye_counts == root_counts
                    reconciled = reconciled and ok
                else:
                    bye_counts = None
                    ok = False
                    if w["alive"]:
                        reconciled = False
                    else:
                        # SIGKILLed worker: its post-flush tail is
                        # gone; root counts stand as the lower bound
                        lost_with_worker += 1
                per_worker[str(wid)] = {
                    "alive": w["alive"], "root": root_counts,
                    "bye": bye_counts, "reconciled": ok,
                    "engine": (bye.get("engine")
                               if bye is not None else None),
                }
            totals = dict(self._verdicts)
        received = sum(totals.get(v, 0) for v in VERDICTS)
        return {
            "received": received,
            "served": totals.get("served", 0),
            "rejected": sum(totals.get(v, 0) for v in VERDICTS
                            if v.startswith("rejected")),
            "errors": totals.get("error", 0),
            "unknown_site": totals.get("unknown_site", 0),
            "per_worker": per_worker,
            "dead_workers": lost_with_worker,
            "reconciled": reconciled,
        }

    def dump_obs(self, reason: str = "end of run"
                 ) -> dict[str, str | None]:
        """Merged trace/flight artifacts at the bare configured paths
        (idempotent); workers write ``.wN``-suffixed secondaries."""
        with self._lock:
            if self._obs_dumped:
                return {}
            self._obs_dumped = True
        out: dict[str, str | None] = {}
        if self.trace_out:
            out["trace"] = self.fanin.dump_trace(self.trace_out)
        if self.flight_out:
            out["flight"] = self.fanin.dump_flight(self.flight_out,
                                                   reason=reason)
        return out

    # ---- shutdown ----

    def stop(self, timeout: float = 15.0) -> dict:
        """Finish the fleet: ask each live worker to flush+bye, wait
        for the byes (the drain thread ingests them), then join/kill
        and return the audit."""
        for wid, w in self._workers.items():
            if not w["alive"]:
                continue
            try:
                w["conn"].send(("finish",))
            except (BrokenPipeError, OSError):
                self._mark_dead(wid)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                waiting = [wid for wid, w in self._workers.items()
                           if w["alive"] and w["bye"] is None]
            if not waiting:
                break
            time.sleep(0.05)
        self._stop.set()
        self._drain_thread.join(timeout=5.0)
        for w in self._workers.values():
            w["proc"].join(timeout=5.0)
            if w["proc"].is_alive():
                w["proc"].kill()
                w["proc"].join(timeout=5.0)
        self._port_holder.close()
        self._obs_workers_live.set(0)
        self.dump_obs()
        return self.audit()

    def _kill_workers(self) -> None:
        for w in self._workers.values():
            if w["proc"].is_alive():
                w["proc"].kill()
            w["proc"].join(timeout=5.0)
        self._port_holder.close()
