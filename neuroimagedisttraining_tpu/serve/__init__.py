"""Serving plane (ISSUE 17): checkpoint→bundle contract, jitted
micro-batched inference, per-site model routing, and the SO_REUSEPORT
HTTP frontend.

Layering mirrors the ingest plane: ``bundle`` owns the versioned
deployment artifact, ``engine`` owns the compiled forward programs and
the micro-batcher, ``worker`` is the per-process HTTP listener, and
``server`` is the root that spawns/audits/fans-in the worker fleet.
``python -m neuroimagedisttraining_tpu.serve`` is the operator CLI.
"""

from neuroimagedisttraining_tpu.serve.bundle import (  # noqa: F401
    BundleError,
    ServeBundle,
    build_bundle,
    load_bundle,
)
from neuroimagedisttraining_tpu.serve.engine import ServeEngine  # noqa: F401


def __getattr__(name):
    # server pulls in the multiprocessing stack; keep bundle/engine
    # importable without it (worker processes import serve.bundle only)
    if name == "ShardedServeServer":
        from neuroimagedisttraining_tpu.serve.server import (
            ShardedServeServer)
        return ShardedServeServer
    raise AttributeError(name)
