"""Per-process serving worker (ISSUE 17 tentpole part 3, worker half).

Same sharding shape as the ingest plane (asyncfl/ingest.py): N spawned
worker processes all listen on ONE ``SO_REUSEPORT`` port — here with a
stdlib ``ThreadingHTTPServer`` speaking ``/predict`` instead of the
framed selector protocol — and talk to the root over one duplex pipe
with the SAME message grammar: ``("ready", wid)``, batched
``("vb", wid, counts)`` admission verdicts, rate-limited
``("obs", wid, payload)`` telemetry (obs/fanin.py), clock echoes, and a
final ``("bye", wid, stats)`` whose counts the root audits against its
accumulated verdict batches.

Admission is flight-recorded: malformed / oversized / unknown-site
verdicts land in the flight ring with the peer address, so a post-crash
dump shows WHAT the serving path was rejecting. ``/metrics`` and
``/healthz`` (model version, last-dispatch age, queue depth, rule-engine
status) are served per worker; the root fans the registries in.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from neuroimagedisttraining_tpu.obs import compute as obs_compute
from neuroimagedisttraining_tpu.obs import fanin as obs_fanin
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import rules as obs_rules
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.serve.bundle import load_bundle
from neuroimagedisttraining_tpu.serve.engine import (
    ServeEngine,
    serve_latency_histogram,
)

log = logging.getLogger("neuroimagedisttraining_tpu.serve")

#: request-body ceiling; a 256^3 f32 volume is ~64 MiB, the default
#: covers the shipped volumetric shapes with headroom
MAX_BODY_BYTES = 16 << 20

#: verdict-batch flush cadence over the root pipe (matches the ingest
#: plane's batching posture: one pipe message per batch, never per
#: request)
_VB_AGE_S = 0.05
_VB_MAX = 256

#: admission verdict names (the ``outcome`` label set)
VERDICTS = ("served", "rejected_malformed", "rejected_oversized",
            "error")


class _ReuseportHTTPServer(ThreadingHTTPServer):
    """Stdlib HTTP on a shared port: SO_REUSEPORT before bind, so the
    kernel balances accepted connections across the worker fleet."""

    daemon_threads = True
    allow_reuse_address = True

    def server_bind(self):
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class _ServeWorkerProc:
    """One worker process: the HTTP listener + engine + root pipe."""

    def __init__(self, wid: int, engine: ServeEngine, conn, port: int,
                 max_body: int = MAX_BODY_BYTES):
        self.wid = wid
        self.engine = engine
        self.conn = conn
        self.max_body = int(max_body)
        self._lock = threading.Lock()
        #: verdict batch (under _lock): counts per outcome, ONE "vb"
        #: pipe message per batch (size/age/pre-bye flush)
        self._vb_counts: dict[str, int] = {}
        self._vb_n = 0
        #: lifetime totals (under _lock) — the bye payload the root
        #: audits its accumulated batches against
        self._totals: dict[str, int] = {v: 0 for v in VERDICTS}
        self._totals["unknown_site"] = 0
        self._shipper = obs_fanin.WorkerObsShipper()
        self._requests = obs_metrics.counter(
            obs_names.SERVE_REQUESTS,
            "admission verdicts on the serving path (serve/worker.py)",
            labelnames=("outcome",))
        self._lat = serve_latency_histogram()
        self._done = threading.Event()
        self._bye_sent = threading.Event()
        self.httpd = _ReuseportHTTPServer(("0.0.0.0", port),
                                          _make_handler(self))
        self._pipe_thread = threading.Thread(target=self._pipe_loop,
                                             daemon=True,
                                             name=f"serve-w{wid}-pipe")

    # ---- admission bookkeeping (handler threads) ----

    def note_verdict(self, outcome: str, unknown_site: bool = False
                     ) -> None:
        self._requests.labels(outcome=outcome).inc()
        if unknown_site:
            self._requests.labels(outcome="unknown_site").inc()
        with self._lock:
            self._totals[outcome] += 1
            self._vb_counts[outcome] = self._vb_counts.get(outcome, 0) + 1
            if unknown_site:
                self._totals["unknown_site"] += 1
                self._vb_counts["unknown_site"] = \
                    self._vb_counts.get("unknown_site", 0) + 1
            self._vb_n += 1
            if self._vb_n >= _VB_MAX:
                self._flush_verdicts_locked()

    def _flush_verdicts_locked(self) -> None:
        if not self._vb_n:
            return
        self.conn.send(("vb", self.wid, self._vb_counts))  # nidt: allow[lock-send] -- every caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it
        self._vb_counts, self._vb_n = {}, 0

    def _ship_obs_locked(self, force: bool = False) -> None:
        payload = self._shipper.payload(force=force)
        if payload is not None:
            self.conn.send(("obs", self.wid, payload))  # nidt: allow[lock-send] -- caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it

    # ---- lifecycle ----

    def run(self) -> None:
        self._pipe_thread.start()
        with self._lock:
            self.conn.send(("ready", self.wid))
        try:
            self.httpd.serve_forever(poll_interval=0.1)
        finally:
            self.httpd.server_close()
            if self._done.is_set():
                # the pipe thread is mid-_finish: hold the process
                # open until the bye is on the wire (a daemon thread
                # dies with the main thread)
                self._bye_sent.wait(timeout=20.0)

    def _pipe_loop(self) -> None:
        while True:
            try:
                if not self.conn.poll(_VB_AGE_S):
                    # quiet tick: age out a partial verdict batch; the
                    # shipper rate-limits the telemetry payload itself
                    with self._lock:
                        self._flush_verdicts_locked()
                        self._ship_obs_locked()
                    continue
                cmd = self.conn.recv()
            except (EOFError, OSError):
                log.warning("serve worker %d: root pipe closed; "
                            "shutting down", self.wid)
                self.httpd.shutdown()
                return
            kind = cmd[0]
            if kind == "clock":
                with self._lock:
                    self.conn.send(("clock_reply", self.wid, cmd[1],
                                    time.perf_counter_ns()))
            elif kind == "finish":
                self._finish()
                return

    def _finish(self) -> None:
        self._done.set()
        # stop accepting; in-flight handler threads finish their
        # replies before the engine closes below (predict blocks, so
        # give the tail a short drain)
        self.httpd.shutdown()
        deadline = time.monotonic() + 2.0
        while self.engine.queue_depth() and time.monotonic() < deadline:
            time.sleep(0.02)
        # dispatches are done; give reply threads a beat to record
        # their verdicts before the bye snapshot
        time.sleep(0.1)
        self.engine.close()
        with self._lock:
            self._flush_verdicts_locked()
            obs_flight.record("serve_worker_finish", worker=self.wid,
                              served=self._totals["served"])
            # final telemetry ship BEFORE the bye (same pipe, FIFO) so
            # the root's merged artifacts include this worker's tail
            self._ship_obs_locked(force=True)
            stats = dict(self._totals)
            stats["engine"] = self.engine.stats()
            self.conn.send(("bye", self.wid, stats))  # nidt: allow[lock-send] -- caller holds self._lock; the one pipe has no other writer thread outside it
        obs_trace.dump()
        self._bye_sent.set()


def _make_handler(proc: _ServeWorkerProc):
    engine = proc.engine
    bundle = engine.bundle
    known_sites = set(bundle.sites)

    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # stdlib default writes stderr
            pass

        def _reply(self, code: int, body: bytes,
                   ctype: str = "application/json",
                   close: bool = False) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            if close:
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()
            self.wfile.write(body)

        def _reject(self, code: int, outcome: str, reason: str,
                    close: bool = False) -> None:
            obs_flight.record("serve_reject", worker=proc.wid,
                              outcome=outcome, reason=reason,
                              peer=self.client_address[0])
            proc.note_verdict(outcome)
            self._reply(code, json.dumps({"error": reason}).encode(),
                        close=close)

        # ---- /predict ----

        def do_POST(self) -> None:
            if self.path != "/predict":
                self._reply(404, b'{"error": "unknown path"}')
                return
            try:
                length = int(self.headers.get("Content-Length", ""))
                if length < 0:
                    raise ValueError("negative length")
            except ValueError:
                self._reject(411, "rejected_malformed",
                             "Content-Length required", close=True)
                return
            if length > proc.max_body:
                # refuse WITHOUT reading the body; the connection is
                # unusable past an unread body, so close it
                self._reject(413, "rejected_oversized",
                             f"body {length} > max {proc.max_body}",
                             close=True)
                return
            body = self.rfile.read(length)
            site: str | None = None
            try:
                ctype = (self.headers.get("Content-Type") or "").split(
                    ";")[0].strip()
                if ctype == "application/json":
                    obj = json.loads(body)
                    if "site" in obj and obj["site"] is not None:
                        site = str(obj["site"])
                    x = np.asarray(obj["x"], dtype=np.float32)
                else:
                    # raw little-endian f32 array; shape and site ride
                    # headers
                    shape = tuple(
                        int(d) for d in
                        (self.headers.get("X-NIDT-Shape") or "").split(
                            ",") if d)
                    site = self.headers.get("X-NIDT-Site") or None
                    x = np.frombuffer(body, dtype="<f4").reshape(shape)
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reject(400, "rejected_malformed",
                             f"bad request body: {e}")
                return
            unknown = site is not None and site not in known_sites
            if unknown:
                obs_flight.record("serve_unknown_site", worker=proc.wid,
                                  site=site,
                                  peer=self.client_address[0])
            try:
                pending, model_key = engine.submit(site, x)
            except ValueError as e:
                self._reject(400, "rejected_malformed", str(e))
                return
            except RuntimeError as e:  # engine closed (finish race)
                self._reject(503, "error", str(e), close=True)
                return
            if not pending.event.wait(30.0):
                self._reject(504, "error", "dispatch timeout")
                return
            t_result = time.perf_counter()
            if pending.error is not None:
                self._reject(500, "error",
                             f"dispatch failed: {pending.error}")
                return
            out = {
                "y": np.asarray(pending.result, np.float64).tolist(),
                "model": model_key,
                "digest": bundle.digest(model_key),
                "model_version": bundle.source_round,
                "worker": proc.wid,
            }
            self._reply(200, json.dumps(out).encode())
            proc._lat.labels(stage="reply").observe(
                (time.perf_counter() - t_result) * 1e3)
            proc.note_verdict("served", unknown_site=unknown)

        # ---- /metrics + /healthz ----

        def do_GET(self) -> None:
            if self.path == "/metrics":
                self._reply(200,
                            obs_metrics.REGISTRY.prometheus_text(
                            ).encode(),
                            ctype="text/plain; version=0.0.4")
            elif self.path == "/healthz":
                rules_block = obs_rules.health_block()
                ok = rules_block.get("status") != "critical"
                body = {
                    "ok": ok,
                    "worker": proc.wid,
                    "model": bundle.model_name,
                    "model_version": bundle.source_round,
                    "bundle_sha256": bundle.manifest["weights_sha256"],
                    "precision": engine.precision,
                    "queue_depth": engine.queue_depth(),
                    "compute": obs_compute.health(),
                    "health": rules_block,
                }
                self._reply(200 if ok else 503,
                            json.dumps(obs_metrics._json_safe(body)
                                       ).encode())
            else:
                self._reply(404, b'{"error": "unknown path"}')

    return _Handler


def _serve_worker_main(wid: int, conn, wcfg: dict) -> None:
    """Spawned worker entry point ('spawn' context — fresh interpreter,
    fresh obs registry, its own jax runtime and compile cache)."""
    ocfg = wcfg.get("obs") or {}
    if ocfg.get("trace"):
        obs_trace.arm(
            obs_fanin.suffixed_path(ocfg.get("trace_path", ""), wid)
            or None,
            tags={"role": "serve-worker", "worker": wid})
    obs_flight.configure(
        capacity=ocfg.get("flight_capacity"),
        path=obs_fanin.suffixed_path(ocfg.get("flight_path", ""), wid))
    # arm the serving health rules in-process: the engine's dispatch
    # boundary evaluates them, /healthz degrades, nidt_alert fires
    obs_rules.configure(obs_rules.builtin_rules())
    bundle = load_bundle(wcfg["bundle"])
    engine = ServeEngine(bundle,
                         batch_buckets=tuple(wcfg["batch_buckets"]),
                         max_queue_ms=wcfg["max_queue_ms"],
                         precision=wcfg.get("precision", ""))
    worker = _ServeWorkerProc(wid, engine, conn, wcfg["port"],
                              max_body=wcfg.get("max_body",
                                                MAX_BODY_BYTES))
    try:
        worker.run()
    except Exception:  # noqa: BLE001 — log the real error before the
        # process dies; the root sees the pipe sentinel either way
        log.exception("serve worker %d crashed", wid)
        raise
