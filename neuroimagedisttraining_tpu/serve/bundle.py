"""Checkpoint→deployment bundle contract (ISSUE 17 tentpole part 1).

A bundle is the unit ROADMAP item 2's regional distribution ships: a
directory holding ``manifest.json`` + ``weights.msgpack``. ``build_bundle``
reads a training checkpoint (utils/checkpoint.py format), strips training
state (opt state, PRNG keys, history, fomo weights), converts the f32
masters to bf16 inference weights (fp32 retained behind ``precision=
"fp32"``), applies salientgrads sparse masks at load (the served params
ARE sparse; nnz is pinned in the manifest), and unstacks per-silo
personalized models (ditto/fedfomo ``per_params`` [C, ...] stacks) into
per-site entries so the frontend can route ``site → personalized model``.

The manifest is deliberately timestamp-free and written with sorted keys
so save→load→save is bitwise-stable (tests pin this), and it carries a
sha256 over the weights payload plus per-model digests: ``load_bundle``
recomputes both and rejects loudly on any drift — the same
trust-the-committed-artifact posture as analysis/bench_gate.py.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any

import jax
import numpy as np

from flax import serialization

from neuroimagedisttraining_tpu.utils.checkpoint import load_checkpoint

#: bump when the manifest schema or weights layout changes; load_bundle
#: rejects any other version.
BUNDLE_VERSION = 1

MANIFEST_NAME = "manifest.json"
WEIGHTS_NAME = "weights.msgpack"

#: manifest keys that must be present (schema floor for drift rejection)
_REQUIRED_KEYS = (
    "bundle_version", "model", "num_classes", "input_shape", "precision",
    "source_round", "flavor", "sites", "sparse_nnz", "total_params",
    "weights_sha256", "models",
)

GLOBAL_KEY = "global"


class BundleError(ValueError):
    """Raised on any bundle build/load contract violation. Always loud:
    the message names the file and the specific drift."""


def _site_key(site: str) -> str:
    return f"site:{site}"


def _sha256(raw: bytes) -> str:
    return hashlib.sha256(raw).hexdigest()


def _to_numpy(tree: Any) -> Any:
    return jax.tree.map(np.asarray, tree)


def _cast_floats(tree: Any, dtype) -> Any:
    def cast(x):
        x = np.asarray(x)
        if np.issubdtype(x.dtype, np.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


def _count_params(tree: Any) -> int:
    return int(sum(np.asarray(x).size for x in jax.tree.leaves(tree)))


def _count_nnz(tree: Any) -> int:
    return int(sum(int(np.count_nonzero(np.asarray(x)))
                   for x in jax.tree.leaves(tree)))


def _model_digest(entry: dict) -> str:
    """Per-model digest: sha256 over the model's own serialized subtree.

    This is what /predict replies echo, so two sites can PROVE they were
    served different personalized weights (bench routing check)."""
    return _sha256(serialization.msgpack_serialize(entry))


def _apply_mask(params: Any, masks: Any) -> Any:
    """Multiply salientgrads masks into the served params (sparse at
    load — the engine never sees the mask, only the zeroed weights)."""
    try:
        return jax.tree.map(lambda p, m: np.asarray(p) * np.asarray(m),
                            params, masks)
    except ValueError as e:
        raise BundleError(
            f"salientgrads mask tree does not match params tree: {e}"
        ) from e


def _unstack(tree: Any, idx: int) -> Any:
    """Row ``idx`` of a [C, ...] stacked per-silo tree."""
    return jax.tree.map(lambda x: np.asarray(x)[idx], tree)


def _stack_dim(tree: Any) -> int:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return 0
    return int(np.asarray(leaves[0]).shape[0])


def _infer_flavor(state: dict) -> str:
    """Name the checkpoint flavor from its state keys (the shapes are an
    engine contract — see engines/*.py maybe_checkpoint payloads)."""
    if "masks" in state:
        return "salientgrads"
    if "p_choose" in state or "weights" in state:
        return "fedfomo"
    if "per_params" in state:
        return "ditto"
    if "params" in state:
        return "fedavg"
    raise BundleError(
        f"unrecognized checkpoint state (keys={sorted(state)}): no "
        "params/per_params — not a federation checkpoint?")


def _mean_tree(tree: Any) -> Any:
    """Uniform mean over the leading [C, ...] stack axis — the global
    fallback for fedfomo checkpoints, which keep no global model."""
    return jax.tree.map(
        lambda x: np.mean(np.asarray(x, np.float32), axis=0), tree)


def build_bundle(checkpoint_dir: str, out_dir: str, *, model: str,
                 num_classes: int, input_shape: tuple[int, ...] | list[int],
                 precision: str = "bf16", round_idx: int | None = None,
                 ) -> dict:
    """Convert a training checkpoint into a deployment bundle directory.

    Returns the manifest dict. ``precision`` is ``"bf16"`` (default:
    f32 masters → bf16 inference weights) or ``"fp32"`` (the retained
    full-precision flag)."""
    if precision not in ("bf16", "fp32"):
        raise BundleError(
            f"precision must be bf16|fp32, got {precision!r}")
    found = load_checkpoint(checkpoint_dir, round_idx)
    if found is None:
        raise BundleError(f"no checkpoints in {checkpoint_dir!r}")
    source_round, state = found
    flavor = _infer_flavor(state)

    g_params = state.get("params")
    g_bstats = state.get("batch_stats", {})
    per_params = state.get("per_params")
    per_bstats = state.get("per_bstats", {})
    masks = state.get("masks")
    sparse_nnz = None
    if masks is not None and g_params is not None:
        g_params = _apply_mask(g_params, masks)
        sparse_nnz = _count_nnz(g_params)
    if g_params is None:
        # fedfomo keeps no global model; serve the uniform mean of the
        # personalized stack as the unknown-site fallback.
        if per_params is None:
            raise BundleError(
                f"checkpoint flavor {flavor!r} has neither params nor "
                "per_params")
        g_params = _mean_tree(per_params)
        g_bstats = (_mean_tree(per_bstats)
                    if jax.tree.leaves(per_bstats) else {})

    dtype = np.float32 if precision == "fp32" else jax.numpy.bfloat16
    models: dict[str, dict] = {
        GLOBAL_KEY: {
            "params": _cast_floats(g_params, dtype),
            "batch_stats": _cast_floats(_to_numpy(g_bstats), dtype),
        }
    }
    sites: list[str] = []
    if per_params is not None:
        n_sites = _stack_dim(per_params)
        has_bstats = bool(jax.tree.leaves(per_bstats))
        for i in range(n_sites):
            site = str(i)
            sites.append(site)
            p_i = _unstack(per_params, i)
            if masks is not None:
                p_i = _apply_mask(p_i, masks)
            models[_site_key(site)] = {
                "params": _cast_floats(p_i, dtype),
                "batch_stats": _cast_floats(
                    _unstack(per_bstats, i) if has_bstats else {}, dtype),
            }

    payload = serialization.msgpack_serialize(
        {k: models[k] for k in sorted(models)})
    manifest = {
        "bundle_version": BUNDLE_VERSION,
        "model": model,
        "num_classes": int(num_classes),
        "input_shape": [int(d) for d in input_shape],
        "precision": precision,
        "source_round": int(source_round),
        "flavor": flavor,
        "sites": sites,
        "sparse_nnz": sparse_nnz,
        "total_params": _count_params(models[GLOBAL_KEY]["params"]),
        "weights_sha256": _sha256(payload),
        "models": {k: {"digest": _model_digest(models[k])}
                   for k in sorted(models)},
    }
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, WEIGHTS_NAME), "wb") as f:
        f.write(payload)
    with open(os.path.join(out_dir, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    return manifest


@dataclasses.dataclass(frozen=True)
class ServeBundle:
    """A validated, loaded bundle: the manifest plus the weight trees
    keyed ``"global"`` / ``"site:<id>"``."""

    path: str
    manifest: dict
    models: dict[str, dict]

    @property
    def model_name(self) -> str:
        return self.manifest["model"]

    @property
    def num_classes(self) -> int:
        return int(self.manifest["num_classes"])

    @property
    def input_shape(self) -> tuple[int, ...]:
        return tuple(self.manifest["input_shape"])

    @property
    def precision(self) -> str:
        return self.manifest["precision"]

    @property
    def source_round(self) -> int:
        return int(self.manifest["source_round"])

    @property
    def sites(self) -> tuple[str, ...]:
        return tuple(self.manifest["sites"])

    def digest(self, model_key: str) -> str:
        return self.manifest["models"][model_key]["digest"]

    def route(self, site: str | None) -> str:
        """Site → model key; unknown/absent sites fall back to the
        global model (the caller records the unknown-site verdict)."""
        if site is not None and _site_key(site) in self.models:
            return _site_key(site)
        return GLOBAL_KEY


def read_manifest(bundle_dir: str) -> dict:
    """Parse + schema-validate manifest.json (no weights read)."""
    mpath = os.path.join(bundle_dir, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except FileNotFoundError as e:
        raise BundleError(f"not a bundle: {mpath} missing") from e
    except json.JSONDecodeError as e:
        raise BundleError(f"corrupt manifest {mpath}: {e}") from e
    missing = [k for k in _REQUIRED_KEYS if k not in manifest]
    if missing:
        raise BundleError(
            f"stale manifest {mpath}: missing keys {missing} "
            f"(schema version {BUNDLE_VERSION} requires "
            f"{list(_REQUIRED_KEYS)})")
    if manifest["bundle_version"] != BUNDLE_VERSION:
        raise BundleError(
            f"bundle version mismatch in {mpath}: found "
            f"{manifest['bundle_version']!r}, this tree speaks "
            f"{BUNDLE_VERSION}")
    return manifest


def load_bundle(bundle_dir: str) -> ServeBundle:
    """Load + verify a bundle. Every drift path raises ``BundleError``
    naming the mismatch: bad schema, payload sha256, site set, or
    per-model digest."""
    manifest = read_manifest(bundle_dir)
    wpath = os.path.join(bundle_dir, WEIGHTS_NAME)
    try:
        with open(wpath, "rb") as f:
            payload = f.read()
    except FileNotFoundError as e:
        raise BundleError(f"bundle {bundle_dir!r}: {WEIGHTS_NAME} "
                          "missing") from e
    got = _sha256(payload)
    if got != manifest["weights_sha256"]:
        raise BundleError(
            f"weights drift in {wpath}: sha256 {got[:12]}… != manifest "
            f"{manifest['weights_sha256'][:12]}…")
    try:
        models = serialization.msgpack_restore(payload)
    except Exception as e:  # msgpack raises library-specific types
        raise BundleError(f"corrupt weights payload {wpath}: {e}") from e
    want_keys = {GLOBAL_KEY} | {_site_key(s) for s in manifest["sites"]}
    if set(models) != want_keys:
        raise BundleError(
            f"bundle {bundle_dir!r}: weights carry models "
            f"{sorted(models)} but manifest declares {sorted(want_keys)}")
    for key, entry in models.items():
        digest = _model_digest(entry)
        if digest != manifest["models"][key]["digest"]:
            raise BundleError(
                f"model {key!r} drift in {wpath}: digest {digest[:12]}… "
                f"!= manifest {manifest['models'][key]['digest'][:12]}…")
    return ServeBundle(path=os.path.abspath(bundle_dir),
                       manifest=manifest, models=models)
