// Native host-side data-path kernels for the streaming federation.
//
// The reference's data plane leans on native code inside its dependencies
// (libhdf5 fancy reads + torch pinned-tensor copies, SURVEY.md §2.9); the
// in-Python part — assembling per-client row batches — is a single-threaded
// numpy gather. Here that gather is a multithreaded row memcpy: ABCD rows
// are ~2.1 MB of uint8 each, so the copy is memory-bandwidth-bound and
// scales with threads until DRAM saturates (~4-8x over one core on the
// 5-CPU hosts BASELINE.md records).
//
// Exposed via ctypes (no pybind11 in this image); see utils/native.py for
// the build-on-first-use wrapper and the numpy fallback.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

void copy_span(const uint8_t* src, const int64_t* idx, int64_t row_bytes,
               uint8_t* dst, int64_t begin, int64_t end) {
  for (int64_t i = begin; i < end; ++i) {
    std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

template <typename Fn>
void parallel_rows(int64_t n_rows, int n_threads, Fn fn) {
  if (n_threads <= 1 || n_rows < 2) {
    fn(0, n_rows);
    return;
  }
  std::vector<std::thread> workers;
  int64_t per = (n_rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t begin = t * per;
    int64_t end = begin + per < n_rows ? begin + per : n_rows;
    if (begin >= end) break;
    workers.emplace_back([=] { fn(begin, end); });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

// dst[i] = src[idx[i]] for uint8 rows of row_bytes each.
void nidt_gather_rows_u8(const uint8_t* src, const int64_t* idx,
                         int64_t n_rows, int64_t row_bytes, uint8_t* dst,
                         int n_threads) {
  parallel_rows(n_rows, n_threads, [&](int64_t b, int64_t e) {
    copy_span(src, idx, row_bytes, dst, b, e);
  });
}

}  // extern "C"
