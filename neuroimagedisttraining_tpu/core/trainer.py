"""The local trainer: jitted client-side SGD and evaluation.

Replaces the reference's per-algorithm ``MyModelTrainer`` torch classes
(e.g. fedml_api/standalone/sailentgrads/my_model_trainer.py:201-236 train,
239-274 test) with pure functions designed to be ``vmap``-ed over a leading
client axis and sharded over a TPU mesh:

- ``local_train``: E local epochs of minibatch SGD via ``lax.scan`` —
  BCE/CE loss, global-norm grad clip 10, torch-parity SGD momentum + weight
  decay, per-round lr, optional post-step sparse-mask reapply
  (``param *= mask``, my_model_trainer.py:228-231).
- Per-client *step counts* are preserved under vmap: every client scans the
  same static number of steps, but steps beyond ``ceil(n_i/B)`` per epoch are
  masked no-ops, so small clients do exactly as many updates as the
  reference's DataLoader would give them.
- ``evaluate``: full-cohort chunked eval returning correct/loss/total plus
  raw scores for AUC (metrics dict parity: my_model_trainer.py:245-274).

Data lives on device as padded per-client arrays (uint8 voxels cast raw to
float32, matching my_model_trainer.py:197-198's ``torch.tensor(X_batch,
dtype=float32)`` with no rescale).
"""

from __future__ import annotations

import math
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.config import OptimConfig
from neuroimagedisttraining_tpu.core.losses import make_loss, predictions
from neuroimagedisttraining_tpu.core.optim import (
    make_local_optimizer, validate_precision,
)
from neuroimagedisttraining_tpu.models import primary_logits

PyTree = Any


def epoch_permutations(rng: jax.Array, epochs: int, max_samples: int,
                       n_valid) -> jax.Array:
    """[epochs, max_samples] of per-epoch uniform permutations of the
    VALID rows (indices < ``n_valid``) with every padded row sorted last.

    Static-shape analog of the reference DataLoader's per-epoch shuffle
    (my_model_trainer.py:213): sort per-row uniforms, with padded rows
    pinned to a sentinel above the uniform range so positions
    ``[0, n_valid)`` of each row are a uniform permutation of the valid
    indices."""
    keys = jax.random.split(rng, epochs)
    u = jax.vmap(lambda k: jax.random.uniform(k, (max_samples,)))(keys)
    u = jnp.where(jnp.arange(max_samples) < n_valid, u, 2.0)
    return jnp.argsort(u, axis=-1)


def epoch_perms_for(rng: jax.Array, epochs: int, max_samples: int,
                    n_valid) -> jax.Array:
    """The epoch permutations ``local_train`` would derive from ``rng``
    (its ``cs.rng`` at entry) — the hoisted form the cohort-sharded round
    computes OUTSIDE its ``shard_map`` and passes via ``perms=``: the
    argsort-lowered permutation miscompiles inside a shard_map partition
    on this toolchain (see ``local_train``'s docstring and
    parallel/cohort.py). Must mirror local_train's split exactly."""
    _, prng = jax.random.split(rng)
    return epoch_permutations(prng, epochs, max_samples, n_valid)


def shuffle_batch_indices(perms: jax.Array, t, steps_per_epoch: int,
                          batch_size: int, n_valid):
    """Row indices + validity weights for scan step ``t`` when walking the
    per-epoch permutations in ``batch_size`` strides.

    The final batch of an epoch may run past ``n_valid``; those positions
    wrap to the epoch's start so every gathered row is a real sample, and
    their weight is 0 so the loss/grad is the mean over the true partial
    batch — exactly the reference's smaller last DataLoader batch."""
    e = t // steps_per_epoch
    pos = (t % steps_per_epoch) * batch_size + jnp.arange(batch_size)
    idx = perms[e][pos % jnp.maximum(n_valid, 1)]
    w = (pos < n_valid).astype(jnp.float32)  # nidt: allow[precision-upcast] -- loss weights are a blessed f32 loss site (the loss itself is f32 by contract)
    return idx, w


@flax.struct.dataclass
class ClientState:
    """All trainable state of one client; with a leading client axis this is
    the whole federation."""
    params: PyTree
    batch_stats: PyTree
    opt_state: PyTree
    rng: jax.Array


class LocalTrainer:
    """Functional trainer bound to one model + optimizer config."""

    def __init__(self, model, optim: OptimConfig, num_classes: int):
        self.model = model
        self.optim_cfg = optim
        self.num_classes = num_classes
        self.loss = make_loss(num_classes)
        # precision contract (ISSUE 10, core/optim.py): validated here so
        # a bad precision/loss_scale/fused_update combination dies at
        # trainer build, not at first trace. The model's compute dtype is
        # chosen where the model is built (build_experiment passes
        # compute_dtype(optim.precision)); the trainer owns the fixed
        # loss-scale constant — a static multiply of the f32 loss before
        # grad and an f32 divide of the grads after, skipped entirely at
        # scale 1.0 so the default path stays bitwise-unchanged.
        validate_precision(optim)
        self._loss_scale = float(optim.loss_scale)
        self.opt = make_local_optimizer(optim)
        # Full input ndim (batch + spatial + channel) the model expects;
        # drives channel-dim completion in _prep. Declared per model family
        # so a 4-D [B,H,W,C] CIFAR batch is never mistaken for an
        # unchanneled volumetric one.
        self._input_rank = getattr(model, "input_rank", None)

    # ---------- init ----------

    def init_client_state(self, rng: jax.Array, sample_x: jax.Array) -> ClientState:
        prng, drng, srng = jax.random.split(rng, 3)
        variables = self.model.init({"params": prng, "dropout": drng},
                                    self._prep(sample_x), train=False)
        params = variables["params"]
        bstats = variables.get("batch_stats", {})
        return ClientState(params=params, batch_stats=bstats,
                           opt_state=self.opt.init(params), rng=srng)

    def _prep(self, x: jax.Array) -> jax.Array:
        """uint8 -> float32 raw cast; add trailing channel dim when the input
        is exactly one rank short of the model's declared ``input_rank``
        (reference ``unsqueeze(1)``, my_model_trainer.py:216 — ours is
        channels-last)."""
        x = x.astype(jnp.float32)  # nidt: allow[precision-upcast] -- reference raw-cast parity (my_model_trainer.py:197-198): the uint8 input-quantization boundary, models re-cast to compute dtype
        if self._input_rank is not None and x.ndim == self._input_rank - 1:
            x = x[..., None]  # e.g. [B,D,H,W] -> [B,D,H,W,1]
        return x

    def _apply(self, params, batch_stats, x, train: bool, dropout_rng=None):
        variables = {"params": params}
        has_bn = bool(jax.tree.leaves(batch_stats))
        if has_bn:
            variables["batch_stats"] = batch_stats
        rngs = {"dropout": dropout_rng} if (train and dropout_rng is not None) else None
        if train and has_bn:
            out, mut = self.model.apply(variables, x, train=True, rngs=rngs,
                                        mutable=["batch_stats"])
            return out, mut["batch_stats"]
        out = self.model.apply(variables, x, train=train, rngs=rngs)
        return out, batch_stats

    # ---------- training ----------

    def _scaled(self, loss):
        """Loss-scale multiply inside the grad function (bf16_mixed
        static scaling); a literal no-op at the pinned scale 1.0."""
        return loss * self._loss_scale if self._loss_scale != 1.0 else loss

    def _unscaled(self, loss, grads):
        """Invert the loss scale on the f32 loss/grads outside the grad
        function; a literal no-op at scale 1.0 (bitwise-f32 contract)."""
        if self._loss_scale == 1.0:
            return loss, grads
        inv = self._loss_scale
        return loss / inv, jax.tree.map(lambda g: g / inv, grads)

    def loss_and_grad(self, cs: ClientState, x, y):
        """One batch's (loss, grads, new batch_stats); used directly by SNIP
        scoring and gradient probes as well as by ``local_train``."""
        rng, drng = jax.random.split(cs.rng)

        def f(params):
            out, bstats = self._apply(params, cs.batch_stats, self._prep(x),
                                      train=True, dropout_rng=drng)
            return self._scaled(self.loss(primary_logits(out), y)), bstats

        (loss, bstats), grads = jax.value_and_grad(f, has_aux=True)(cs.params)
        loss, grads = self._unscaled(loss, grads)
        return loss, grads, bstats, rng

    def local_train(self, cs: ClientState, X, y, n_valid, lr, epochs: int,
                    batch_size: int, max_samples: int,
                    mask: PyTree | None = None,
                    prox_lamda: float | None = None,
                    prox_ref: PyTree | None = None,
                    perms: jax.Array | None = None):
        """E epochs of local SGD on device-resident (padded) client data.

        Returns ``(new_state, mean_loss)``. ``n_valid`` is the client's true
        sample count; steps beyond its per-epoch quota are masked no-ops so
        vmapped clients keep reference-parity update counts.

        Batch selection follows ``optim.batch_order``: ``"shuffle"``
        (default) walks a fresh per-epoch permutation in ``batch_size``
        strides with a weighted partial final batch — the reference
        DataLoader's semantics (my_model_trainer.py:213) under static
        shapes. Loss and gradients of the partial batch are EXACTLY the
        reference's smaller-batch mean (torch-pinned in
        tests/test_torch_parity.py); the one residual deviation is
        BatchNorm models, whose partial-batch activation statistics see
        the wrapped filler rows (real samples, zero loss weight) that a
        genuinely smaller torch batch would not contain.
        ``"replacement"`` draws i.i.d. uniform batches.

        ``prox_lamda``/``prox_ref``: Ditto's personalized proximal pull,
        applied after each optimizer step: ``w -= lr * lamda * (w - ref)``
        (ditto/my_model_trainer.py:63-64).

        ``perms``: precomputed epoch permutations (what
        :func:`epoch_perms_for` derives from the SAME ``cs.rng``) — the
        cohort-sharded round (parallel/cohort.py) computes them OUTSIDE
        its ``shard_map`` and passes them in, because the argsort-based
        permutation lowering MISCOMPILES inside a shard_map partition on
        this toolchain (jax 0.4.x CPU SPMD: the consumed permutation
        silently differs from the observable one — caught by the cohort
        bitwise pins). The rng stream is identical either way: the split
        that would have fed the permutation is still consumed.
        """
        steps_per_epoch = max(1, math.ceil(max_samples / batch_size))
        my_steps = jnp.ceil(n_valid / batch_size).astype(jnp.int32)
        total = epochs * steps_per_epoch
        shuffle = self.optim_cfg.batch_order == "shuffle"
        if shuffle:
            # reference DataLoader semantics: each epoch walks a fresh
            # permutation of the client's rows in batch_size strides
            rng0, prng = jax.random.split(cs.rng)
            cs = cs.replace(rng=rng0)
            if perms is None:
                perms = epoch_permutations(prng, epochs, max_samples,
                                           n_valid)

        def step(carry, t):
            state = carry
            rng, brng, drng = jax.random.split(state.rng, 3)
            if shuffle:
                idx, wb = shuffle_batch_indices(perms, t, steps_per_epoch,
                                                batch_size, n_valid)
            else:
                idx = jax.random.randint(brng, (batch_size,), 0,
                                         jnp.maximum(n_valid, 1))
                wb = None
            xb = jnp.take(X, idx, axis=0)
            yb = jnp.take(y, idx, axis=0)

            def f(params):
                out, bstats = self._apply(params, state.batch_stats,
                                          self._prep(xb), train=True,
                                          dropout_rng=drng)
                return self._scaled(
                    self.loss(primary_logits(out), yb, weights=wb)), bstats

            (loss, bstats), grads = jax.value_and_grad(f, has_aux=True)(
                state.params)
            loss, grads = self._unscaled(loss, grads)
            if self.opt.fused_apply is not None:
                # fused clip+wd+momentum+update+mask tail in one pass
                # (ops/fused_update.py; bit-parity with the chain below)
                params, opt_state = self.opt.fused_apply(
                    grads, state.opt_state, state.params, lr, mask)
            else:
                updates, opt_state = self.opt.update(grads, state.opt_state,
                                                     state.params, lr)
                params = jax.tree.map(jnp.add, state.params, updates)
                if mask is not None:
                    params = jax.tree.map(jnp.multiply, params, mask)
            if prox_lamda is not None:
                params = jax.tree.map(
                    lambda w, ref: w - lr * prox_lamda * (w - ref),
                    params, prox_ref)

            active = (t % steps_per_epoch) < my_steps

            def keep(new, old):
                return jax.tree.map(
                    lambda a, b: jnp.where(active, a, b), new, old)

            new_state = ClientState(
                params=keep(params, state.params),
                batch_stats=keep(bstats, state.batch_stats),
                opt_state=keep(opt_state, state.opt_state),
                rng=rng)
            return new_state, jnp.where(active, loss, 0.0)

        cs, losses = jax.lax.scan(step, cs, jnp.arange(total))
        denom = jnp.maximum(epochs * my_steps, 1)
        return cs, jnp.sum(losses) / denom

    def lower_train_step(self, input_shape: tuple[int, ...],
                         batch_size: int):
        """AOT-lower ONE training step (``loss_and_grad``: forward +
        backward + BN update) at fully ABSTRACT shapes — params come
        from an ``eval_shape`` of the model init, the batch is a
        ``ShapeDtypeStruct``, so nothing is materialized, compiled or
        executed even at the flagship 121x145x121 volume on the CPU
        harness. The returned ``jax.stages.Lowered`` is the XLA
        accounting surface: ``cost_analysis()`` reads FLOPs off the
        unoptimized HLO, ``.compile().memory_analysis()`` adds the
        temp/argument byte accounting (obs/compute.analyze_train_step
        reconciles both against the analytic ops/flops.py counter)."""
        x1 = jax.ShapeDtypeStruct((1, *input_shape), jnp.float32)
        cs = jax.eval_shape(self.init_client_state, jax.random.key(0),
                            x1)
        xs = jax.ShapeDtypeStruct((batch_size, *input_shape),
                                  jnp.float32)
        ys = jax.ShapeDtypeStruct((batch_size,), jnp.int32)

        def step(cs, x, y):
            loss, grads, bstats, _ = self.loss_and_grad(cs, x, y)
            return loss, grads, bstats

        return jax.jit(step).lower(cs, xs, ys)

    def eval_grad(self, params: PyTree, batch_stats: PyTree, x, y) -> PyTree:
        """One-batch DENSE gradient probe in eval mode (no dropout, BN in
        inference mode) — DisPFL's ``screen_gradients``
        (DisPFL/my_model_trainer.py:165-188, model.eval() + one batch)."""
        def f(p):
            out, _ = self._apply(p, batch_stats, self._prep(x), train=False)
            return self._scaled(self.loss(primary_logits(out), y))

        grads = jax.grad(f)(params)
        if self._loss_scale != 1.0:
            grads = jax.tree.map(lambda g: g / self._loss_scale, grads)
        return grads

    # ---------- evaluation ----------

    def evaluate(self, params, batch_stats, X, y, valid, batch_size: int = 32):
        """Chunked full-set eval. Returns dict with ``test_correct``,
        ``test_loss`` (sum), ``test_total`` and raw ``scores`` for AUC."""
        n = X.shape[0]
        nb = max(1, math.ceil(n / batch_size))
        pad = nb * batch_size - n
        Xp = jnp.pad(X, [(0, pad)] + [(0, 0)] * (X.ndim - 1))
        yp = jnp.pad(y, (0, pad))
        vp = jnp.pad(valid.astype(jnp.float32), (0, pad))

        def chunk(_, i):
            sl = lambda a: jax.lax.dynamic_slice_in_dim(a, i * batch_size,
                                                        batch_size, 0)
            xb, yb, vb = sl(Xp), sl(yp), sl(vp)
            out, _ = self._apply(params, batch_stats, self._prep(xb),
                                 train=False)
            logits = primary_logits(out)
            preds = predictions(logits, self.num_classes)
            correct = jnp.sum((preds == yb.astype(jnp.int32)) * vb)
            loss = self.loss(logits, yb, weights=vb) * jnp.sum(vb)
            score = (logits.reshape(batch_size, -1)[:, 0]
                     if self.num_classes == 1
                     else jax.nn.log_softmax(logits)[:, -1])
            return None, (correct, loss, jnp.sum(vb), score)

        _, (corrects, losses, totals, scores) = jax.lax.scan(
            chunk, None, jnp.arange(nb))
        return {
            "test_correct": jnp.sum(corrects),
            "test_loss": jnp.sum(losses),
            "test_total": jnp.sum(totals),
            "scores": scores.reshape(-1)[:n],
        }
