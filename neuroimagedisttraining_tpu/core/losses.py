"""Losses and evaluation metrics.

Parity targets: BCE-with-logits for ABCD sex classification
(my_model_trainer.py:206 ``nn.BCEWithLogitsLoss``), cross-entropy for the
CIFAR paths (dpsgd/my_model_trainer.py:39-65); accuracy at threshold 0.5
(my_model_trainer.py:263-268). The reference's BASELINE metric names AUC but
computes accuracy (SURVEY.md §5.5) — we log both.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def bce_with_logits(logits: jax.Array, labels: jax.Array,
                    weights: jax.Array | None = None) -> jax.Array:
    """Mean binary cross-entropy over valid entries. ``logits`` [B] or [B,1]."""
    logits = logits.reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    per = optax.sigmoid_binary_cross_entropy(logits, labels)
    if weights is None:
        return jnp.mean(per)
    w = weights.reshape(-1).astype(jnp.float32)
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1e-9)


def softmax_ce(logits: jax.Array, labels: jax.Array,
               weights: jax.Array | None = None) -> jax.Array:
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits, labels.astype(jnp.int32))
    if weights is None:
        return jnp.mean(per)
    w = weights.reshape(-1).astype(jnp.float32)
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1e-9)


def make_loss(num_classes: int):
    """num_classes==1 -> BCE-with-logits (ABCD); else integer-label CE."""
    return bce_with_logits if num_classes == 1 else softmax_ce


def predictions(logits: jax.Array, num_classes: int) -> jax.Array:
    """Hard predictions: sigmoid>0.5 for binary (my_model_trainer.py:263-268),
    argmax otherwise."""
    if num_classes == 1:
        return (logits.reshape(-1) > 0.0).astype(jnp.int32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def binary_auc(scores: jax.Array, labels: jax.Array,
               valid: jax.Array | None = None) -> jax.Array:
    """Exact pairwise ROC-AUC (Mann-Whitney U with 0.5 tie credit).

    O(N^2) pairwise form — fine at per-client cohort sizes (~10^2-10^3) and
    fully jittable with a validity mask (padded client shards)."""
    s = scores.reshape(-1).astype(jnp.float32)
    y = labels.reshape(-1).astype(jnp.int32)
    v = jnp.ones_like(s) if valid is None else valid.reshape(-1).astype(jnp.float32)
    pos = (y == 1).astype(jnp.float32) * v
    neg = (y == 0).astype(jnp.float32) * v
    gt = (s[:, None] > s[None, :]).astype(jnp.float32)
    eq = (s[:, None] == s[None, :]).astype(jnp.float32)
    wins = jnp.einsum("i,ij,j->", pos, gt + 0.5 * eq, neg)
    denom = jnp.sum(pos) * jnp.sum(neg)
    return jnp.where(denom > 0, wins / jnp.maximum(denom, 1.0), 0.5)
