from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer  # noqa: F401
from neuroimagedisttraining_tpu.core.losses import (  # noqa: F401
    bce_with_logits,
    softmax_ce,
    binary_auc,
    make_loss,
    predictions,
)
from neuroimagedisttraining_tpu.core.optim import make_local_optimizer, round_lr  # noqa: F401
