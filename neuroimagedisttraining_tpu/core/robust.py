"""Byzantine-robust aggregation defenses.

Parity target: fedml_core/robustness/robust_aggregation.py:28-55 —
``RobustAggregator.norm_diff_clipping`` (w_t + clip(w_local − w_t), clip
scale = max(1, ‖diff‖/norm_bound)) and ``.add_noise`` (weak-DP Gaussian).

The reference excludes BatchNorm running stats from the clipped vector by
key-name filtering (``is_weight_param``, robust_aggregation.py:28-29). Here
the exclusion is structural: running stats live in the separate
``batch_stats`` collection, so clipping the ``params`` pytree alone IS the
reference's filter — no name matching needed.

The reference ships the aggregator but nothing in the fork calls it
(SURVEY.md §2.1); BASELINE.json's robustness config ("robust aggregation
under Byzantine clients") defines the behavior contract. Defenses compose as
pure functions on stacked client pytrees, applied between local training and
``tree_weighted_mean`` — inside the jitted round program, so the per-client
clip norms reduce over the client mesh axis on ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.utils import pytree as pt

DEFENSES = ("none", "norm_diff_clipping", "weak_dp")


def norm_diff_clip(local_params, global_params, norm_bound):
    """w_t + diff / max(1, ‖diff‖/norm_bound), diff = w_local − w_t
    (robust_aggregation.py:38-49)."""
    diff = pt.tree_sub(local_params, global_params)
    norm = pt.tree_norm(diff)
    scale = jnp.maximum(1.0, norm / jnp.float32(norm_bound))
    return pt.tree_add(global_params, pt.tree_scale(diff, 1.0 / scale))


def add_weak_dp_noise(params, rng, stddev):
    """Per-leaf Gaussian noise N(0, stddev²) (robust_aggregation.py:51-55)."""
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        (x + jax.random.normal(k, x.shape, jnp.float32)
         * jnp.float32(stddev)).astype(x.dtype)
        for x, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noised)


def defend_stacked(stacked_params, global_params, *, defense: str,
                   norm_bound: float, stddev: float, rngs=None):
    """Apply a defense to each client's params along the leading client axis.

    ``norm_diff_clipping``: clip every client's update norm to norm_bound.
    ``weak_dp``: clipping + per-client Gaussian noise (the weak-DP defense
    uses the clipped update as its sensitivity bound, so noise composes on
    top of clipping). ``rngs``: [C] stacked PRNG keys, required for weak_dp.
    """
    if defense == "none":
        return stacked_params
    if defense not in DEFENSES:
        raise ValueError(f"unknown defense {defense!r}; one of {DEFENSES}")
    clipped = jax.vmap(lambda p: norm_diff_clip(p, global_params, norm_bound)
                       )(stacked_params)
    if defense == "weak_dp":
        if rngs is None:
            raise ValueError("weak_dp needs per-client rngs")
        clipped = jax.vmap(
            lambda p, r: add_weak_dp_noise(p, r, stddev))(clipped, rngs)
    return clipped
