"""Byzantine-robust aggregation defenses.

Parity target: fedml_core/robustness/robust_aggregation.py:28-55 —
``RobustAggregator.norm_diff_clipping`` (w_t + clip(w_local − w_t), clip
scale = max(1, ‖diff‖/norm_bound)) and ``.add_noise`` (weak-DP Gaussian).

The reference excludes BatchNorm running stats from the clipped vector by
key-name filtering (``is_weight_param``, robust_aggregation.py:28-29). Here
the exclusion is structural: running stats live in the separate
``batch_stats`` collection, so clipping the ``params`` pytree alone IS the
reference's filter — no name matching needed.

The reference ships the aggregator but nothing in the fork calls it
(SURVEY.md §2.1); BASELINE.json's robustness config ("robust aggregation
under Byzantine clients") defines the behavior contract. Defenses compose as
pure functions on stacked client pytrees, applied between local training and
``tree_weighted_mean`` — inside the jitted round program, so the per-client
clip norms reduce over the client mesh axis on ICI.

Byzantine-robust aggregators (ISSUE 5). Clipping bounds an update's
*magnitude* but not its *direction*: a sign-flipped update inside the
norm bound passes untouched and still drags the mean. The order-
statistic family closes that gap with provable breakdown points:

- ``trimmed_mean`` / ``median`` — coordinate-wise trimmed mean and
  median (Yin et al. 2018): per coordinate, sort the client values,
  drop the ``byz_f`` smallest and largest (median: keep the middle),
  average the rest. Tolerates any f < n/2 arbitrary clients.
- ``krum`` / ``multi_krum`` — Krum (Blanchard et al. 2017): score each
  client by the summed squared distances to its n−f−2 nearest peers,
  select the lowest-scoring client (multi-Krum: the best n−f−2) and
  average the selection. Requires n ≥ f + 3.
- ``geometric_median`` — the classical robust center, approximated by a
  fixed-iteration Weiszfeld loop (``lax.fori_loop``, trace-static
  iteration count so fused K-round windows stay one compiled program).

Unlike the clip family these REPLACE the weighted mean rather than
preceding it: ``aggregate_with_defense`` is the single dispatch the
engines and the cross-silo server call — clip-family defenses run
per-client and fall through to ``mean_fn`` (the engine's silo-aware
weighted mean), order-statistic defenses consume the stacked updates
whole. Weighting stays consistent with ``tree_weighted_mean``: surviving
coordinates/selections are combined with the clients' sample-count
weights renormalized over the survivors (the unweighted coordinate
median is the one exception — a weighted order statistic has no exact
streaming form; documented at the function).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.utils import pytree as pt

#: clip-family defenses: per-client transforms BEFORE the weighted mean
CLIP_DEFENSES = ("none", "norm_diff_clipping", "weak_dp")
#: order-statistic defenses: replace the weighted mean outright
ROBUST_AGGREGATORS = ("trimmed_mean", "median", "krum", "multi_krum",
                      "geometric_median")
DEFENSES = CLIP_DEFENSES + ROBUST_AGGREGATORS


def validate_defense(name: str) -> str:
    """Fail loudly at STARTUP on an unknown defense name (an unknown
    ``--defense`` must never surface as a mid-round trace error)."""
    if name not in DEFENSES:
        raise ValueError(
            f"unknown defense {name!r}; one of {', '.join(DEFENSES)}")
    return name


def norm_diff_clip(local_params, global_params, norm_bound):
    """w_t + diff / max(1, ‖diff‖/norm_bound), diff = w_local − w_t
    (robust_aggregation.py:38-49)."""
    diff = pt.tree_sub(local_params, global_params)
    norm = pt.tree_norm(diff)
    scale = jnp.maximum(1.0, norm / jnp.float32(norm_bound))  # nidt: allow[precision-upcast] -- defense math runs on f32 master weights by contract (ARCHITECTURE.md Precision & memory)
    return pt.tree_add(global_params, pt.tree_scale(diff, 1.0 / scale))


def add_weak_dp_noise(params, rng, stddev):
    """Per-leaf Gaussian noise N(0, stddev²) (robust_aggregation.py:51-55)."""
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        (x + jax.random.normal(k, x.shape, jnp.float32)
         * jnp.float32(stddev)).astype(x.dtype)  # nidt: allow[precision-upcast] -- weak-DP noise is drawn in f32 against f32 master weights (reference parity, robust_aggregation.py:51-55)
        for x, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noised)


def defend_stacked(stacked_params, global_params, *, defense: str,
                   norm_bound: float, stddev: float, rngs=None):
    """Apply a defense to each client's params along the leading client axis.

    ``norm_diff_clipping``: clip every client's update norm to norm_bound.
    ``weak_dp``: clipping + per-client Gaussian noise (the weak-DP defense
    uses the clipped update as its sensitivity bound, so noise composes on
    top of clipping). ``rngs``: [C] stacked PRNG keys, required for weak_dp.

    Order-statistic defenses (``ROBUST_AGGREGATORS``) pass through
    UNCHANGED — they act at aggregation time (``robust_aggregate``), not
    per client; ``aggregate_with_defense`` is the dispatch that runs
    both stages in the right order.
    """
    validate_defense(defense)
    if defense == "none" or defense in ROBUST_AGGREGATORS:
        return stacked_params
    clipped = jax.vmap(lambda p: norm_diff_clip(p, global_params, norm_bound)
                       )(stacked_params)
    if defense == "weak_dp":
        if rngs is None:
            raise ValueError("weak_dp needs per-client rngs")
        clipped = jax.vmap(
            lambda p, r: add_weak_dp_noise(p, r, stddev))(clipped, rngs)
    return clipped


# ---------------------------------------------------------------------------
# non-finite upload guard (ISSUE 5 satellite): a single NaN/Inf client
# poisons tree_weighted_mean (0·NaN = NaN), so rounds sanitize BEFORE
# any aggregation — independent of the --defense flag
# ---------------------------------------------------------------------------

def finite_per_client(stacked) -> jax.Array:
    """[C] bool: client c's row is finite in EVERY leaf."""
    def per_client(tree):
        flags = [jnp.all(jnp.isfinite(x.astype(jnp.float32)))  # nidt: allow[precision-upcast] -- finiteness guard must see exact f32 view of every upload leaf (int leaves included)
                 for x in jax.tree.leaves(tree)]
        return jnp.stack(flags).all() if flags else jnp.bool_(True)

    return jax.vmap(per_client)(stacked)


def replace_nonfinite_clients(stacked, reference, finite: jax.Array):
    """Swap each non-finite client's row for the round's broadcast
    ``reference`` (a no-op update — neutral for the order-statistic
    defenses, and exactly what a client that never trained would have
    uploaded). Callers also zero the client's aggregation weight, so
    under the weighted mean the row contributes nothing at all."""
    def leaf(x, r):
        keep = finite.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(keep, x,
                         r.astype(x.dtype)[None] if hasattr(r, "dtype")
                         else r)

    return jax.tree.map(leaf, stacked, reference)


# ---------------------------------------------------------------------------
# order-statistic aggregators (jitted; stacked [C, ...] pytrees in, one
# aggregate tree out)
# ---------------------------------------------------------------------------

def _client_count(stacked) -> int:
    leaves = jax.tree.leaves(stacked)
    if not leaves:
        raise ValueError("robust aggregation over an empty pytree")
    return int(leaves[0].shape[0])


def _check_f(n: int, f: int, defense: str) -> int:
    f = int(f)
    if f < 0:
        raise ValueError(f"byz_f must be >= 0, got {f}")
    if defense in ("krum", "multi_krum"):
        # n >= f+3 is the MECHANICAL floor (the score sums distances to
        # n-f-2 >= 1 nearest peers); Blanchard et al.'s (f,lambda)-
        # resilience theorem needs n >= 2f+3 — between the two the
        # selection is defined but f COLLUDING attackers can win it
        # (effective_defense warns there; PAPERS.md states the bound)
        if n < f + 3:
            raise ValueError(
                f"{defense} needs n >= byz_f + 3 sampled clients "
                f"(n={n}, byz_f={f}): the score sums distances to the "
                "n-f-2 nearest peers (the provable Blanchard guarantee "
                "additionally needs n >= 2*byz_f + 3)")
    elif 2 * f >= n:
        raise ValueError(
            f"{defense} breakdown point exceeded: needs 2*byz_f < n "
            f"(n={n}, byz_f={f})")
    return f


def trimmed_mean(stacked, weights: jax.Array, f: int):
    """Coordinate-wise f-trimmed weighted mean (Yin et al. 2018): per
    coordinate, sort the voting client values, discard the f smallest
    and f largest, and average the survivors with the clients' weights
    (renormalized over the survivors — ``tree_weighted_mean`` over the
    per-coordinate surviving set).

    Zero-weight rows — streaming mesh pads, non-finite uploads
    sanitized to the broadcast reference — are not client updates at
    all and vote here like in ``coordinate_median``: excluded outright
    (pushed past the voting window in the sort) rather than kept at
    weight 0, where a trim window landing on only zero-weight rows
    would 0/eps-collapse the coordinate to 0. The trim depth shrinks to
    ``(k-1)//2`` per side when the voting cohort k is too small for the
    configured f (fault-schedule shrinkage past the startup check), so
    the kept window is never empty."""
    C = _client_count(stacked)
    _check_f(C, f, "trimmed_mean")
    w = weights.astype(jnp.float32)
    valid = w > 0
    # pathological all-zero cohort (every client sanitized/padded):
    # degrade to the uniform trimmed mean over all rows, like krum's
    # all-zero-selection fallback
    any_valid = jnp.any(valid)
    valid = valid | ~any_valid
    wv = jnp.where(valid, jnp.where(any_valid, w, 1.0), 0.0)
    k = jnp.sum(valid)  # voting rows (traced scalar)
    lo = jnp.minimum(jnp.int32(int(f)), (k - 1) // 2)
    hi = k - lo

    def leaf(x):
        x32 = x.astype(jnp.float32)
        vb = valid.reshape((-1,) + (1,) * (x32.ndim - 1))
        order = jnp.argsort(jnp.where(vb, x32, jnp.inf), axis=0)
        xs = jnp.take_along_axis(x32, order, axis=0)
        wb = jnp.broadcast_to(
            wv.reshape((-1,) + (1,) * (x32.ndim - 1)), x32.shape)
        ws = jnp.take_along_axis(wb, order, axis=0)
        keep = ((jnp.arange(C) >= lo) & (jnp.arange(C) < hi)).reshape(
            (-1,) + (1,) * (x32.ndim - 1))
        ws = ws * keep
        num = jnp.sum(xs * ws, axis=0)
        den = jnp.maximum(jnp.sum(ws, axis=0), 1e-12)
        return (num / den).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


def coordinate_median(stacked, weights: jax.Array | None = None):
    """Coordinate-wise median (Yin et al. 2018). UNWEIGHTED among the
    voting rows by design: a sample-weighted order statistic has no
    exact closed form, and the breakdown-point guarantee (any f < n/2
    arbitrary clients) is stated for the plain median — documented
    deviation from the weighted-mean contract.

    ``weights`` (optional) only gates WHO votes: zero-weight rows —
    streaming mesh pads, non-finite uploads sanitized to the broadcast
    reference — are not client updates at all and must not drag the
    median toward the reference, so they are excluded outright (pushed
    past the voting window in the sort) before the order statistic."""
    if weights is None:
        return jax.tree.map(
            lambda x: jnp.median(x.astype(jnp.float32), axis=0).astype(
                x.dtype), stacked)
    valid = weights.astype(jnp.float32) > 0
    # pathological all-zero cohort (every client sanitized/padded):
    # degrade to the plain median over all rows like trimmed_mean/krum
    # (masking EVERY row to +inf would return inf and destroy the model)
    valid = valid | ~jnp.any(valid)
    k = jnp.sum(valid)  # voting rows (traced scalar, >= 1)
    lo, hi = (k - 1) // 2, k // 2

    def leaf(x):
        x32 = x.astype(jnp.float32)
        keep = valid.reshape((-1,) + (1,) * (x32.ndim - 1))
        xs = jnp.sort(jnp.where(keep, x32, jnp.inf), axis=0)
        med = 0.5 * (jnp.take(xs, lo, axis=0) + jnp.take(xs, hi, axis=0))
        return med.astype(x.dtype)

    return jax.tree.map(leaf, stacked)


def _stacked_matrix(stacked) -> jax.Array:
    """[C, D] float32 flatten-concat of every client's update vector."""
    leaves = jax.tree.leaves(stacked)
    C = leaves[0].shape[0]
    return jnp.concatenate(
        [x.astype(jnp.float32).reshape(C, -1) for x in leaves], axis=1)


def krum_select(stacked, weights: jax.Array, f: int, m: int) -> jax.Array:
    """[m] client indices with the lowest Krum scores. Score_i = sum of
    squared distances to i's n−f−2 nearest OTHER clients (Blanchard et
    al. 2017). Zero-weight clients (non-finite uploads sanitized to the
    reference row, streaming mesh pads) are pushed out of the selection
    with an additive penalty — they are not updates at all."""
    V = _stacked_matrix(stacked)
    C = V.shape[0]
    sq = jnp.sum(V * V, axis=1)
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (V @ V.T), 0.0)
    srt = jnp.sort(d2, axis=1)  # column 0 is the self-distance (0)
    closest = max(1, C - int(f) - 2)
    scores = jnp.sum(srt[:, 1:closest + 1], axis=1)
    scores = scores + jnp.where(weights > 0, 0.0, jnp.float32(1e30))
    return jnp.argsort(scores)[:m]


def krum(stacked, weights: jax.Array, f: int, multi: bool = False):
    """(multi-)Krum aggregate: select the lowest-score client (multi:
    the best n−f−2) and return the selection's sample-weighted mean
    (weights renormalized over the selection, degenerating to the single
    selected update for m = 1)."""
    C = _client_count(stacked)
    _check_f(C, f, "multi_krum" if multi else "krum")
    m = max(1, C - int(f) - 2) if multi else 1
    sel = krum_select(stacked, weights, f, m)
    chosen = jax.tree.map(lambda x: x[sel], stacked)
    wsel = weights.astype(jnp.float32)[sel]
    # all-zero selection weights (pathological: every selected client
    # was sanitized/padded) fall back to uniform over the selection
    wsel = jnp.where(jnp.sum(wsel) > 0, wsel, jnp.ones_like(wsel))
    return pt.tree_weighted_mean(chosen, wsel)


def geometric_median(stacked, weights: jax.Array, iters: int = 8):
    """Weighted geometric median via ``iters`` fixed Weiszfeld steps
    (``lax.fori_loop`` — trace-static, so the fused K-round scan stays
    one compiled program). Initialized at the weighted mean; an
    eps-guarded reweighting 1/max(dist, eps) keeps iterates finite when
    the estimate lands on a client point."""
    w = weights.astype(jnp.float32)
    w = jnp.where(jnp.sum(w) > 0, w, jnp.ones_like(w))
    z0 = pt.tree_weighted_mean(stacked, w)

    def step(_, z):
        d2 = jax.vmap(
            lambda u: pt.tree_dot(pt.tree_sub(u, z), pt.tree_sub(u, z))
        )(stacked)
        beta = w / jnp.maximum(jnp.sqrt(jnp.maximum(d2, 0.0)), 1e-8)
        return pt.tree_weighted_mean(stacked, beta)

    return jax.lax.fori_loop(0, int(iters), step, z0)


def effective_defense(defense: str, n: int, f: int,
                      warn: Callable | None = None) -> str:
    """The defense a cohort of ``n`` clients can actually run: when an
    order-statistic defense's breakdown requirement fails over ``n``
    (fault-schedule crashes or a clamped sampling frac can shrink a
    round's cohort below what the STARTUP check validated — krum needs
    n >= f+3, trim/median need 2f < n), fall back to ``"none"`` with a
    warning rather than dying mid-run — the same availability choice
    the cross-silo server makes for deadline-truncated survivor sets.
    ``n`` is trace-static (the stacked client axis), so this resolves
    at trace time, once per cohort size."""
    if defense not in ROBUST_AGGREGATORS:
        return defense
    try:
        _check_f(n, f, defense)
    except ValueError as e:
        if warn is not None:
            warn("defense %s infeasible over this round's %d-client "
                 "cohort (%s) - falling back to the plain weighted "
                 "mean for rounds at this cohort size", defense, n, e)
        return "none"
    if defense in ("krum", "multi_krum") and n < 2 * f + 3 \
            and warn is not None:
        warn("%s over a %d-client cohort with byz_f=%d is below the "
             "provable Blanchard bound n >= 2f+3: the selection runs, "
             "but %d COLLUDING attackers (mutual distance 0) can win "
             "it — treat the guarantee as empirical at this size",
             defense, n, f, f)
    return defense


def robust_aggregate(stacked, weights: jax.Array, *, defense: str,
                     byz_f: int, geomed_iters: int = 8):
    """Dispatch one order-statistic aggregator over a stacked update
    tree. ``defense`` must be in ``ROBUST_AGGREGATORS`` (the clip family
    and ``none`` go through ``defend_stacked`` + a weighted mean — see
    ``aggregate_with_defense``)."""
    if defense == "trimmed_mean":
        return trimmed_mean(stacked, weights, byz_f)
    if defense == "median":
        _check_f(_client_count(stacked), byz_f, "median")
        return coordinate_median(stacked, weights)
    if defense == "krum":
        return krum(stacked, weights, byz_f, multi=False)
    if defense == "multi_krum":
        return krum(stacked, weights, byz_f, multi=True)
    if defense == "geometric_median":
        return geometric_median(stacked, weights, iters=geomed_iters)
    validate_defense(defense)
    raise ValueError(
        f"defense {defense!r} is not an order-statistic aggregator; "
        f"have {ROBUST_AGGREGATORS}")


def aggregate_with_defense(stacked, reference, weights: jax.Array, *,
                           defense: str, norm_bound: float = 5.0,
                           stddev: float = 0.0, rngs=None, byz_f: int = 1,
                           geomed_iters: int = 8,
                           mean_fn: Callable | None = None):
    """THE defended-aggregation entry: clip-family defenses transform
    per client then reduce with ``mean_fn`` (default
    ``tree_weighted_mean``; engines pass their silo-aware ``aggregate``),
    order-statistic defenses consume the stacked tree whole. Trace-safe —
    the engines call this inside their jitted round bodies, the
    cross-silo server from a host-level jit."""
    validate_defense(defense)
    if defense in ROBUST_AGGREGATORS:
        return robust_aggregate(stacked, weights, defense=defense,
                                byz_f=byz_f, geomed_iters=geomed_iters)
    defended = defend_stacked(stacked, reference, defense=defense,
                              norm_bound=norm_bound, stddev=stddev,
                              rngs=rngs)
    fn = mean_fn if mean_fn is not None else pt.tree_weighted_mean
    return fn(defended, weights)
