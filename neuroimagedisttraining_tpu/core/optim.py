"""Local optimizers with torch-parity semantics.

The reference builds ``torch.optim.SGD(lr=args.lr * args.lr_decay**round,
momentum, weight_decay)`` fresh each round and clips gradients to global-norm
10 before the step (my_model_trainer.py:209, 224-225). Torch SGD applies lr
AFTER the momentum accumulation: ``buf = m*buf + (g + wd*p); p -= lr*buf``.
We reproduce that exactly by running the optax chain at unit lr and scaling
the final update by the per-round lr — so lr can be a traced scalar argument
of the jitted round program instead of a fresh optimizer object.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from neuroimagedisttraining_tpu.config import OptimConfig


class LocalOptimizer(NamedTuple):
    init: object   # params -> opt_state
    update: object  # (grads, opt_state, params, lr) -> (updates, opt_state)


def make_local_optimizer(cfg: OptimConfig) -> LocalOptimizer:
    if cfg.client_optimizer == "sgd":
        tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip) if cfg.grad_clip > 0
            else optax.identity(),
            optax.add_decayed_weights(cfg.wd) if cfg.wd > 0 else optax.identity(),
            optax.trace(decay=cfg.momentum) if cfg.momentum > 0
            else optax.identity(),
        )
    elif cfg.client_optimizer == "adam":
        tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip) if cfg.grad_clip > 0
            else optax.identity(),
            optax.scale_by_adam(),
            optax.add_decayed_weights(cfg.wd) if cfg.wd > 0 else optax.identity(),
        )
    else:
        raise ValueError(f"unknown client_optimizer {cfg.client_optimizer!r}")

    def init(params):
        return tx.init(params)

    def update(grads, opt_state, params, lr):
        updates, opt_state = tx.update(grads, opt_state, params)
        updates = jax.tree.map(lambda u: -lr * u, updates)
        return updates, opt_state

    return LocalOptimizer(init=init, update=update)


def round_lr(cfg: OptimConfig, round_idx) -> jax.Array:
    """Per-round exponential decay: lr * lr_decay**round
    (my_model_trainer.py:209)."""
    return jnp.asarray(cfg.lr, jnp.float32) * (
        jnp.asarray(cfg.lr_decay, jnp.float32) ** round_idx)
