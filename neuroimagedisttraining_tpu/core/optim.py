"""Local optimizers with torch-parity semantics + the precision contract.

The reference builds ``torch.optim.SGD(lr=args.lr * args.lr_decay**round,
momentum, weight_decay)`` fresh each round and clips gradients to global-norm
10 before the step (my_model_trainer.py:209, 224-225). Torch SGD applies lr
AFTER the momentum accumulation: ``buf = m*buf + (g + wd*p); p -= lr*buf``.
We reproduce that exactly by running the optax chain at unit lr and scaling
the final update by the per-round lr — so lr can be a traced scalar argument
of the jitted round program instead of a fresh optimizer object.

Precision contract (ISSUE 10): ``OptimConfig.precision`` picks the train
step's COMPUTE dtype only. Under ``bf16_mixed`` the flax modules run conv /
matmul / norm in bfloat16 (``dtype=bf16``) while every parameter, momentum
buffer, and the loss stay float32 — flax's ``param_dtype`` default keeps
master weights f32 and casts per-apply, the models cast logits back to f32,
and the optimizer below therefore always sees f32 grads against f32 params.
Everything outside the jitted step (FedAvg aggregation, the wire codec,
secure aggregation, checkpoints) sees ONLY the f32 master weights. A fixed
``loss_scale`` constant (static scaling — scale the loss before grad, divide
the f32 grads after) is available for underflow-prone models; it is pinned
to 1.0 under fp32 so the plain-f32 path stays bitwise-identical.

``fused_update=True`` routes the SGD tail (global-norm clip -> weight decay
-> momentum -> lr-scaled update -> mask re-apply) through the fused kernel
in ops/fused_update.py — one HBM pass instead of one per stage — with the
optax chain's exact arithmetic (bit-parity pinned in tests/test_precision).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from neuroimagedisttraining_tpu.config import OptimConfig

#: legal ``OptimConfig.precision`` values, in contract order
PRECISIONS = ("fp32", "bf16_mixed")

#: ``--remat auto`` activation budget by precision: the max samples in
#: flight per device before stem remat arms. The fp32 cutoff (128) is
#: the measured activation-bytes knee on the harness box; under
#: bf16_mixed the conv/matmul activations are stored in bfloat16 —
#: half the bytes per sample — so the same HBM headroom carries 2x the
#: samples before recompute pays for itself (ISSUE 19 satellite; the
#: ratio is pinned in tests/test_tune.py).
REMAT_AUTO_SAMPLES = {"fp32": 128, "bf16_mixed": 256}


def remat_auto_samples_threshold(precision: str) -> int:
    """Samples-in-flight-per-device cutoff above which ``--remat auto``
    resolves to stem remat, for this precision policy."""
    validate_precision_name(precision)
    return REMAT_AUTO_SAMPLES[precision]


def compute_dtype(precision: str):
    """The flax module ``dtype`` a precision policy compiles to (master
    weights stay float32 either way — flax ``param_dtype`` default)."""
    validate_precision_name(precision)
    return jnp.bfloat16 if precision == "bf16_mixed" else jnp.float32


def validate_precision_name(precision: str) -> None:
    if precision not in PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r}; choose one of {PRECISIONS}")


def validate_precision(cfg: OptimConfig) -> None:
    """The whole-config precision contract, enforced at trainer build so a
    bad combination dies at startup, not at first trace:

    - ``precision`` must be a known policy;
    - ``loss_scale`` must be positive and finite (it divides gradients);
    - ``loss_scale != 1`` requires ``bf16_mixed`` — under fp32 the scale
      pair would perturb rounding and silently break the bitwise-
      unchanged-fp32 pin the whole plan rests on;
    - ``fused_update`` exists for the SGD chain only (the adam path has
      no fused kernel; training un-fused while the flag claimed fusion
      would corrupt any bench comparing the two)."""
    import math

    validate_precision_name(cfg.precision)
    scale = float(cfg.loss_scale)
    if not (scale > 0 and math.isfinite(scale)):
        raise ValueError(f"loss_scale must be a positive finite constant "
                         f"(got {cfg.loss_scale!r})")
    if scale != 1.0 and cfg.precision != "bf16_mixed":
        raise ValueError(
            f"loss_scale={cfg.loss_scale} needs precision=bf16_mixed: "
            "under fp32 the scale/unscale pair would only perturb "
            "rounding and break the bitwise-f32 contract")
    if cfg.fused_update and cfg.client_optimizer != "sgd":
        raise ValueError(
            "--fused_update fuses the SGD clip/momentum/update tail "
            f"(ops/fused_update.py); client_optimizer="
            f"{cfg.client_optimizer!r} has no fused kernel and would "
            "silently train un-fused")


class LocalOptimizer(NamedTuple):
    init: object   # params -> opt_state
    update: object  # (grads, opt_state, params, lr) -> (updates, opt_state)
    #: fused one-pass apply (ops/fused_update.py), or None when the
    #: config keeps the unfused optax chain:
    #: (grads, opt_state, params, lr, mask|None) -> (params, opt_state)
    fused_apply: object | None = None


def make_local_optimizer(cfg: OptimConfig) -> LocalOptimizer:
    if cfg.client_optimizer == "sgd":
        tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip) if cfg.grad_clip > 0
            else optax.identity(),
            optax.add_decayed_weights(cfg.wd) if cfg.wd > 0 else optax.identity(),
            optax.trace(decay=cfg.momentum) if cfg.momentum > 0
            else optax.identity(),
        )
    elif cfg.client_optimizer == "adam":
        tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip) if cfg.grad_clip > 0
            else optax.identity(),
            optax.scale_by_adam(),
            optax.add_decayed_weights(cfg.wd) if cfg.wd > 0 else optax.identity(),
        )
    else:
        raise ValueError(f"unknown client_optimizer {cfg.client_optimizer!r}")

    def init(params):
        return tx.init(params)

    def update(grads, opt_state, params, lr):
        updates, opt_state = tx.update(grads, opt_state, params)
        updates = jax.tree.map(lambda u: -lr * u, updates)
        return updates, opt_state

    fused_apply = None
    if cfg.fused_update and cfg.client_optimizer == "sgd":
        from neuroimagedisttraining_tpu.ops import fused_update as fu

        has_trace = cfg.momentum > 0

        def fused_apply(grads, opt_state, params, lr, mask=None):
            # the chain state is always a 3-tuple (identity substitutes
            # keep the arity); slot 2 is the TraceState when momentum>0
            trace = opt_state[2].trace if has_trace else None
            new_params, new_trace = fu.fused_sgd_step(
                params, grads, trace, mask, clip=cfg.grad_clip,
                wd=cfg.wd, momentum=cfg.momentum, lr=lr)
            if has_trace:
                opt_state = (opt_state[0], opt_state[1],
                             optax.TraceState(trace=new_trace))
            return new_params, opt_state

    return LocalOptimizer(init=init, update=update, fused_apply=fused_apply)


def round_lr(cfg: OptimConfig, round_idx) -> jax.Array:
    """Per-round exponential decay: lr * lr_decay**round
    (my_model_trainer.py:209)."""
    return jnp.asarray(cfg.lr, jnp.float32) * (
        jnp.asarray(cfg.lr_decay, jnp.float32) ** round_idx)
