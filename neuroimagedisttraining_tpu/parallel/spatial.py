"""Spatial (voxel) sharding: the context-parallelism analog for volumes.

The reference has no sequence models, so ring attention / sequence
parallelism has no direct counterpart (SURVEY §5.7); its scaling axes are
clients and volume size. This module supplies the volume-size axis: a 3D
convolution whose DEPTH dimension is sharded across a mesh axis, with halo
exchange over ICI (`lax.ppermute` inside `shard_map`) — structurally the
same neighbor-exchange pattern ring attention uses for KV blocks, applied
to conv receptive fields. With it, a volume too large for one chip's HBM
(or a future higher-resolution cohort) can be partitioned D-wise across
the mesh while every shard computes only its local rows.

Scope: stride-1 'SAME' convolutions (the shape-preserving f2/f3/f4 stages
of AlexNet3D). Strided stems and pools mix shard boundaries with stride
phase and are left to XLA's own SPMD partitioner when whole-model spatial
sharding is wanted; this module is the hand-rolled building block + parity
proof (tests/test_spatial.py: matches the unsharded conv to float32
accumulation tolerance (1e-5) on an 8-device CPU mesh).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

SPACE_AXIS = "space"


def make_space_mesh(num_devices: int | None = None) -> Mesh:
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh

    return make_mesh(num_devices=num_devices, axis_name=SPACE_AXIS)


def _axis_size(axis_name: str) -> int:
    """``lax.axis_size`` exists only on jax >= 0.5; under the pinned
    0.4.x toolchain the axis env lookup returns the size directly."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax import core

    return core.axis_frame(axis_name)


def _halo_exchange(x: jax.Array, halo: int, axis_name: str) -> jax.Array:
    """Concatenate each shard's D-block with ``halo`` rows from both
    neighbors (zeros at the global volume edges).

    x: [B, D_local, H, W, C] (one shard's rows). Ring ppermutes move the
    boundary rows over ICI; the first/last shards mask their missing
    neighbor with zero padding — exactly 'SAME' conv semantics.
    """
    if halo == 0:  # 1-wide depth kernel: nothing to exchange (x[:, -0:]
        return x   # would select the WHOLE block, doubling the depth)
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # receive the LAST `halo` rows of the left neighbor (shift right)
    from_left = lax.ppermute(x[:, -halo:], axis_name,
                             perm=[(i, (i + 1) % n) for i in range(n)])
    # receive the FIRST `halo` rows of the right neighbor (shift left)
    from_right = lax.ppermute(x[:, :halo], axis_name,
                              perm=[(i, (i - 1) % n) for i in range(n)])
    from_left = jnp.where(idx == 0, jnp.zeros_like(from_left), from_left)
    from_right = jnp.where(idx == n - 1, jnp.zeros_like(from_right),
                           from_right)
    return jnp.concatenate([from_left, x, from_right], axis=1)


def spatial_sharded_conv3d(x: jax.Array, kernel: jax.Array, mesh: Mesh,
                           bias: jax.Array | None = None) -> jax.Array:
    """Stride-1 'SAME' Conv3D with the depth axis sharded over ``mesh``.

    x: [B, D, H, W, Cin] with D divisible by the mesh size; kernel:
    [kd, kh, kw, Cin, Cout] with odd kd. Returns [B, D, H, W, Cout],
    matching the unsharded lax conv to f32 accumulation tolerance.
    """
    kd, kh, kw = kernel.shape[:3]
    assert kd % 2 == 1 and kh % 2 == 1 and kw % 2 == 1, (
        "all kernel dims must be odd for SAME semantics")
    halo = kd // 2
    n = mesh.devices.size
    assert x.shape[1] % n == 0, (
        f"depth {x.shape[1]} not divisible by mesh size {n}")
    assert x.shape[1] // n >= halo, (
        "each shard must hold at least `halo` rows")

    def block(xb, kb, bb):
        xx = _halo_exchange(xb, halo, SPACE_AXIS)
        out = lax.conv_general_dilated(
            xx, kb, window_strides=(1, 1, 1),
            padding=[(0, 0), (kh // 2, kh // 2), (kw // 2, kw // 2)],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if bb is not None:
            out = out + bb
        return out

    spec_x = P(None, SPACE_AXIS)            # shard D, replicate the rest
    spec_k = P()
    fn = shard_map(block, mesh=mesh,
                   in_specs=(spec_x, spec_k, spec_k),
                   out_specs=spec_x)
    return fn(x, kernel, bias)
