from neuroimagedisttraining_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    client_sharding,
    replicated_sharding,
    shard_federation,
)
from neuroimagedisttraining_tpu.parallel import topology  # noqa: F401
