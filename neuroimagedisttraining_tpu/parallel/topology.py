"""Decentralized-FL topology managers: mixing-weight matrices for gossip.

Semantics ported from fedml_core/distributed/topology/
symmetric_topology_manager.py:21-52 and asymmetric_topology_manager.py:23-75:
a ring lattice (Watts-Strogatz with rewiring p=0) unioned with a k-neighbor
lattice, self-loops added, rows normalized by degree. Implemented directly in
numpy (no networkx): WS(p=0) is a circulant ring lattice, each node linked to
``k//2`` nearest neighbors per side.

On device, one gossip/consensus step over the client-sharded federation is
``einsum("ij,j...->i...", W, params)`` — an all-to-all matmul over the mesh —
or ``lax.ppermute`` ring steps for the pure-ring case (D-PSGD, DisPFL).
"""

from __future__ import annotations

import numpy as np


def ring_lattice(n: int, k: int) -> np.ndarray:
    """Adjacency of a circulant lattice: node i ~ i±1..i±(k//2) (mod n)."""
    adj = np.zeros((n, n), dtype=np.float32)
    half = max(1, k // 2) if n > 1 else 0
    for off in range(1, half + 1):
        for i in range(n):
            adj[i, (i + off) % n] = 1.0
            adj[i, (i - off) % n] = 1.0
    return adj


class BaseTopologyManager:
    """Interface parity with base_topology_manager.py:4-24."""

    topology: np.ndarray

    def generate_topology(self):
        raise NotImplementedError

    def get_in_neighbor_weights(self, node_index: int):
        return self.topology[:, node_index]

    def get_out_neighbor_weights(self, node_index: int):
        return self.topology[node_index]

    def get_in_neighbor_idx_list(self, node_index: int) -> list[int]:
        w = np.asarray(self.get_in_neighbor_weights(node_index))
        return [i for i in range(len(w)) if w[i] > 0 and i != node_index]

    def get_out_neighbor_idx_list(self, node_index: int) -> list[int]:
        w = np.asarray(self.get_out_neighbor_weights(node_index))
        return [i for i in range(len(w)) if w[i] > 0 and i != node_index]

    def mixing_matrix(self) -> np.ndarray:
        return self.topology


class SymmetricTopologyManager(BaseTopologyManager):
    """Ring ∪ k-lattice, self-loops, row-normalized (doubly stochastic for
    these symmetric circulants). Parity: symmetric_topology_manager.py:16-52."""

    def __init__(self, n: int, neighbor_num: int = 2):
        self.n = n
        self.neighbor_num = neighbor_num
        self.topology = np.zeros((n, n), np.float32)

    def generate_topology(self):
        adj = ring_lattice(self.n, 2)
        adj = np.maximum(adj, ring_lattice(self.n, int(self.neighbor_num)))
        np.fill_diagonal(adj, 1.0)
        self.topology = adj / adj.sum(axis=1, keepdims=True)
        return self.topology


class AsymmetricTopologyManager(BaseTopologyManager):
    """Symmetric base graph plus randomly added directed links, rows
    normalized. Parity: asymmetric_topology_manager.py:17-75 (including its
    use of the global numpy RNG for link selection — pass ``rng`` for
    reproducibility instead)."""

    def __init__(self, n: int, undirected_neighbor_num: int = 3,
                 out_directed_neighbor: int = 3, rng: np.random.Generator | None = None):
        self.n = n
        self.undirected_neighbor_num = undirected_neighbor_num
        self.out_directed_neighbor = out_directed_neighbor
        self._rng = rng or np.random.default_rng()  # nidt: allow[determinism-unseeded-rng] -- parity: the reference draws links from an unseeded stream; callers inject a seeded rng for reproducible topologies
        self.topology = np.zeros((n, n), np.float32)

    def generate_topology(self):
        adj = ring_lattice(self.n, 2)
        adj = np.maximum(adj, ring_lattice(self.n, self.undirected_neighbor_num))
        np.fill_diagonal(adj, 1.0)
        # Randomly promote ~half of the remaining zero entries to directed
        # links, skipping entries whose reverse was already added
        # (asymmetric_topology_manager.py:45-61).
        added = set()
        for i in range(self.n):
            zeros = np.where(adj[i] == 0)[0]
            picks = self._rng.integers(0, 2, size=len(zeros))
            for j, p in zip(zeros, picks):
                if p == 1 and (j * self.n + i) not in added:
                    adj[i, j] = 1.0
                    added.add(i * self.n + j)
        self.topology = adj / adj.sum(axis=1, keepdims=True)
        return self.topology


def ring_mixing_matrix(n: int) -> np.ndarray:
    """Plain ring consensus weights (each row: self + 2 neighbors, 1/3)."""
    adj = ring_lattice(n, 2)
    np.fill_diagonal(adj, 1.0)
    return adj / adj.sum(axis=1, keepdims=True)


def full_mixing_matrix(n: int) -> np.ndarray:
    return np.full((n, n), 1.0 / n, dtype=np.float32)
