"""Sparse gossip consensus: ``lax.ppermute`` rings and routed all-to-all.

The decentralized engines' consensus is ``einsum("cj,j...->c...", M, x)`` —
an all-gather that materializes the full C-stacked model per device and is
the scaling wall at the 100-client north star. Two sparse lowerings
replace it whenever the round's mixing matrix allows:

1. CIRCULANT (``circulant_plan`` / ``gossip_apply``): the ring /
   Watts-Strogatz k-lattice topologies the reference ships
   (fedml_core/distributed/topology/symmetric_topology_manager.py:21-52,
   dpsgd_api.py:116-139 cs="ring") give ``M[c, j] = base[(j - c) mod C]``,
   so the consensus is a handful of weighted client-axis rotations, each a
   ``lax.ppermute`` of a |k|-row slice. Per-device traffic O(k_max *
   model), independent of C. The rotation offsets are part of the compiled
   program — fine, because ring plans are round-invariant.

2. GENERAL SPARSE (``sparse_plan`` / ``gossip_apply_sparse``): the
   reference's DisPFL default and dpsgd ``cs="random"`` draw a NEW
   k-regular random adjacency every round (dispfl_api.py:200,
   dpsgd_api.py:116-139), so any lowering whose communication pattern is
   baked into the program would retrace per round. The TPU-native answer
   is a capped ``lax.all_to_all`` with TRACED routing tables: each device
   sends, per destination, just the (deduplicated) client rows that
   destination's clients actually reference, padded to a static per-pair
   cap ``m``; receivers reassemble their neighbor rows by a local gather.
   The routing tables (send indices, gather indices, weights) are runtime
   OPERANDS, so one compiled program serves every round whose size bucket
   matches — per-device traffic O(D * m * model) with
   ``m ~ B * (k+1) / D`` rows (B = clients per device), vs the einsum's
   O(C * model), and peak memory O(D * m) instead of the gathered
   O(C) stack. ``sparse_plan`` returns None when the pattern is dense
   enough that the einsum is no better (m would equal B).

Plan detection runs on the host per round (cheap: O(C^2) compares /
O(C * k) bucketing); engines fall back to the dense einsum whenever
neither structure applies — behavior is identical either way, only the
lowering differs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:
    from jax.experimental.shard_map import shard_map

from neuroimagedisttraining_tpu.parallel.mesh import CLIENT_AXIS

#: plan entry: (signed client-axis offset, mixing weight)
Plan = tuple[tuple[int, float], ...]


def circulant_plan(M: np.ndarray, tol: float = 0.0) -> Plan | None:
    """``((offset, weight), ...)`` when ``M`` is circulant, else None.

    Offsets are signed (shortest direction around the ring) and sorted, so
    equal matrices always produce the same (hashable) plan — engines key
    their jit caches on it."""
    M = np.asarray(M)
    C = M.shape[0]
    if M.ndim != 2 or M.shape[1] != C or C == 0:
        return None
    base = M[0]
    for i in range(1, C):
        if not (np.abs(M[i] - np.roll(base, i)) <= tol).all():
            return None
    plan = []
    for j in np.flatnonzero(base):
        k = int(j) if j <= C // 2 else int(j) - C
        plan.append((k, float(base[j])))
    return tuple(sorted(plan))


def plan_fits_mesh(plan: Plan, mesh, num_clients: int) -> bool:
    """A plan lowers to single-hop ppermutes iff the mesh is the 1-D
    client mesh, the client axis tiles it, and every offset stays within
    one device block."""
    if mesh is None or plan is None:
        return False
    if tuple(mesh.axis_names) != (CLIENT_AXIS,):
        return False
    D = mesh.devices.size
    if D < 2 or num_clients % D != 0:
        return False
    block = num_clients // D
    return all(abs(k) <= block for k, _ in plan)


def _rolled(blk: jax.Array, k: int, D: int) -> jax.Array:
    """This device's rows of the client-axis rotation
    ``rolled[i] = x[(i + k) mod C]``: a |k|-row ppermute from the
    neighboring device plus a local slice-concat."""
    if k == 0:
        return blk
    B = blk.shape[0]
    if k > 0:
        # rows [k:] are local; the tail comes from the NEXT device's head
        recv = jax.lax.ppermute(blk[:k], CLIENT_AXIS,
                                [((d + 1) % D, d) for d in range(D)])
        return jnp.concatenate([blk[k:], recv], axis=0)
    kk = -k
    # rows [:B-kk] are local (shifted); the head comes from the PREVIOUS
    # device's tail
    recv = jax.lax.ppermute(blk[B - kk:], CLIENT_AXIS,
                            [((d - 1) % D, d) for d in range(D)])
    return jnp.concatenate([recv, blk[:B - kk]], axis=0)


def gossip_apply(tree, plan: Plan, mesh):
    """Circulant consensus of a client-stacked pytree via ppermute shifts.

    Equivalent to ``einsum("cj,j...->c...", M, x)`` (float32 accumulate,
    cast back) for the circulant ``M`` that produced ``plan``, but lowers
    to collective-permutes of |k|-row slices instead of an all-to-all."""
    from jax.sharding import PartitionSpec

    if plan is None:
        # None is the "not circulant" sentinel from circulant_plan — the
        # caller should have taken the dense einsum path; silently gossiping
        # nothing here would return an all-zero consensus for a matrix that
        # is NOT all-zero
        raise ValueError(
            "gossip_apply(plan=None): None means 'not circulant, use the "
            "dense einsum path'; only an actual Plan tuple is accepted")
    if not jax.tree.leaves(tree):  # e.g. batch_stats of a GroupNorm model
        return tree
    if plan == ():
        # an all-zero matrix is (trivially) circulant and yields an empty
        # plan; the consensus it defines is identically zero — match the
        # einsum path instead of tripping over an empty accumulation
        return jax.tree.map(jnp.zeros_like, tree)
    D = mesh.devices.size
    specs = jax.tree.map(
        lambda x: PartitionSpec(CLIENT_AXIS, *([None] * (x.ndim - 1))),
        tree)

    def block_fn(blk_tree):
        def one(blk):
            b32 = blk.astype(jnp.float32)
            acc = None
            for k, w in plan:
                term = w * _rolled(b32, k, D)
                acc = term if acc is None else acc + term
            return acc.astype(blk.dtype)

        return jax.tree.map(one, blk_tree)

    return shard_map(block_fn, mesh=mesh, in_specs=(specs,),
                     out_specs=specs)(tree)


def make_plan(M: np.ndarray, mesh, num_clients: int):
    """``(plan, plan_arrays)`` for a round's mixing/adjacency matrix — the
    shared circulant -> sparse -> dense cascade used by the decentralized
    engines: a hashable circulant Plan tuple (ppermute shifts) when the
    matrix is circulant and tiles the mesh, a SparseSpec + traced routing
    arrays (routed all_to_all) for sparse patterns, else ``(None, {})``
    for the dense einsum."""
    plan = circulant_plan(M)
    if plan_fits_mesh(plan, mesh, num_clients):
        return plan, {}
    sp = sparse_plan(M, mesh, num_clients)
    if sp is not None:
        return sp
    return None, {}


# ---------- general sparse (per-round random) topologies ----------


@dataclasses.dataclass(frozen=True)
class SparseSpec:
    """Static (hashable, jit-cache-keying) part of a sparse gossip plan.

    ``m`` is bucketed to quarters of B so a config's rounds share a
    handful of compiled programs even though the random topology (and
    therefore the traced routing tables) changes every round; ``n_max``
    is the per-round max row support, which is constant for a fixed
    (k, activity) config."""
    D: int       # devices on the 1-D client mesh
    B: int       # clients per device (C // D)
    m: int       # padded per-(src, dst) slot count for the all_to_all
    n_max: int   # padded per-client neighbor count for the local gather


def _bucket(n: int, q: int) -> int:
    """Round n up to the next multiple of q (n >= 1)."""
    n = max(n, 1)
    return ((n + q - 1) // q) * q


def sparse_plan(M: np.ndarray, mesh, num_clients: int
                ) -> tuple[SparseSpec, dict[str, np.ndarray]] | None:
    """Routing plan for an arbitrary sparse mixing matrix on the 1-D
    client mesh, or None when the einsum is no worse (pattern dense
    enough that some device pair would exchange its full block).

    Returns ``(spec, arrays)``:
    - ``arrays["send_idx"]`` [D, D, m] int32 — device s's slot for
      destination d holds LOCAL row indices (deduplicated, ascending),
      padded with 0 (padding rows are sent but never gathered).
    - ``arrays["gather_idx"]`` [C, n_max] int32 — per client, positions
      into the receiver's pool = concat(all-to-all result [D*m], local
      block [B]), neighbor terms in ascending global-j order (matching
      the einsum's reduction order), padded with 0.
    - ``arrays["gather_w"]`` [C, n_max] float32 — matching weights,
      padding 0.
    """
    M = np.asarray(M)
    C = M.shape[0]
    if M.ndim != 2 or M.shape[1] != C or C == 0:
        return None
    if mesh is None or tuple(mesh.axis_names) != (CLIENT_AXIS,):
        return None
    D = mesh.devices.size
    if D < 2 or num_clients % D != 0 or C != num_clients:
        return None
    B = C // D

    rows = [np.flatnonzero(M[c]) for c in range(C)]
    n_actual = max((len(r) for r in rows), default=0)
    # send sets: per ordered device pair (s != d), the deduplicated local
    # rows of s referenced by any client of d
    need: list[list[set]] = [[set() for _ in range(D)] for _ in range(D)]
    for c in range(C):
        d = c // B
        for j in rows[c]:
            s = int(j) // B
            if s != d:
                need[s][d].add(int(j) - s * B)
    m_actual = max((len(need[s][d]) for s in range(D) for d in range(D)),
                   default=0)
    # bucket to quarters of B (bounded program count per config); the plan
    # only pays off when the padded per-pair slots stay strictly below a
    # full block — at m == B the all_to_all moves the all-gather volume
    # (that covers B == 1 too: one-client-per-device random gossip has no
    # sparse win, every row is a full block)
    m = _bucket(m_actual, max(1, B // 4))
    if m >= B:
        return None
    n_max = min(max(n_actual, 1), C)

    send_idx = np.zeros((D, D, m), np.int32)
    slot: dict[tuple[int, int, int], int] = {}
    for s in range(D):
        for d in range(D):
            for i, r in enumerate(sorted(need[s][d])):
                send_idx[s, d, i] = r
                slot[(s, d, r)] = i
    gather_idx = np.zeros((C, n_max), np.int32)
    gather_w = np.zeros((C, n_max), np.float32)
    for c in range(C):
        d = c // B
        for i, j in enumerate(rows[c]):  # ascending j == einsum order
            s = int(j) // B
            if s == d:
                gather_idx[c, i] = D * m + (int(j) - d * B)
            else:
                gather_idx[c, i] = s * m + slot[(s, d, int(j) - s * B)]
            gather_w[c, i] = M[c, j]
    spec = SparseSpec(D=D, B=B, m=m, n_max=n_max)
    return spec, {"send_idx": send_idx, "gather_idx": gather_idx,
                  "gather_w": gather_w}


def gossip_apply_sparse(tree, spec: SparseSpec, arrays, mesh):
    """Sparse consensus of a client-stacked pytree via one routed
    ``lax.all_to_all`` + local gathers.

    Equivalent to ``einsum("cj,j...->c...", M, x)`` (float32 accumulate in
    ascending-j order, cast back) for the ``M`` that produced the plan;
    per-device traffic D*m rows instead of the einsum's C-row all-gather.
    ``arrays`` are traced operands — one compiled program per SparseSpec
    bucket, reused across rounds of changing random topologies."""
    from jax.sharding import PartitionSpec

    if not jax.tree.leaves(tree):  # e.g. batch_stats of a GroupNorm model
        return tree
    D, B, m, n_max = spec.D, spec.B, spec.m, spec.n_max
    specs = jax.tree.map(
        lambda x: PartitionSpec(CLIENT_AXIS, *([None] * (x.ndim - 1))),
        tree)
    vec = PartitionSpec(CLIENT_AXIS)

    def block_fn(blk_tree, send_blk, gidx_blk, gw_blk):
        # send_blk [1, D, m]; gidx_blk/gw_blk [B, n_max]
        def one(blk):
            b32 = blk.astype(jnp.float32)
            S = b32[send_blk[0]]                         # [D, m, ...]
            R = jax.lax.all_to_all(S, CLIENT_AXIS, 0, 0, tiled=True)
            pool = jnp.concatenate(
                [R.reshape((D * m,) + b32.shape[1:]), b32], axis=0)
            G = pool[gidx_blk]                           # [B, n_max, ...]
            w = gw_blk.reshape((B, n_max) + (1,) * (b32.ndim - 1))
            return jnp.sum(w * G, axis=1).astype(blk.dtype)

        return jax.tree.map(one, blk_tree)

    return shard_map(
        block_fn, mesh=mesh,
        in_specs=(specs, vec, vec, vec), out_specs=specs,
    )(tree, jnp.asarray(arrays["send_idx"]),
      jnp.asarray(arrays["gather_idx"]), jnp.asarray(arrays["gather_w"]))
