"""Sparse gossip consensus over ``lax.ppermute`` (ring / k-lattice).

The decentralized engines' consensus is ``einsum("cj,j...->c...", M, x)`` —
an all-to-all that materializes the full C-stacked model per device and is
the scaling wall at the 100-client north star. For the ring/Watts-Strogatz
topologies the reference actually ships
(fedml_core/distributed/topology/symmetric_topology_manager.py:21-52,
dpsgd_api.py:116-139 cs="ring"), the mixing matrix is CIRCULANT:
``M[c, j] = base[(j - c) mod C]``, so the consensus is a handful of
weighted client-axis rotations:

    y_c = sum_k base[k] * x_{(c+k) mod C}

Each rotation by ``k`` moves only ``|k|`` client rows between neighboring
devices — a ``lax.ppermute`` (collective-permute over ICI) of a k-row
slice plus a local concat, NOT a full-stack all-gather. Per-device traffic
drops from O(C * model) to O(k_max * model), independent of C.

``circulant_plan`` detects the structure on the host (per round, cheap:
C^2 compares); engines fall back to the dense einsum whenever the matrix
is not circulant (random neighbor draws, partial activity, padded client
rows) — behavior is identical either way, only the lowering differs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.parallel.mesh import CLIENT_AXIS

#: plan entry: (signed client-axis offset, mixing weight)
Plan = tuple[tuple[int, float], ...]


def circulant_plan(M: np.ndarray, tol: float = 0.0) -> Plan | None:
    """``((offset, weight), ...)`` when ``M`` is circulant, else None.

    Offsets are signed (shortest direction around the ring) and sorted, so
    equal matrices always produce the same (hashable) plan — engines key
    their jit caches on it."""
    M = np.asarray(M)
    C = M.shape[0]
    if M.ndim != 2 or M.shape[1] != C or C == 0:
        return None
    base = M[0]
    for i in range(1, C):
        if not (np.abs(M[i] - np.roll(base, i)) <= tol).all():
            return None
    plan = []
    for j in np.flatnonzero(base):
        k = int(j) if j <= C // 2 else int(j) - C
        plan.append((k, float(base[j])))
    return tuple(sorted(plan))


def plan_fits_mesh(plan: Plan, mesh, num_clients: int) -> bool:
    """A plan lowers to single-hop ppermutes iff the mesh is the 1-D
    client mesh, the client axis tiles it, and every offset stays within
    one device block."""
    if mesh is None or plan is None:
        return False
    if tuple(mesh.axis_names) != (CLIENT_AXIS,):
        return False
    D = mesh.devices.size
    if D < 2 or num_clients % D != 0:
        return False
    block = num_clients // D
    return all(abs(k) <= block for k, _ in plan)


def _rolled(blk: jax.Array, k: int, D: int) -> jax.Array:
    """This device's rows of the client-axis rotation
    ``rolled[i] = x[(i + k) mod C]``: a |k|-row ppermute from the
    neighboring device plus a local slice-concat."""
    if k == 0:
        return blk
    B = blk.shape[0]
    if k > 0:
        # rows [k:] are local; the tail comes from the NEXT device's head
        recv = jax.lax.ppermute(blk[:k], CLIENT_AXIS,
                                [((d + 1) % D, d) for d in range(D)])
        return jnp.concatenate([blk[k:], recv], axis=0)
    kk = -k
    # rows [:B-kk] are local (shifted); the head comes from the PREVIOUS
    # device's tail
    recv = jax.lax.ppermute(blk[B - kk:], CLIENT_AXIS,
                            [((d - 1) % D, d) for d in range(D)])
    return jnp.concatenate([recv, blk[:B - kk]], axis=0)


def gossip_apply(tree, plan: Plan, mesh):
    """Circulant consensus of a client-stacked pytree via ppermute shifts.

    Equivalent to ``einsum("cj,j...->c...", M, x)`` (float32 accumulate,
    cast back) for the circulant ``M`` that produced ``plan``, but lowers
    to collective-permutes of |k|-row slices instead of an all-to-all."""
    from jax.sharding import PartitionSpec

    if plan is None:
        # None is the "not circulant" sentinel from circulant_plan — the
        # caller should have taken the dense einsum path; silently gossiping
        # nothing here would return an all-zero consensus for a matrix that
        # is NOT all-zero
        raise ValueError(
            "gossip_apply(plan=None): None means 'not circulant, use the "
            "dense einsum path'; only an actual Plan tuple is accepted")
    if not jax.tree.leaves(tree):  # e.g. batch_stats of a GroupNorm model
        return tree
    if plan == ():
        # an all-zero matrix is (trivially) circulant and yields an empty
        # plan; the consensus it defines is identically zero — match the
        # einsum path instead of tripping over an empty accumulation
        return jax.tree.map(jnp.zeros_like, tree)
    D = mesh.devices.size
    specs = jax.tree.map(
        lambda x: PartitionSpec(CLIENT_AXIS, *([None] * (x.ndim - 1))),
        tree)

    def block_fn(blk_tree):
        def one(blk):
            b32 = blk.astype(jnp.float32)
            acc = None
            for k, w in plan:
                term = w * _rolled(b32, k, D)
                acc = term if acc is None else acc + term
            return acc.astype(blk.dtype)

        return jax.tree.map(one, blk_tree)

    return jax.shard_map(block_fn, mesh=mesh, in_specs=(specs,),
                         out_specs=specs)(tree)
