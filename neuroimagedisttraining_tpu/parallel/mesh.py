"""Device mesh + sharding layout for federated simulation.

The reference simulates clients with a sequential Python loop on one GPU
(sailentgrads_api.py:126-138). Here the client axis IS a mesh axis: stacked
client pytrees (``[C, ...]``) are sharded over ``Mesh(axis="clients")`` so
each TPU core trains ``C/ndev`` clients in parallel inside one jitted round
program, and cross-client reductions (FedAvg, score means, gossip) lower to
XLA collectives over ICI (SURVEY.md §2.10, BASELINE.json north star).

On multi-host slices the same mesh spans all devices; host-local data feeding
uses ``jax.make_array_from_process_local_data`` (data layer) and collectives
ride ICI/DCN as laid out by XLA.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any

CLIENT_AXIS = "clients"
SILO_AXIS = "silos"  # outer axis of a two-level (host, core) mesh


def provision_virtual_devices(n: int) -> bool:
    """Provision ``n`` virtual CPU devices for mesh simulation (SURVEY.md §4:
    fake-device meshes stand in for multi-node without a cluster).

    Must run before first backend touch; the axon/TPU plugin ignores the
    ``XLA_FLAGS=--xla_force_host_platform_device_count`` env route, so the
    config API is preferred — but the ``jax_num_cpu_devices`` option only
    exists on jax >= 0.5, so older toolchains fall back to the env route
    (read at backend init, i.e. still effective before first touch).
    Returns True if a provisioning route was applied, False if the backend
    was already initialized (in which case the caller must live with
    whatever devices exist)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return False
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:
        import os
        import re

        flag = f"--xla_force_host_platform_device_count={n}"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            # replace a pre-set (possibly different) count rather than
            # silently keeping it and still claiming the route applied
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags)
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
    try:
        from jax._src import xla_bridge
        if xla_bridge.backends_are_initialized():
            return False
    except Exception:
        pass  # private API moved: trust the config.update calls above
    return True


def make_mesh(num_devices: int | None = None, devices=None,
              axis_name: str = CLIENT_AXIS,
              shape: tuple[int, ...] = ()) -> Mesh:
    """1-D mesh over all (or the first N) visible devices; a 2-entry
    ``shape`` (e.g. ``--mesh_shape 2 4``) builds the two-level
    ``(silos, clients)`` mesh instead — silo reductions ride ICI, cross-
    silo traffic rides DCN (parallel/hierarchical.py)."""
    if devices is None:
        devices = jax.devices()
    if shape and (len(shape) > 2 or any(s < 1 for s in shape)):
        raise ValueError(
            f"--mesh_shape must be 1 or 2 positive integers, got {shape}")
    if len(shape) == 2:
        need = shape[0] * shape[1]
        if len(devices) < need:
            raise ValueError(
                f"--mesh_shape {shape} needs {need} devices, "
                f"have {len(devices)}")
        grid = np.asarray(devices[:need]).reshape(shape)
        return Mesh(grid, (SILO_AXIS, CLIENT_AXIS))
    if shape:
        num_devices = shape[0]
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(
                f"mesh needs {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def client_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (client) axis sharded over EVERY mesh axis — on a two-level
    mesh clients split across silos x cores — rest replicated."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, d: int) -> int:
    return ((n + d - 1) // d) * d


def shard_federation(tree: PyTree, mesh: Mesh) -> PyTree:
    """Device-put a stacked client pytree with its leading axis sharded over
    the mesh's client axis. Leading dim must be a multiple of the mesh size
    (pad clients with zero-weight shards first if needed)."""
    sh = client_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
