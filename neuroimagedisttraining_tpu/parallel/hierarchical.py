"""Two-level (silo -> global) federated aggregation over a 2-D mesh.

The reference's cross-silo scale-out path re-partitions the pooled cohort
into many equal client shards (``load_partition_data_abcd_rescale``,
ABCD/data_loader.py:216-315; BASELINE.json's 256-client cross-silo
config). On a TPU pod that federation has a natural two-level shape:

    mesh ("silos", "clients"): silo = one host (DCN between silos),
    clients = that host's cores (ICI within a silo).

Aggregation then decomposes into a silo-local weighted reduction (rides
ICI) followed by a cross-silo reduction of ONE pytree per silo (rides
DCN) — the bandwidth-correct layout: the narrow inter-host links carry
`num_silos` model-sized messages instead of `num_clients`.

The decomposition is also a semantic capability the flat mean cannot
express: ``silo_then_global_mean(..., norm_bound=...)`` applies the
reference's Byzantine norm-diff clipping (robust_aggregation.py:38-49)
to each SILO AGGREGATE before the global mean — the cross-silo trust
model (silos are administrative domains; a hostile silo is bounded as a
unit no matter how many clients it claims to contain).

With no clipping the result is bit-comparable to the flat
``tree_weighted_mean`` over all clients (same sums, same division),
pinned by tests/test_sharding.py on a 2x4 virtual mesh.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from neuroimagedisttraining_tpu.core.robust import norm_diff_clip
from neuroimagedisttraining_tpu.parallel.mesh import CLIENT_AXIS, SILO_AXIS

PyTree = Any


def make_two_level_mesh(num_silos: int, clients_per_silo: int,
                        devices=None) -> Mesh:
    """2-D mesh [silos, clients]; on a real pod pass a devices array whose
    first axis groups devices by host so the silo axis maps onto DCN."""
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=devices,
                     shape=(num_silos, clients_per_silo))


def is_two_level(mesh: Mesh | None) -> bool:
    return mesh is not None and SILO_AXIS in mesh.axis_names


def silo_then_global_mean(stacked: PyTree, weights: jax.Array, mesh: Mesh,
                          global_params: PyTree | None = None,
                          norm_bound: float | None = None) -> PyTree:
    """Weighted mean of client-stacked ``stacked`` ([C, ...], C sharded over
    both mesh axes) computed silo-locally first, then across silos.

    ``norm_bound`` (with ``global_params``) clips each silo's aggregate to
    within ``norm_bound`` of the previous global params before the
    cross-silo mean — norm-diff clipping at silo granularity.
    """
    spec = P((SILO_AXIS, CLIENT_AXIS))

    def agg(stacked, weights, *maybe_global):
        # silo-local weighted sum over this device's clients + ICI psum
        wsum = jax.tree.map(
            lambda x: jax.lax.psum(
                jnp.tensordot(weights, x.astype(jnp.float32), axes=(0, 0)),
                CLIENT_AXIS),
            stacked)
        wtot = jax.lax.psum(jnp.sum(weights.astype(jnp.float32)),
                            CLIENT_AXIS)
        if norm_bound is not None:
            silo_mean = jax.tree.map(lambda s: s / jnp.maximum(wtot, 1e-9),
                                     wsum)
            clipped = norm_diff_clip(silo_mean, maybe_global[0], norm_bound)
            wsum = jax.tree.map(lambda c: c * wtot, clipped)
        # cross-silo (DCN) reduction of one aggregate per silo; cast each
        # leaf back to its input dtype so the two-level path matches the
        # flat tree_weighted_mean for non-f32 leaves
        gsum = jax.tree.map(lambda s: jax.lax.psum(s, SILO_AXIS), wsum)
        gtot = jax.lax.psum(wtot, SILO_AXIS)
        return jax.tree.map(
            lambda s, x: (s / jnp.maximum(gtot, 1e-9)).astype(x.dtype),
            gsum, stacked)

    args = (stacked, weights)
    in_specs = (jax.tree.map(lambda _: spec, stacked), spec)
    if norm_bound is not None:
        assert global_params is not None, "clipping needs global_params"
        args += (global_params,)
        in_specs += (jax.tree.map(lambda _: P(), global_params),)
    out_specs = jax.tree.map(lambda _: P(), stacked)
    return shard_map(agg, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)(*args)
