"""Cohort sharding: one dispatched program trains every sampled client.

The flagship workload is 21 acquisition-site clients, but until ISSUE 6
the round driver ran the whole ``[C, ...]`` client stack on one device:
the federation's DATA was mesh-sharded (data/federate.py), yet the jitted
round program's vmapped local-training stage carried no placement
contract, so XLA was free to (and on the measured configs did) execute
all C clients' local SGD serially on one device — round time linear in C.
This module supplies the missing placement contract (ROADMAP item 2, the
SysML-2018 compile-once/dispatch-once premise in PAPERS.md):

- :func:`cohort_map` wraps the per-client training block in ``shard_map``
  over the mesh's client axis with EXPLICIT in/out specs: each device
  trains its ``C/D`` client shard, then the trained stacks are
  all-gathered back to replicated full stacks.
- :func:`pad_cohort` pads a sampled set that does not tile the mesh
  (21 sites on 8 devices -> 24 rows) with zero-weight pad rows, and
  :func:`pad_row_weights` is THE one place pad-row weights are zeroed
  (nidtlint's ``mesh-pad-weights`` rule rejects ad-hoc reconstructions).

Numerical contract (tests/test_cohort.py), stated with the precision
the measurements force:

- vs the UNPARTITIONED sequential C-loop (:func:`sequential_map` in a
  plain jit) AND the shipped vmapped round: a FedAvg round's training
  losses from identical state are BITWISE-equal — the proof that batch
  selection, masking, weighting, and every semantic choice is
  identical (the masked salientgrads round's mean loss sits exactly 1
  float32 ulp off: the per-step mask multiply adds one more fusion
  seam) — and trained params/batch stats agree to ~1 ulp of their own
  magnitude. The residue is an XLA compile-context artifact, not a
  semantic one (different modules tile a handful of reductions
  differently); over multi-round windows it feeds back through
  training and surfaces as ~1e-6-level relative drift.
- MESH-WIDTH INDEPENDENCE to the same ~1 ulp: a full sharded
  ``train()`` on a 2-device mesh matches the 8-device run through
  different pad counts (21 real sites -> 22 vs 24 rows) and per-device
  work lists. Exactly-bitwise equality holds only between runs whose
  COMPILED MODULE is identical; a K=4 fused window IS bitwise-equal to
  four single sharded dispatches (pinned).

Three design decisions exist to keep those pins maximal — the third is
a hard CORRECTNESS requirement, not a preference:

- Per-client training runs UNBATCHED, ``lax.map``-looped within each
  device's shard (:func:`sequential_map` is the same loop on one
  device). It does NOT run as vmapped client lanes: XLA tiles a batched
  client contraction by its total width, so a client's trained values
  differ at 1e-3 level between a 3-lane device block and a 21-lane
  unsharded vmap — vmap lanes are not width-stable; unbatched
  per-client programs are.
- The aggregation is NOT a ``psum`` of per-device partial weighted
  sums: partial sums reorder the float reduction. Instead the trained
  stacks are all-gathered to every device and the engine's existing
  aggregation/defense/codec tail runs unchanged on replicated full
  stacks — identical operations on identical values. The gather moves
  the same bytes per device a reduce-scatter + broadcast pair would;
  what it gives up is only the redundant (cheap, model-sized)
  reduction arithmetic per device.
- RANDOM-SORT OPS MUST BE HOISTED OUT OF THE PARTITION. On this
  toolchain (jax 0.4.x CPU SPMD) an argsort-lowered
  ``jax.random.permutation`` computed INSIDE a shard_map partition and
  CONSUMED by the training scan silently yields different batch
  selections than the same code unpartitioned — while OBSERVING the
  permutation (returning it as an output) makes it correct, the
  signature of a fusion miscompilation. The bisection that found it:
  per-client losses diverged at 1e-0 level with identical inputs,
  identical observable indices, across every gather mode,
  ``optimization_barrier`` placement, and XLA runtime flag — and went
  to ZERO the moment the permutations were computed outside the
  ``shard_map`` and passed in. Hence ``LocalTrainer.local_train``'s
  ``perms=`` parameter + the round-program builder's perm hoist
  (``engines/program.py``: ``hoisted_epoch_perms`` /
  ``RoundCtx.client_map``) for the rounds, and
  ``ops.snip.iter_snip_batch_indices`` for phase-1's IterSNIP draws;
  the non-hoistable ``batch_order=replacement`` (i.i.d. per-step
  randint draws — same in-partition lowering family, same measured
  wrongness) falls back to the unsharded round with a logged reason.

The compute-dominant stage (per-client Conv3D local training, ~99% of
round FLOPs) therefore runs ``ceil(C/D)`` sequential clients per device
instead of ``C`` — flat in C up to the device count, the flagship
deployment's one-site-per-core layout.

Pad-row semantics: pad ids prefer the federation's zero-sample padding
clients (rows ``[real_clients, num_clients)`` — ``n_train == 0``), then
repeat the last sampled id; either way :func:`pad_row_weights` zeroes
their sample counts before local training, so pads train as zero-weight
no-ops, and the engine round bodies STATICALLY SLICE the pad rows off
after the gather — the aggregation/defense tail never sees them (the
robust aggregators additionally ignore zero-weight rows, so even an
unsliced consumer is safe).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

PyTree = Any


def pad_cohort(sampled: np.ndarray, real_clients: int, num_clients: int,
               n_devices: int) -> tuple[np.ndarray, int]:
    """``(padded_ids, n_real)``: the sampled set padded to tile an
    ``n_devices``-wide client mesh. Pad entries prefer the federation's
    zero-sample padding clients (rows ``[real_clients, num_clients)``),
    then repeat the last sampled id (its pad rows are zero-weighted by
    position via :func:`pad_row_weights`, never by sample count). The
    shared pad rule of the streamed feed (``stream_sampling``) and the
    cohort-sharded resident round."""
    sampled = np.asarray(sampled)
    if len(sampled) == 0:
        raise ValueError("pad_cohort got an empty sampled set — no client "
                         "to pad the mesh tile from (configuration error)")
    pad = (-len(sampled)) % n_devices
    if pad == 0:
        return sampled, len(sampled)
    pool = np.arange(real_clients, num_clients)
    fill = np.concatenate([pool, np.full(max(0, pad - len(pool)),
                                         sampled[-1])])[:pad]
    return np.concatenate([sampled, fill]).astype(sampled.dtype), \
        len(sampled)


def pad_row_weights(ns: jax.Array, n_real: int) -> jax.Array:
    """Zero the per-client sample counts of mesh-pad rows (index >=
    ``n_real``). THE shared helper for pad-row zero-weight construction:
    a pad entry may DUPLICATE a real client id (``pad_cohort`` repeats
    the last sampled id once the zero-sample pool runs dry), so gathering
    ``n_train`` rows is not enough — the position mask is what guarantees
    pads train as zero-weight no-ops. nidtlint's ``mesh-pad-weights``
    rule keeps every call site on this function."""
    return jnp.where(jnp.arange(ns.shape[0]) < n_real, ns,
                     jnp.zeros_like(ns))


def sequential_map(fn, *stacked: PyTree) -> PyTree:
    """The sequential C-loop as ONE dispatched program: ``lax.map`` of
    the UNBATCHED per-client ``fn`` over the stacks' leading client axis
    — the reference's client-at-a-time simulation
    (sailentgrads_api.py:126-138) expressed as a single XLA while loop.
    :func:`cohort_map` runs D of these loops in parallel, one per mesh
    device; because both paths execute the identical unbatched
    per-client program, the sharded round matches this loop to ~1 ulp
    with bitwise first-round losses (module docstring) — which no
    vmap-lane formulation can promise."""
    return jax.lax.map(lambda args: fn(*args), tuple(stacked))


def _gather_replicated(x: jax.Array, axis_name: str) -> jax.Array:
    """All-gather one leaf's per-device client blocks back into the full
    replicated ``[C, ...]`` stack. Typed PRNG-key arrays (the trained
    ``ClientState.rng`` leaves) gather through their uint32 key data —
    collectives do not accept extended dtypes on this toolchain."""
    if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
        data = jax.lax.all_gather(jax.random.key_data(x), axis_name,
                                  axis=0, tiled=True)
        return jax.random.wrap_key_data(data)
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)


def cohort_map(mesh: Mesh, fn, *stacked: PyTree) -> PyTree:
    """Map the unbatched per-client ``fn`` over the leading client axis
    of the ``stacked`` pytrees with that axis SHARDED over ``mesh``'s
    (single) client axis: each device runs :func:`sequential_map`'s
    client loop over its ``C/D`` block, and the outputs are all-gathered
    back to replicated full ``[C, ...]`` stacks — ~1-ulp-equal (with
    bitwise losses from identical state) to
    ``sequential_map(fn, *stacked)`` and across mesh widths (the module
    docstring explains why the loop, and not vmap lanes, is what makes
    those pins possible, and where exact bitwise equality holds).

    ``fn`` may close over replicated (unbatched) state — the round's
    incoming global params, the SNIP mask, FedProx's proximal reference;
    ``shard_map`` lifts closed-over values as replicated. The leading
    axis must tile the mesh (:func:`pad_cohort`); anything else is a
    caller bug and fails loudly here."""
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"cohort_map shards over a 1-D client mesh; got axes "
            f"{mesh.axis_names} (two-level meshes route aggregation "
            "silo-first instead — parallel/hierarchical.py)")
    axis = mesh.axis_names[0]
    D = mesh.devices.size
    C = jax.tree.leaves(stacked[0])[0].shape[0]
    if C % D != 0:
        raise ValueError(
            f"cohort_map: client axis ({C}) does not tile the {D}-device "
            "mesh — pad the sampled set with pad_cohort first")

    def block(*blocks):
        out = sequential_map(fn, *blocks)
        return jax.tree.map(lambda x: _gather_replicated(x, axis), out)

    in_specs = tuple(P(axis) for _ in stacked)
    # out_specs P(): the all-gather leaves every output replicated. The
    # static replication checker of jax < 0.5 cannot see through a tiled
    # all_gather, so it is disabled (the gather IS the replication proof;
    # newer jax drops the kwarg, hence the fallback).
    try:
        shmapped = shard_map(block, mesh=mesh, in_specs=in_specs,
                             out_specs=P(), check_rep=False)
    except TypeError:  # pragma: no cover - jax >= 0.8 removed check_rep
        shmapped = shard_map(block, mesh=mesh, in_specs=in_specs,
                             out_specs=P())
    return shmapped(*stacked)
