"""Configuration dataclasses.

Externalizes the reference's per-entry-point argparse flag sets
(reference: fedml_experiments/standalone/sailentgrads/main_sailentgrads.py:31-127,
main_ditto.py:79,101, main_subavg.py:105-108) into typed, serializable config
objects shared by every algorithm engine. Defaults preserve the reference's
canonical ABCD configuration: 3DCNN model, ABCD dataset, 21 site-clients,
batch 16, 200 communication rounds, SGD lr 0.01 with 0.998/round decay,
weight decay 5e-4, gradient clip 10 (main_sailentgrads.py:61-99;
my_model_trainer.py:209,224).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class OptimConfig:
    """Local-optimizer configuration (reference flags: lr, lr_decay, wd,
    momentum, batch_size, epochs, client_optimizer)."""

    client_optimizer: str = "sgd"  # "sgd" | "adam"
    lr: float = 0.01
    lr_decay: float = 0.998        # per-round exponential: lr * lr_decay**round
    wd: float = 5e-4
    momentum: float = 0.9
    batch_size: int = 16
    epochs: int = 2                # local epochs per round
    grad_clip: float = 10.0        # torch clip_grad_norm_ parity (my_model_trainer.py:224)
    # "shuffle": walk a fresh per-epoch permutation in batch_size strides
    # (reference DataLoader semantics, my_model_trainer.py:213);
    # "replacement": i.i.d. uniform draws per step (rounds 1-3 behavior)
    batch_order: str = "shuffle"
    # Mixed-precision train-step contract (ISSUE 10, core/optim.py):
    # "fp32" — everything float32, bitwise-identical to the pre-ISSUE-10
    # tree; "bf16_mixed" — bf16 compute + activations (the model's
    # flax ``dtype``), fp32 MASTER weights / momentum / loss (flax
    # ``param_dtype`` stays float32, models cast logits back to f32).
    # The FedAvg/codec/secure/checkpoint planes only ever see the fp32
    # master weights — bf16 exists strictly inside the jitted step.
    precision: str = "fp32"       # fp32 | bf16_mixed
    # Fixed loss-scale constant for bf16_mixed (Frostig et al.'s static
    # scaling; bf16's f32-sized exponent rarely needs it, so 1.0 is the
    # pinned default — scale S is mathematically a no-op: loss * S
    # before grad, grads / S after, both in fp32). Must be 1.0 under
    # fp32 (any other value would break the bitwise-unchanged pin).
    loss_scale: float = 1.0
    # Fused mask-apply + clip + momentum + SGD-update tail
    # (ops/fused_update.py): one Pallas pass over params instead of the
    # unfused chain's per-stage HBM round-trips; XLA fallback off-TPU,
    # bit-parity with the optax chain pinned. SGD only.
    fused_update: bool = False


@dataclass(frozen=True)
class DataConfig:
    """Dataset + partitioning configuration."""

    dataset: str = "abcd"          # abcd | cifar10 | cifar100 | tiny | synthetic
    data_dir: str = "./data"
    partition_method: str = "site"  # site | dir | n_cls | my_part | homo | hetero | rescale
    partition_alpha: float = 0.3
    # Synthetic-ABCD knobs (tests / benchmarks without the private cohort).
    synthetic_num_subjects: int = 256
    synthetic_shape: tuple[int, int, int] = (121, 145, 121)
    synthetic_signal: float = 12.0  # class-blob amplitude vs the fixed
    # sigma-8 voxel noise — lower it for harder tasks (run_byz_bench.sh
    # uses a low-signal cohort so a Byzantine slowdown is visible in AUC)
    seed_split: int = 42           # per-site 80/20 split seed (ABCD/data_loader.py:82-86)
    val_fraction: float = 0.0      # >0 adds per-client validation split (FedFomo 9-tuple)


@dataclass(frozen=True)
class SparsityConfig:
    """Sparse-training configuration shared by SalientGrads / DisPFL / SubAvg
    (reference flags: dense_ratio, anneal_factor, erk_power_scale, uniform,
    static, dis_gradient_check, snip_mask, itersnip_iteration,
    stratified_sampling, each_prune_ratio, dist_thresh, acc_thresh)."""

    dense_ratio: float = 0.5
    anneal_factor: float = 0.5
    erk_power_scale: float = 1.0
    uniform: bool = False          # uniform layer sparsity instead of ERK
    static: bool = False           # no mask evolution (DisPFL)
    dis_gradient_check: bool = False
    different_initial: bool = False  # per-client distinct initial masks (DisPFL)
    diff_spa: bool = False         # per-client density cycle 0.2..1.0 (DisPFL)
    snip_mask: bool = True         # SalientGrads dense escape hatch when False
    itersnip_iterations: int = 1
    stratified_sampling: bool = False
    # Sub-FedAvg
    each_prune_ratio: float = 0.1
    dist_thresh: float = 0.001
    acc_thresh: float = 0.5
    save_masks: bool = False


@dataclass(frozen=True)
class FedConfig:
    """Federation topology + schedule (reference flags: client_num_in_total,
    frac, comm_round, cs, active; Ditto lamda/local_epochs)."""

    client_num_in_total: int = 21
    frac: float = 1.0              # fraction of clients sampled per round
    comm_round: int = 200
    cs: str = "random"             # neighbor/topology selector: random | ring | full
    active: float = 1.0            # Bernoulli client-activity (fault injection, DisPFL)
    neighbor_num: int = 5          # gossip fan-out when cs == "random"
    # Ditto
    lamda: float = 0.5
    local_epochs: int = 1
    # FedFomo
    fomo_m: int = 5                # number of models requested per round
    # Robust aggregation (fedml_core/robustness/robust_aggregation.py:32-55;
    # the reference constructs RobustAggregator(args) from defense_type /
    # norm_bound / stddev flags). Byzantine-robust aggregators (ISSUE 5,
    # core/robust.py): trimmed_mean | median | krum | multi_krum |
    # geometric_median replace the weighted mean with an order statistic
    # tolerating up to byz_f arbitrary (value-faulty) clients.
    defense_type: str = "none"     # none | norm_diff_clipping | weak_dp |
    # trimmed_mean | median | krum | multi_krum | geometric_median
    norm_bound: float = 5.0        # clip threshold for the update-norm diff
    stddev: float = 0.05           # weak-DP Gaussian noise stddev
    byz_f: int = 1                 # assumed Byzantine count f: trim depth
    # per side (trimmed_mean), Krum's score neighborhood (needs the
    # sampled cohort n >= f + 3; trimmed_mean/median need 2f < n)
    geomed_iters: int = 8          # fixed Weiszfeld iterations
    # (geometric_median; trace-static so fused dispatch stays one program)
    # TurboAggregate secure aggregation (additive shares over GF(p))
    mpc_n_shares: int = 3          # shares per client update (paper: one
    # per neighbor group)
    mpc_frac_bits: int = 16        # fixed-point fraction bits for GF(p)
    # quantization
    # "device": the quantize/share/accumulate pipeline runs as jitted
    # uint32 mod-p ops on the TPU's VPU, fused with the round (no host
    # round-trip); "host": the numpy path that models the client<->server
    # communication boundary (the multi-aggregator cross-silo deployment
    # always uses the host toolkit — it crosses real process boundaries)
    mpc_backend: str = "device"
    # Secure QUANTIZED aggregation (privacy/secure_quant.py, ISSUE 8):
    # uploads become field-element frames in GF(p) for the largest prime
    # below 2^field_bits — one wire-dtype residue per parameter plus
    # seed-expanded mask slots, vs the dense secure protocol's n_shares
    # int64 stacks. These fields mirror distributed/run.py's
    # --secure_quant* flags (the encoded secure wire lives on the
    # cross-silo/async control planes; the simulated engines' jitted
    # counterpart is ops/mpc_device.py at this same (p, frac_bits)).
    secure_quant: bool = False
    secure_quant_field_bits: int = 16
    secure_quant_frac_bits: int = 10
    # Round-level differential privacy for the dpsgd engine (privacy/
    # accountant.py, ISSUE 8): every client's post-training update delta
    # vs its consensus point is clipped to dp_clip and noised with
    # N(0, (dp_sigma * dp_clip)^2) INSIDE the jitted round (keys folded
    # from the config seed), and the RDP accountant reports the running
    # per-silo (epsilon, dp_delta) in stat_info. 0 disables; dp_sigma>0
    # requires dp_clip>0 (the clip IS the sensitivity bound).
    dp_clip: float = 0.0
    dp_sigma: float = 0.0
    dp_delta: float = 1e-5
    # Epsilon budget for the built-in DP health rules (obs/rules.py,
    # ISSUE 15): > 0 arms dp-budget-exceeded (critical once the running
    # epsilon crosses it) and dp-burn-rate (warn when a round burns
    # over 2x the uniform budget/comm_round rate). Purely a verdict
    # threshold — the accountant itself never stops at a budget.
    dp_epsilon_budget: float = 0.0
    # Deterministic fault injection + tolerance (faults/, ISSUE 2).
    # fault_spec grammar: "crash:RANK@ROUND,crash_prob:P,straggle:P:MAX_S,
    # drop:P,dup:P,disconnect:P,byz:RANK@ROUND:KIND,preempt:NDEV@ROUND"
    # (faults/schedule.parse_fault_spec); one config seed replays the
    # identical fault trace in the simulated engines AND the
    # multiprocess federation. preempt: is the elastic-plane device loss
    # (ISSUE 20): the engine shrinks client_mesh to NDEV survivors and
    # resumes from the last checkpoint instead of dying.
    fault_spec: str = ""
    # Model-update wire codec (codec/, ISSUE 3): stages joined by '+'
    # from {delta, sparse, quant, quant16} or "none" (dense wire). In
    # the simulated engines the codec's lossy value transform is applied
    # to client updates BEFORE aggregation (jitted, codec/device.py) so
    # an in-process run aggregates exactly what a cross-silo federation
    # shipping encoded frames would; bytes ride stat_info
    # ("sum_comm_bytes" encoded vs "sum_comm_bytes_dense").
    wire_codec: str = "none"
    wire_topk_ratio: float = 0.25  # top-k keep fraction for dense engines
    round_deadline: float = 0.0    # s; >0 arms the cross-silo per-round deadline
    quorum: int = 0                # min uploads to aggregate at deadline; 0 = all
    # Async buffered control plane (ISSUE 7, asyncfl/): the cross-silo
    # server becomes a FedBuff-style buffered aggregator — uploads
    # accepted continuously, aggregated every buffer_k arrivals with
    # polynomial staleness weighting (1 + tau)^-staleness_alpha, and
    # uploads staler than max_staleness versions dropped at admission.
    # The simulated in-process engines stay round-synchronous (the
    # buffer is a control-plane construct); these fields mirror
    # distributed/run.py's flags like round_deadline/quorum do.
    async_server: bool = False
    buffer_k: int = 0              # aggregate every K uploads; 0 = cohort size
    staleness_alpha: float = 0.5   # FedBuff polynomial staleness exponent
    max_staleness: int = 20        # admission bound (and codec-ref ring depth)
    heartbeat_interval: float = 0.0  # s; >0 makes silo clients beat liveness
    heartbeat_timeout: float = 0.0   # s; >0 marks silent clients suspect
    # Fused multi-round dispatch (ISSUE 4): when > 1 and the federation
    # is resident, non-streaming, and host-free between rounds, the
    # driver precomputes up to this many rounds of sampling indices /
    # per-round rngs / lr schedule on the host and runs them as ONE
    # lax.scan over the engine's round body — eval/checkpoint/logging
    # hooks fire at window boundaries (the window planner shrinks so
    # every hook round lands on a boundary, preserving the sequential
    # loop's observable behavior bitwise). Engines that cross the host
    # each round (fedfomo pair lists, turboaggregate MPC, mask/topology
    # evolution, streaming, --wire_codec byte accounting) transparently
    # fall back to one round per dispatch with a logged reason.
    rounds_per_dispatch: int = 1
    # Cohort sharding (ISSUE 6, parallel/cohort.py): when > 0, the
    # sampled-client axis of every jitted round program shards over a
    # client mesh of exactly this many devices (one shard_map per round:
    # per-device local training on the client shards, trained stacks
    # all-gathered, aggregation/defense/codec tail on replicated full
    # stacks — bitwise-equal to the unsharded round). Sampled sets that
    # do not tile the mesh (the flagship 21 sites on 8 devices) pad with
    # zero-weight rows. Engines whose rounds cross the host or exchange
    # per-client state outside the fedavg/salientgrads shape — and the
    # streaming/two-level-mesh/single-device modes — fall back to the
    # unsharded round with a logged reason; a mismatch with the
    # constructed mesh size is a startup error.
    client_mesh: int = 0
    # Evaluation cadence
    frequency_of_the_test: int = 1
    ci: bool = False               # CI mode: evaluate client 0 only

    @property
    def client_num_per_round(self) -> int:
        # parity: main_sailentgrads.py:234
        return max(1, int(self.client_num_in_total * self.frac))


@dataclass(frozen=True)
class ExperimentConfig:
    """Top-level experiment config = the reference's full flag surface."""

    model: str = "3DCNN"           # 3DCNN | 3DCNN_deeper | 3DCNN_regression | resnet3d | resnet18 | ...
    num_classes: int = 1           # 1 => BCE-with-logits (ABCD sex), >1 => CE
    algorithm: str = "fedavg"
    seed: int = 1024
    tag: str = "exp"
    data: DataConfig = field(default_factory=DataConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    fed: FedConfig = field(default_factory=FedConfig)
    sparsity: SparsityConfig = field(default_factory=SparsityConfig)
    # TPU execution. Compute dtype is ``optim.precision`` (the old
    # param_dtype/compute_dtype strings were dead config — nothing
    # consumed them; the precision contract in core/optim.py replaces
    # them with a single validated knob).
    mesh_shape: tuple[int, ...] = ()   # () => all visible devices on one "clients" axis
    remat: str = "auto"            # auto | none | stem | all — 3D-model
    # rematerialization policy (PROFILE.md); auto picks from samples
    # in flight per device (build_experiment)
    # Autotune recipe applied at startup (tune/recipe.py, ISSUE 19):
    # path to a committed bench_matrix/recipes/<device_kind>.json or
    # "auto" (resolve by visible device kind); "" = none. Recorded so a
    # run's config names the recipe that defaulted its knobs.
    recipe: str = ""
    checkpoint_dir: str = ""
    checkpoint_every: int = 0          # rounds; 0 disables
    log_dir: str = "LOG"
    # Observability (obs/, ISSUE 9). All off-by-default-cheap; none of
    # these may ever add a host sync or clock read inside a jitted body
    # (the obs-discipline lint family enforces it).
    trace_out: str = ""            # Chrome trace-event JSON path; ""=off
    metrics_port: int = 0          # /metrics + /healthz port; 0 = off
    flight_events: int = 256       # flight-recorder ring capacity
    # Training-health plane (ISSUE 15). health_stats arms the
    # in-dispatch federation-statistics leg on every declared round
    # program (engines/program.py -> obs/health.py): per-client update
    # norms, cosine-to-aggregate, dispersion, global norms, mask health
    # — computed inside the jitted round, fetched only in the existing
    # batched host-boundary device_get (armed-vs-disarmed rounds are
    # BITWISE identical; zero added syncs). health_rules names a JSON
    # manifest extending the built-in anomaly rules (obs/rules.py);
    # health_gate makes the CLI exit nonzero when the run's worst
    # health status was not "ok". metrics_out appends one registry
    # JSONL record per round (with monotonic round/seq join keys) for
    # analysis/run_report.py.
    health_stats: bool = False
    health_rules: str = ""
    health_gate: bool = False
    metrics_out: str = ""
    # Reflex plane (ISSUE 20, obs/actions.py): what a firing rule's
    # declared action is allowed to DO — "off" (no dispatch), "dry_run"
    # (log what WOULD fire; the default, so nothing changes behavior
    # silently), "on" (registered handlers run: quarantine, defense
    # escalation, buffer adaptation, freeze-and-rollback).
    actions: str = "dry_run"
    # streaming mode: clients per host-fetched chunk for streamed eval /
    # phase-1 scoring / chunked DisPFL rounds; 0 = auto (mesh size or 4)
    stream_chunk_clients: int = 0

    def identity(self) -> str:
        """Experiment-identity string encoding the config, mirroring the
        reference's identity-string construction (main_sailentgrads.py:202-242)."""
        d, o, f, s = self.data, self.optim, self.fed, self.sparsity
        parts = [
            self.algorithm, d.dataset, self.model,
            f"c{f.client_num_in_total}", f"frac{f.frac}", f"r{f.comm_round}",
            f"e{o.epochs}", f"b{o.batch_size}", f"lr{o.lr}", f"dec{o.lr_decay}",
            f"wd{o.wd}", f"part-{d.partition_method}{d.partition_alpha}",
            f"dr{s.dense_ratio}", f"seed{self.seed}", self.tag,
        ]
        return "_".join(str(p) for p in parts)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), default=str, sort_keys=True)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ExperimentConfig":
        def sub(cls, key):
            v = d.get(key, {})
            if isinstance(v, cls):
                return v
            fields = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: tuple(x) if isinstance(x, list) else x
                          for k, x in v.items() if k in fields})

        top = {k: v for k, v in d.items()
               if k in {f.name for f in dataclasses.fields(ExperimentConfig)}
               and k not in ("data", "optim", "fed", "sparsity")}
        if "mesh_shape" in top and isinstance(top["mesh_shape"], list):
            top["mesh_shape"] = tuple(top["mesh_shape"])
        return ExperimentConfig(
            data=sub(DataConfig, "data"), optim=sub(OptimConfig, "optim"),
            fed=sub(FedConfig, "fed"), sparsity=sub(SparsityConfig, "sparsity"),
            **top,
        )
