"""Typed message model for the cross-silo control plane.

Semantics parity with fedml_core/distributed/communication/message.py:5-74:
a message is {msg_type, sender_id, receiver_id} + a key-value payload whose
values may be model-parameter pytrees. Codec re-design: the reference
serializes to JSON (message.py:62-65 — model weights would ship as JSON
lists); here the wire format is a 2-frame msgpack envelope — a small header
dict plus a flax-msgpack body for array payloads — so a 2.6 M-param model is
~10 MB binary, not ~60 MB of JSON text.

Message-type constants keep the reference protocol contract
(SURVEY.md §5.8): init/broadcast params -> local train -> upload update ->
aggregate, plus register/finish lifecycle.

Wire codec (ISSUE 3): an ``ARG_MODEL_PARAMS`` value may be either the
dense pytree (the format above — always understood) or a TAGGED BODY
FRAME produced by ``codec/wire.py``: a dict carrying the magic key
``codec.FRAME_KEY`` with a version int, a spec string, and one
zlib-deflated msgpack blob of per-leaf records (delta residuals,
mask-sparse packed values + bitmap, int8/bf16 quantized values with
per-leaf scales). The frame rides this envelope unchanged — msgpack
serializes the dict like any payload — and receivers route through
``codec.decode_update``, which passes dense trees through untouched, so
a dense sender and an encoded sender interoperate on one control plane.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import jax
from flax import serialization

# protocol message types (client_manager.py / server_manager.py handler keys)
MSG_TYPE_CONNECTION_IS_READY = "connection_ready"
MSG_TYPE_C2S_REGISTER = "client_register"
MSG_TYPE_S2C_INIT_CONFIG = "server_init_config"
MSG_TYPE_S2C_SYNC_MODEL = "server_sync_model"
MSG_TYPE_C2S_SEND_MODEL = "client_send_model"
MSG_TYPE_S2C_FINISH = "server_finish"
# liveness signal (cross_silo heartbeat monitor): clients beat on an
# interval; the server marks silent clients suspect within a bound
MSG_TYPE_C2S_HEARTBEAT = "client_heartbeat"
# secure-aggregation weight exchange (cross_silo.SecureFedAvgServer)
MSG_TYPE_C2S_NUM_SAMPLES = "client_num_samples"
MSG_TYPE_S2C_AGG_WEIGHTS = "server_agg_weights"
# multi-aggregator secure aggregation (cross_silo.SlotAggregatorProc):
# client -> aggregator j carries ONE share slot; aggregator -> server
# carries the cross-client slot total
MSG_TYPE_C2A_SEND_SLOT = "client_send_slot"
MSG_TYPE_A2S_SLOT_TOTAL = "aggregator_slot_total"

# payload keys (Message.MSG_ARG_KEY_* parity)
ARG_MODEL_PARAMS = "model_params"
ARG_NUM_SAMPLES = "num_samples"
ARG_CLIENT_INDEX = "client_index"
ARG_ROUND_IDX = "round_idx"
ARG_AGG_WEIGHT = "agg_weight"
ARG_SLOT_INDEX = "slot_index"
#: sync-message flag (ISSUE 5): the receiving silo must RESET its wire-
#: codec error-feedback accumulator before training this round — sent on
#: the first sync after a quarantine window ends, because the EF mass the
#: silo accumulated against frames the server dropped no longer
#: corresponds to anything the server aggregated
ARG_EF_RESET = "ef_reset"
#: per-sender monotone upload counter (ISSUE 7): the asynchronous server
#: dedups re-delivered frames by watermark — ``seq <= last seen`` from a
#: sender is a transport duplicate — while a client honestly re-training
#: from an unchanged base version ships a fresh seq and is accepted. The
#: synchronous server keys dedup on the round tag instead and ignores
#: this; senders without it fall back to one-contribution-per-version.
ARG_UPLOAD_SEQ = "upload_seq"
#: wire trace context (ISSUE 13): ``{"trace_id": int, "span_id": int}``
#: stamped by the CLIENT on every upload frame
#: (``obs.trace.make_trace_ctx``) and propagated through admission ->
#: fold -> partial merge -> aggregate as Perfetto flow events, so one
#: upload's client->worker->root lifecycle reads as a causally-linked
#: track in the merged trace (obs/fanin.py). THE single key — nidtlint
#: ``obs-trace-ctx-key`` rejects ad-hoc spellings — and always
#: optional: a frame without it is processed identically, just unlinked.
ARG_TRACE_CTX = "trace_ctx"
#: sender promise (ISSUE 7): "this connection stays open — route my
#: replies back on it". The selector core maps rank -> connection only
#: for frames carrying this flag; a legacy ``SocketCommManager`` peer
#: (one short-lived connection per frame, replies dialed to its listener
#: port) never sets it, so its dying connections are never chosen as a
#: reply route.
ARG_CONN_PERSISTENT = "persistent_conn"
#: sender-lifetime nonce (ISSUE 18): distinguishes a RECONNECT (same
#: incarnation — the sender's monotone ``ARG_UPLOAD_SEQ`` continues, so
#: the root-held watermark must survive a worker/region hop) from a
#: RESTART (new incarnation — a fresh seq 0 is legitimate). Senders
#: without it keep the documented per-worker reset-on-re-register
#: semantics unchanged.
ARG_CLIENT_INCARNATION = "client_incarnation"
#: sender capability (ISSUE 18): "my sync replies may ship the lossless
#: delta against my last-synced version instead of the dense body".
#: Never assumed — a server only sends a delta frame to a sender that
#: advertised this at registration.
ARG_SYNC_DELTA_OK = "sync_delta_ok"

_MAGIC = b"NIDT1"


def frame_bytes(msg: "Message") -> bytes:
    """THE on-the-wire framing: an 8-byte ``!Q`` length prefix followed
    by the serialized message. Every transport (socket, selector core,
    load harness, chaos wrapper) must emit exactly this — one
    definition, so a future header change cannot silently desync one
    hand-rolled copy."""
    import struct

    raw = msg.to_bytes()
    return struct.pack("!Q", len(raw)) + raw


class Message:
    """dict-shaped message with typed header (message.py:5-35)."""

    def __init__(self, msg_type: str = "default", sender_id: int = 0,
                 receiver_id: int = 0):
        self.msg_type = msg_type
        self.sender_id = int(sender_id)
        self.receiver_id = int(receiver_id)
        self.params: dict[str, Any] = {}

    def add(self, key: str, value: Any) -> None:
        self.params[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)

    # ---- codec ----

    def to_bytes(self) -> bytes:
        body = {
            "h": {"t": self.msg_type, "s": self.sender_id,
                  "r": self.receiver_id},
            "p": jax.tree.map(
                lambda v: np.asarray(v)
                if isinstance(v, (jax.Array, np.ndarray)) else v,
                self.params),
        }
        return _MAGIC + serialization.msgpack_serialize(body)

    @staticmethod
    def from_bytes(raw: bytes) -> "Message":
        if raw[: len(_MAGIC)] != _MAGIC:
            raise ValueError("bad message frame (magic mismatch)")
        body = serialization.msgpack_restore(raw[len(_MAGIC):])
        m = Message(body["h"]["t"], body["h"]["s"], body["h"]["r"])
        m.params = body["p"]
        return m

    def __repr__(self) -> str:  # small, no payload dump
        return (f"Message({self.msg_type}, {self.sender_id}->"
                f"{self.receiver_id}, keys={sorted(self.params)})")
