"""Runnable cross-silo federation: one OS process per silo, real sockets.

The reference's distributed runtime was vestigial library code with no
entry point (SURVEY §2.3); this module makes ours drivable::

    # terminal 1 — the aggregation server (rank 0)
    python -m neuroimagedisttraining_tpu.distributed.run --role server \
        --num_clients 2 --comm_round 5 --model 3dcnn_tiny \
        --dataset synthetic --base_port 29500

    # terminals 2..N+1 — one trainer process per silo (ranks 1..N)
    python -m neuroimagedisttraining_tpu.distributed.run --role client \
        --rank 1 --num_clients 2 --comm_round 5 --model 3dcnn_tiny \
        --dataset synthetic --base_port 29500

Across machines, pass every rank's address once to all processes:
``--hosts 0=10.0.0.1,1=10.0.0.2,2=10.0.0.3`` (each rank listens on
``base_port + rank``). ``--secure`` swaps in the TurboAggregate
additive-share protocol (SecureFedAvgServer/ClientProc): clients upload
share slots of their weighted quantized updates and the server
reconstructs only the aggregate. Add ``--n_aggregators K`` (= K extra
processes with ``--role aggregator --slot_index j``, ranks
num_clients+1+j) for the grouped deployment: slot j rides to aggregator
j, each aggregator forwards only its cross-client slot total, and no
single node — server included — can reconstruct any client::

    # grouped secure aggregation: server + N silos + K aggregators
    python -m ...distributed.run --role aggregator --slot_index 0 \
        --num_clients 2 --n_aggregators 3 --secure ...

Each client trains its own site shard with the real jitted LocalTrainer
(silo k holds site ``(k-1) mod num_sites``); the server runs the
register -> broadcast -> train -> upload -> aggregate -> finish protocol
(cross_silo.py) and prints one JSON line with the final round count and
aggregate param norm.

Fault tolerance (ISSUE 2): ``--transport broker`` swaps the socket plane
for the pub/sub broker (hosted by the server process);
``--fault_spec "crash:3@1,drop:0.1,..."`` wraps each client's transport
in the seeded FaultyCommManager (faults/) so chaos replays bit-identically
from ``--seed``; ``--round_deadline``/``--quorum`` let the server
aggregate survivor subsets instead of hanging on a dead silo, and
``--heartbeat_interval``/``--heartbeat_timeout`` drive the suspicion
machinery. ``scripts/run_chaos_smoke.sh`` exercises the kill-k scenario
end-to-end on both transports. This is the cross-silo deployment shape: bulk
per-silo compute on each silo's own accelerator(s), small model payloads
on the control plane (on a TPU pod, prefer --multihost_coordinator on
the main CLI so bulk tensors ride ICI/DCN collectives instead).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _parse_hosts(spec: str) -> dict[int, str] | None:
    if not spec:
        return None
    out = {}
    for part in spec.split(","):
        r, ip = part.split("=")
        out[int(r)] = ip
    return out


def _build_shard(args, rank: int):
    """(X, y, n) numpy shard for silo ``rank`` + input sample shape."""
    from neuroimagedisttraining_tpu.data import partition as P

    if args.dataset == "synthetic":
        from neuroimagedisttraining_tpu.data.synthetic import (
            generate_synthetic_abcd,
        )

        cohort = generate_synthetic_abcd(
            num_subjects=args.synthetic_num_subjects,
            shape=tuple(args.synthetic_shape),
            num_sites=max(2, args.num_clients), seed=args.seed)
    else:
        from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5

        cohort = load_abcd_hdf5(args.data_dir, lazy=False)
    train_map, _, _ = P.site_partition(cohort["site"], seed=42)
    site = (rank - 1) % len(train_map)
    idx = train_map[site]
    X = np.asarray(cohort["X"])[idx]
    y = np.asarray(cohort["y"])[idx]
    return X, y, len(idx)


def _make_train_fn(args):
    """Silo-local training closure: jitted LocalTrainer epochs on this
    silo's shard (fedavg my_model_trainer semantics, round-decayed lr)."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.config import OptimConfig
    from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer
    from neuroimagedisttraining_tpu.models import create_model

    X, y, n = _build_shard(args, args.rank)
    optim = OptimConfig(lr=args.lr, lr_decay=args.lr_decay,
                        batch_size=args.batch_size, epochs=args.epochs)
    trainer = LocalTrainer(create_model(args.model,
                                        num_classes=args.num_classes),
                           optim, num_classes=args.num_classes)
    Xd, yd = jnp.asarray(X), jnp.asarray(y)

    @jax.jit
    def step(params, bstats, rng, lr):
        cs = ClientState(params=params, batch_stats=bstats,
                         opt_state=trainer.opt.init(params), rng=rng)
        cs, loss = trainer.local_train(
            cs, Xd, yd, n, lr, epochs=optim.epochs,
            batch_size=optim.batch_size, max_samples=Xd.shape[0])
        return cs.params, cs.batch_stats, loss

    def train_fn(params_np, round_idx):
        # server ships {params, batch_stats}; silo trains and ships back
        params = jax.tree.map(jnp.asarray, params_np["params"])
        bstats = jax.tree.map(jnp.asarray, params_np["batch_stats"])
        rng = jax.random.fold_in(jax.random.key(args.seed + 17 + args.rank),
                                 round_idx)
        lr = jnp.float32(args.lr) * jnp.float32(args.lr_decay) ** round_idx
        p, b, loss = step(params, bstats, rng, lr)
        print(f"[silo {args.rank}] round {round_idx}: "
              f"loss={float(loss):.4f} (n={n})", flush=True)
        return {"params": jax.tree.map(np.asarray, p),
                "batch_stats": jax.tree.map(np.asarray, b)}, float(n)

    return train_fn


def _make_comm(args, rank: int, host_map):
    """Build the rank's transport per ``--transport``; client ranks are
    wrapped in ``FaultyCommManager`` when ``--fault_spec`` is given (the
    transports' own code is untouched). Returns ``(comm, broker)`` —
    ``comm`` may be None (socket, no faults: the manager builds its
    default), ``broker`` is the in-process daemon on the server rank."""
    import time

    comm = None
    broker = None
    world_size = args.num_clients + 1 + args.n_aggregators
    if args.transport == "broker":
        from neuroimagedisttraining_tpu.distributed.broker import (
            BrokerCommManager, MessageBroker,
        )

        port = args.broker_port or args.base_port
        if rank == 0:
            broker = MessageBroker(host="0.0.0.0", port=port)
            comm = BrokerCommManager("127.0.0.1", broker.port, client_id=0,
                                     client_num=args.num_clients)
        else:
            host = (host_map or {}).get(0, "127.0.0.1")
            # the server process hosts the broker daemon — back off while
            # it boots (model build + jit compile precede the broker)
            delay, deadline = 0.25, time.monotonic() + 300
            while True:
                try:
                    comm = BrokerCommManager(host, port, client_id=rank,
                                             client_num=args.num_clients)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(delay)
                    delay = min(2.0, delay * 2)
    elif args.fault_spec and rank != 0:
        from neuroimagedisttraining_tpu.distributed.comm import (
            SocketCommManager,
        )

        comm = SocketCommManager(rank, world_size, host_map=host_map,
                                 base_port=args.base_port)
    if args.fault_spec and rank != 0 and comm is not None:
        from neuroimagedisttraining_tpu.faults import (
            FaultSchedule, FaultyCommManager, parse_fault_spec,
        )

        comm = FaultyCommManager(
            comm, FaultSchedule(parse_fault_spec(args.fault_spec),
                                args.seed), rank)
    return comm, broker


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="neuroimagedisttraining_tpu.distributed.run",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--role", required=True,
                    choices=("server", "client", "aggregator"))
    ap.add_argument("--rank", type=int, default=0,
                    help="client rank 1..num_clients (server is 0); "
                         "aggregator j is rank num_clients+1+j")
    ap.add_argument("--slot_index", type=int, default=0,
                    help="aggregator role: which share slot this process "
                         "aggregates (0..n_aggregators-1)")
    ap.add_argument("--n_aggregators", type=int, default=0,
                    help="secure mode: route share slot j to a distinct "
                         "aggregator process instead of the server "
                         "(TurboAggregate grouped aggregation); must equal "
                         "--mpc_n_shares; 0 = single-server degenerate "
                         "mode")
    ap.add_argument("--num_clients", type=int, required=True)
    ap.add_argument("--comm_round", type=int, default=5)
    ap.add_argument("--base_port", type=int, default=29500)
    ap.add_argument("--hosts", type=str, default="",
                    help="rank=ip,... (default: all localhost)")
    ap.add_argument("--transport", type=str, default="socket",
                    choices=("socket", "broker"),
                    help="control-plane transport: point-to-point TCP "
                         "(every rank listens on base_port+rank) or the "
                         "in-repo pub/sub broker (MQTT topic scheme; the "
                         "server process hosts the broker daemon)")
    ap.add_argument("--broker_port", type=int, default=0,
                    help="broker transport: the broker daemon's port "
                         "(0 = base_port); clients connect to rank 0's "
                         "host at this port")
    ap.add_argument("--fault_spec", type=str, default="",
                    help="deterministic chaos schedule applied to client "
                         "ranks via FaultyCommManager: 'crash:RANK@ROUND,"
                         "crash_prob:P,straggle:P:MAX_S,drop:P,dup:P,"
                         "disconnect:P' — replays identically from "
                         "--seed on every rank")
    ap.add_argument("--round_deadline", type=float, default=0.0,
                    help="server: per-round deadline seconds; when it "
                         "fires with >= --quorum uploads the round "
                         "aggregates over the survivors (sample-count "
                         "re-weighted) instead of hanging forever")
    ap.add_argument("--quorum", type=int, default=0,
                    help="min uploads for a deadline aggregation "
                         "(0 = simple majority when --round_deadline is "
                         "set, else all clients)")
    ap.add_argument("--heartbeat_interval", type=float, default=0.0,
                    help="clients: liveness beat period seconds "
                         "(0 = no heartbeats)")
    ap.add_argument("--heartbeat_timeout", type=float, default=0.0,
                    help="server: mark a client suspect once its "
                         "heartbeat is older than this (0 = off)")
    ap.add_argument("--secure", action="store_true",
                    help="TurboAggregate additive-share aggregation over "
                         "the control plane")
    ap.add_argument("--mpc_n_shares", type=int, default=3)
    ap.add_argument("--mpc_frac_bits", type=int, default=16)
    ap.add_argument("--model", type=str, default="3dcnn_tiny")
    ap.add_argument("--num_classes", type=int, default=1)
    ap.add_argument("--dataset", type=str, default="synthetic",
                    choices=("synthetic", "abcd_h5"))
    ap.add_argument("--data_dir", type=str, default="")
    ap.add_argument("--synthetic_num_subjects", type=int, default=64)
    ap.add_argument("--synthetic_shape", type=int, nargs=3,
                    default=[12, 14, 12])
    ap.add_argument("--batch_size", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--lr_decay", type=float, default=0.998)
    ap.add_argument("--seed", type=int, default=1024)
    ap.add_argument("--force_cpu", action="store_true",
                    help="pin JAX to the CPU backend (e.g. several silo "
                         "processes on one machine sharing a tunneled "
                         "accelerator)")
    args = ap.parse_args(argv)
    if args.role == "aggregator":
        if args.n_aggregators <= 0:
            ap.error("--role aggregator requires --n_aggregators > 0 "
                     "(same value on every rank)")
        if not 0 <= args.slot_index < args.n_aggregators:
            ap.error(f"--slot_index ({args.slot_index}) must be in "
                     f"[0, {args.n_aggregators})")
    if args.n_aggregators > 0:
        # fail fast on EVERY rank: mismatched flags would otherwise leave
        # aggregator processes blocked forever (no slot, no FINISH)
        if not args.secure:
            ap.error("--n_aggregators requires --secure")
        if args.n_aggregators != args.mpc_n_shares:
            ap.error(f"--n_aggregators ({args.n_aggregators}) must equal "
                     f"--mpc_n_shares ({args.mpc_n_shares}): slot j "
                     "routes to aggregator j")
    if args.transport == "broker" and args.n_aggregators > 0:
        ap.error("--transport broker routes messages through the MQTT "
                 "topic scheme (server <-> client only); the grouped "
                 "multi-aggregator deployment needs --transport socket")
    if args.round_deadline > 0 and args.quorum == 0:
        args.quorum = args.num_clients // 2 + 1  # simple majority
    if args.heartbeat_timeout > 0 and not (
            0 < args.heartbeat_interval < args.heartbeat_timeout):
        # beats slower than the timeout would mark every HEALTHY client
        # suspect mid-round and silently truncate aggregates
        ap.error("--heartbeat_timeout requires 0 < --heartbeat_interval "
                 f"< timeout (got interval={args.heartbeat_interval}, "
                 f"timeout={args.heartbeat_timeout})")
    host_map = _parse_hosts(args.hosts)
    if args.force_cpu:
        from neuroimagedisttraining_tpu.parallel.mesh import (
            provision_virtual_devices,
        )
        provision_virtual_devices(1)

    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc, FedAvgServer, SecureFedAvgClientProc,
        SecureFedAvgServer, SlotAggregatorProc,
    )

    if args.role == "aggregator":
        agg = SlotAggregatorProc(args.slot_index, args.num_clients,
                                 args.n_aggregators,
                                 base_port=args.base_port,
                                 host_map=host_map)
        print(f"[aggregator {args.slot_index}] rank {agg.rank} "
              f"aggregating slot {args.slot_index}", flush=True)
        agg.run()
        print(json.dumps({"role": "aggregator",
                          "slot_index": args.slot_index,
                          "clients_seen": len(agg.received)}), flush=True)
        return 0

    if args.role == "server":
        import jax

        from neuroimagedisttraining_tpu.config import OptimConfig
        from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
        from neuroimagedisttraining_tpu.models import create_model

        # seed-deterministic init: every process derives the same model
        trainer = LocalTrainer(
            create_model(args.model, num_classes=args.num_classes),
            OptimConfig(), num_classes=args.num_classes)
        shape = ((1,) + tuple(args.synthetic_shape)
                 if args.dataset == "synthetic" else None)
        if shape is None:
            from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5

            X0 = load_abcd_hdf5(args.data_dir, lazy=True)
            shape = (1,) + tuple(X0["X"].shape[1:])
            X0["file"].close()
        import jax.numpy as jnp

        gs = trainer.init_client_state(jax.random.key(args.seed),
                                       jnp.zeros(shape, jnp.float32))
        init = {"params": jax.tree.map(np.asarray, gs.params),
                "batch_stats": jax.tree.map(np.asarray, gs.batch_stats)}
        cls = SecureFedAvgServer if args.secure else FedAvgServer
        kw = ({"frac_bits": args.mpc_frac_bits,
               "n_aggregators": args.n_aggregators} if args.secure else {})
        comm, broker = _make_comm(args, 0, host_map)
        server = cls(init, args.comm_round, args.num_clients,
                     base_port=args.base_port, host_map=host_map,
                     comm=comm, round_deadline=args.round_deadline,
                     quorum=args.quorum,
                     heartbeat_timeout=args.heartbeat_timeout, **kw)
        print(f"[server] {args.transport} control plane on port "
              f"{args.broker_port or args.base_port}; waiting for "
              f"{args.num_clients} silos", flush=True)
        server.run()
        if broker is not None:
            broker.stop()
        norm = float(np.sqrt(sum(
            float(np.sum(np.asarray(v, np.float64) ** 2))
            for v in jax.tree.leaves(server.params))))
        print(json.dumps({"rounds_completed": len(server.history),
                          "clients": args.num_clients,
                          "secure": bool(args.secure),
                          "transport": args.transport,
                          "suspects": sorted(server.suspect_clients()),
                          "final_param_norm": round(norm, 6)}), flush=True)
        return 0

    train_fn = _make_train_fn(args)
    cls = SecureFedAvgClientProc if args.secure else FedAvgClientProc
    kw = ({"n_shares": args.mpc_n_shares, "frac_bits": args.mpc_frac_bits,
           "mpc_seed": args.seed,
           "n_aggregators": args.n_aggregators} if args.secure else {})
    comm, _ = _make_comm(args, args.rank, host_map)
    client = cls(args.rank, args.num_clients, train_fn,
                 base_port=args.base_port, host_map=host_map, comm=comm,
                 heartbeat_interval=args.heartbeat_interval, **kw)
    print(f"[silo {args.rank}] joining server", flush=True)
    client.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
