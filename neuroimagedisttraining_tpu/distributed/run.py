"""Runnable cross-silo federation: one OS process per silo, real sockets.

The reference's distributed runtime was vestigial library code with no
entry point (SURVEY §2.3); this module makes ours drivable::

    # terminal 1 — the aggregation server (rank 0)
    python -m neuroimagedisttraining_tpu.distributed.run --role server \
        --num_clients 2 --comm_round 5 --model 3dcnn_tiny \
        --dataset synthetic --base_port 29500

    # terminals 2..N+1 — one trainer process per silo (ranks 1..N)
    python -m neuroimagedisttraining_tpu.distributed.run --role client \
        --rank 1 --num_clients 2 --comm_round 5 --model 3dcnn_tiny \
        --dataset synthetic --base_port 29500

Across machines, pass every rank's address once to all processes:
``--hosts 0=10.0.0.1,1=10.0.0.2,2=10.0.0.3`` (each rank listens on
``base_port + rank``). ``--secure`` swaps in the TurboAggregate
additive-share protocol (SecureFedAvgServer/ClientProc): clients upload
share slots of their weighted quantized updates and the server
reconstructs only the aggregate. Add ``--n_aggregators K`` (= K extra
processes with ``--role aggregator --slot_index j``, ranks
num_clients+1+j) for the grouped deployment: slot j rides to aggregator
j, each aggregator forwards only its cross-client slot total, and no
single node — server included — can reconstruct any client::

    # grouped secure aggregation: server + N silos + K aggregators
    python -m ...distributed.run --role aggregator --slot_index 0 \
        --num_clients 2 --n_aggregators 3 --secure ...

Each client trains its own site shard with the real jitted LocalTrainer
(silo k holds site ``(k-1) mod num_sites``); the server runs the
register -> broadcast -> train -> upload -> aggregate -> finish protocol
(cross_silo.py) and prints one JSON line with the final round count and
aggregate param norm.

Fault tolerance (ISSUE 2): ``--transport broker`` swaps the socket plane
for the pub/sub broker (hosted by the server process);
``--fault_spec "crash:3@1,drop:0.1,..."`` wraps each client's transport
in the seeded FaultyCommManager (faults/) so chaos replays bit-identically
from ``--seed``; ``--round_deadline``/``--quorum`` let the server
aggregate survivor subsets instead of hanging on a dead silo, and
``--heartbeat_interval``/``--heartbeat_timeout`` drive the suspicion
machinery. ``scripts/run_chaos_smoke.sh`` exercises the kill-k scenario
end-to-end on both transports.

Wire codec (ISSUE 3): ``--wire_codec delta+sparse+quant`` makes every
silo upload a tagged codec frame (codec/) — delta vs the round's sync,
sparse packing, int8/bf16 quantization — which the server decodes before
aggregation; ``--wire_mask_density 0.5`` additionally emulates the
masked-engine deployment (every rank derives the same seeded mask, silos
train masked, frames ship bitmap-free). ``scripts/run_wire_bench.sh``
A/Bs the bytes-on-wire against the dense format using the transports'
byte counters. This is the cross-silo deployment shape: bulk per-silo
compute on each silo's own accelerator(s), small model payloads on the
control plane (on a TPU pod, prefer --multihost_coordinator on the main
CLI so bulk tensors ride ICI/DCN collectives instead).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def dispatch_fallback_note(k: int) -> str | None:
    """Why ``--rounds_per_dispatch`` collapses to 1 on the distributed
    transport (logged once at startup; None when k <= 1 — nothing to
    say). The fused lax.scan driver (ISSUE 4) requires K host-free
    rounds; the cross-silo protocol is a host round-trip PER ROUND by
    construction (broadcast -> silo train -> upload -> aggregate over
    real sockets)."""
    if k <= 1:
        return None
    from neuroimagedisttraining_tpu.engines import program as round_program

    return (f"rounds_per_dispatch={k} requested; "
            + round_program.report_fallback("distributed",
                                            "distributed-control-plane"))


def cohort_fallback_note(n: int) -> str | None:
    """Why ``--client_mesh`` (ISSUE 6) has nothing to shard on the
    distributed transport (printed once at startup; None when n <= 0).
    The cohort-sharded round maps an IN-PROCESS ``[C, ...]`` client
    stack onto a device mesh; here each rank is one silo training only
    its own cohort — the client axis is the set of OS processes."""
    if n <= 0:
        return None
    from neuroimagedisttraining_tpu.engines import program as round_program

    return (f"client_mesh={n} requested; "
            + round_program.report_fallback(
                "distributed", "distributed-no-client-axis"))


def _parse_hosts(spec: str) -> dict[int, str] | None:
    if not spec:
        return None
    out = {}
    for part in spec.split(","):
        r, ip = part.split("=")
        out[int(r)] = ip
    return out


def _build_shard(args, rank: int):
    """(X, y, n) numpy shard for silo ``rank`` + input sample shape."""
    from neuroimagedisttraining_tpu.data import partition as P

    if args.dataset == "synthetic":
        from neuroimagedisttraining_tpu.data.synthetic import (
            generate_synthetic_abcd,
        )

        cohort = generate_synthetic_abcd(
            num_subjects=args.synthetic_num_subjects,
            shape=tuple(args.synthetic_shape),
            num_sites=max(2, args.num_clients), seed=args.seed,
            signal=args.synthetic_signal)
    else:
        from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5

        cohort = load_abcd_hdf5(args.data_dir, lazy=False)
    train_map, _, _ = P.site_partition(cohort["site"], seed=42)
    site = (rank - 1) % len(train_map)
    idx = train_map[site]
    X = np.asarray(cohort["X"])[idx]
    y = np.asarray(cohort["y"])[idx]
    return X, y, len(idx)


def _optim_from_args(args):
    """One OptimConfig for every silo-side trainer in this process —
    including the mixed-precision train-step contract (ISSUE 10), so a
    cross-silo silo trains at the same precision the simulated engines
    would (fp32 master weights on the wire either way)."""
    from neuroimagedisttraining_tpu.config import OptimConfig

    return OptimConfig(lr=args.lr, lr_decay=args.lr_decay,
                       batch_size=args.batch_size, epochs=args.epochs,
                       precision=args.precision,
                       loss_scale=args.loss_scale,
                       fused_update=args.fused_update)


def _create_model_from_args(args):
    """Model build honoring the precision contract (compute dtype from
    --precision; master weights stay f32) and the --remat policy ("auto"
    defers to the model family's default — the single-silo runner has no
    federation shape to pick from)."""
    from neuroimagedisttraining_tpu.core.optim import compute_dtype
    from neuroimagedisttraining_tpu.models import create_model

    remat = {"auto": None, "none": False, "stem": "stem",
             "all": True}[args.remat]
    return create_model(args.model, num_classes=args.num_classes,
                        dtype=compute_dtype(args.precision), remat=remat)


def _seed_init_state(args):
    """``(trainer, init ClientState)`` — every rank derives the identical
    model from ``--seed``, so init broadcast, delta references, and wire
    masks agree across processes with no extra exchange."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer

    trainer = LocalTrainer(
        _create_model_from_args(args),
        _optim_from_args(args), num_classes=args.num_classes)
    if args.dataset == "synthetic":
        shape = (1,) + tuple(args.synthetic_shape)
    else:
        from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5

        X0 = load_abcd_hdf5(args.data_dir, lazy=True)
        shape = (1,) + tuple(X0["X"].shape[1:])
        X0["file"].close()
    gs = trainer.init_client_state(jax.random.key(args.seed),
                                   jnp.zeros(shape, jnp.float32))
    return trainer, gs


def _build_wire_masks(args, gs=None):
    """Deterministic shared pruning mask for ``--wire_mask_density``: the
    masked-engine deployment shape (SalientGrads ships its phase-1 global
    mask to every silo) emulated with a seeded uniform mask every rank
    derives identically — the codec's mask handoff then packs uploads
    bitmap-free (codec/wire.py shared-mask mode). Pass ``gs`` when the
    caller already derived the seed-deterministic init state (the server
    does — no second model build/jit)."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.ops import masks as Mk

    if gs is None:
        _, gs = _seed_init_state(args)
    sp = Mk.calculate_sparsities(gs.params, "uniform",
                                 dense_ratio=args.wire_mask_density)
    pm = Mk.init_masks(jax.random.key(args.seed + 97), gs.params, sp)
    tree = {"params": pm,
            "batch_stats": jax.tree.map(jnp.ones_like, gs.batch_stats)}
    return jax.tree.map(np.asarray, tree)


def _make_train_fn(args):
    """``(train_fn, wire_masks)``: the silo-local training closure —
    jitted LocalTrainer epochs on this silo's shard (fedavg
    my_model_trainer semantics, round-decayed lr) — plus the shared wire
    mask when ``--wire_mask_density`` is set, derived from THIS
    trainer's seed-deterministic init (one model build per client, not
    two). With a mask the silo trains MASKED (post-step re-mask, the
    SalientGrads/DisPFL client shape) so its uploads are sparse by
    construction — the deployment the codec's mask-sparse stage packs."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer

    X, y, n = _build_shard(args, args.rank)
    optim = _optim_from_args(args)
    trainer = LocalTrainer(_create_model_from_args(args),
                           optim, num_classes=args.num_classes)
    wire_masks = None
    if args.wire_mask_density > 0:
        # derive the shared mask from THIS trainer's init state (the
        # seed-deterministic params every rank agrees on) instead of a
        # second model build + jitted init inside _build_wire_masks
        gs = trainer.init_client_state(
            jax.random.key(args.seed),
            jnp.zeros((1,) + X.shape[1:], jnp.float32))
        wire_masks = _build_wire_masks(args, gs)
    Xd, yd = jnp.asarray(X), jnp.asarray(y)
    mask_d = (jax.tree.map(jnp.asarray, wire_masks["params"])
              if wire_masks is not None else None)

    @jax.jit
    def step(params, bstats, rng, lr):
        cs = ClientState(params=params, batch_stats=bstats,
                         opt_state=trainer.opt.init(params), rng=rng)
        cs, loss = trainer.local_train(
            cs, Xd, yd, n, lr, epochs=optim.epochs,
            batch_size=optim.batch_size, max_samples=Xd.shape[0],
            mask=mask_d)
        return cs.params, cs.batch_stats, loss

    def train_fn(params_np, round_idx):
        # server ships {params, batch_stats}; silo trains and ships back
        params = jax.tree.map(jnp.asarray, params_np["params"])
        bstats = jax.tree.map(jnp.asarray, params_np["batch_stats"])
        rng = jax.random.fold_in(jax.random.key(args.seed + 17 + args.rank),
                                 round_idx)
        lr = jnp.float32(args.lr) * jnp.float32(args.lr_decay) ** round_idx
        p, b, loss = step(params, bstats, rng, lr)
        print(f"[silo {args.rank}] round {round_idx}: "
              f"loss={float(loss):.4f} (n={n})", flush=True)
        return {"params": jax.tree.map(np.asarray, p),
                "batch_stats": jax.tree.map(np.asarray, b)}, float(n)

    return train_fn, wire_masks


def _make_comm(args, rank: int, host_map):
    """Build the rank's transport per ``--transport``; client ranks are
    wrapped in ``FaultyCommManager`` when ``--fault_spec`` is given (the
    transports' own code is untouched). Returns ``(comm, broker)`` —
    ``comm`` may be None (socket, no faults: the manager builds its
    default), ``broker`` is the in-process daemon on the server rank."""
    import time

    comm = None
    broker = None
    world_size = args.num_clients + 1 + args.n_aggregators
    if args.transport == "broker":
        from neuroimagedisttraining_tpu.distributed.broker import (
            BrokerCommManager, MessageBroker,
        )

        port = args.broker_port or args.base_port
        if rank == 0:
            broker = MessageBroker(host="0.0.0.0", port=port)
            comm = BrokerCommManager("127.0.0.1", broker.port, client_id=0,
                                     client_num=args.num_clients)
        else:
            host = (host_map or {}).get(0, "127.0.0.1")
            # the server process hosts the broker daemon — back off while
            # it boots (model build + jit compile precede the broker)
            delay, deadline = 0.25, time.monotonic() + 300
            while True:
                try:
                    comm = BrokerCommManager(host, port, client_id=rank,
                                             client_num=args.num_clients)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(delay)
                    delay = min(2.0, delay * 2)
    elif args.fault_spec and rank != 0:
        from neuroimagedisttraining_tpu.distributed.comm import (
            SocketCommManager,
        )

        comm = SocketCommManager(rank, world_size, host_map=host_map,
                                 base_port=args.base_port)
    if args.fault_spec and rank != 0 and comm is not None:
        from neuroimagedisttraining_tpu.faults import (
            FaultSchedule, FaultyCommManager, parse_fault_spec,
        )

        comm = FaultyCommManager(
            comm, FaultSchedule(parse_fault_spec(args.fault_spec),
                                args.seed), rank)
    return comm, broker


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="neuroimagedisttraining_tpu.distributed.run",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--role", required=True,
                    choices=("server", "client", "aggregator"))
    ap.add_argument("--rank", type=int, default=0,
                    help="client rank 1..num_clients (server is 0); "
                         "aggregator j is rank num_clients+1+j")
    ap.add_argument("--slot_index", type=int, default=0,
                    help="aggregator role: which share slot this process "
                         "aggregates (0..n_aggregators-1)")
    ap.add_argument("--n_aggregators", type=int, default=0,
                    help="secure mode: route share slot j to a distinct "
                         "aggregator process instead of the server "
                         "(TurboAggregate grouped aggregation); must equal "
                         "--mpc_n_shares; 0 = single-server degenerate "
                         "mode")
    ap.add_argument("--num_clients", type=int, required=True)
    ap.add_argument("--comm_round", type=int, default=5)  # nidt: allow[flag-config-cross-cli-drift] -- smoke-scale default; the multiprocess runner ships tiny CPU-safe cells
    ap.add_argument("--base_port", type=int, default=29500)
    ap.add_argument("--hosts", type=str, default="",
                    help="rank=ip,... (default: all localhost)")
    ap.add_argument("--transport", type=str, default="socket",
                    choices=("socket", "broker"),
                    help="control-plane transport: point-to-point TCP "
                         "(every rank listens on base_port+rank) or the "
                         "in-repo pub/sub broker (MQTT topic scheme; the "
                         "server process hosts the broker daemon)")
    ap.add_argument("--broker_port", type=int, default=0,
                    help="broker transport: the broker daemon's port "
                         "(0 = base_port); clients connect to rank 0's "
                         "host at this port")
    ap.add_argument("--fault_spec", type=str, default="",
                    help="deterministic chaos schedule applied to client "
                         "ranks via FaultyCommManager: 'crash:RANK@ROUND,"
                         "crash_prob:P,straggle:P:MAX_S,drop:P,dup:P,"
                         "disconnect:P,byz:RANK@ROUND:KIND,"
                         "byz_prob:P[:KIND]' — replays identically from "
                         "--seed on every rank; byz silos upload "
                         "KIND-corrupted values (sign_flip | scale:K | "
                         "gauss:STD | nonfinite, faults/adversary.py) "
                         "transformed BEFORE the wire codec")
    ap.add_argument("--defense", "--defense_type", dest="defense",
                    type=str, default="none",
                    help="server aggregation defense (core/robust.py): "
                         "none | norm_diff_clipping | weak_dp | "
                         "trimmed_mean | median | krum | multi_krum | "
                         "geometric_median — the order-statistic family "
                         "replaces the weighted mean and tolerates up "
                         "to --byz_f Byzantine silos; validated at "
                         "startup on every rank")
    ap.add_argument("--byz_f", type=int, default=1,
                    help="assumed Byzantine silo count f for the order-"
                         "statistic defenses (trim depth per side / "
                         "Krum neighborhood; krum needs num_clients >= "
                         "f+3, trimmed_mean/median need 2f < n) and the "
                         "quarantine budget (at most f silos "
                         "quarantined at once)")
    ap.add_argument("--geomed_iters", type=int, default=8,
                    help="geometric_median: fixed Weiszfeld iterations")
    ap.add_argument("--norm_bound", type=float, default=5.0,
                    help="clip threshold for norm_diff_clipping/weak_dp")
    ap.add_argument("--stddev", type=float, default=0.05,
                    help="weak_dp per-client Gaussian noise stddev "
                         "(keys derive from --seed per round/silo)")
    ap.add_argument("--quarantine_rounds", type=int, default=0,
                    help="server: > 0 arms Byzantine DETECTION — "
                         "update-norm/cosine outlier scoring feeds "
                         "strike counters, and --outlier_threshold "
                         "strikes quarantine a silo for this many "
                         "rounds (uploads dropped, codec error-"
                         "feedback reset on release); 0 = off")
    ap.add_argument("--outlier_threshold", type=int, default=2,
                    help="value-anomaly strikes before a silo is "
                         "quarantined (clean rounds forgive one strike "
                         "each)")
    ap.add_argument("--async_server", action="store_true",
                    help="server runs the FedBuff-style buffered "
                         "asynchronous control plane (asyncfl/): the "
                         "selector comm core holds every connection in "
                         "one event loop, uploads are accepted "
                         "continuously and aggregated every --buffer_k "
                         "arrivals with (1+tau)^-alpha staleness "
                         "weighting, broadcasts are version-tagged, and "
                         "there is NO round barrier (comm_round counts "
                         "aggregations). Clients run unchanged")
    ap.add_argument("--buffer_k", type=int, default=0,
                    help="async server: aggregate every K accepted "
                         "uploads (0 = num_clients)")
    ap.add_argument("--staleness_alpha", type=float, default=0.5,
                    help="async server: polynomial staleness exponent; "
                         "an upload tau versions stale weighs "
                         "n * (1+tau)^-alpha")
    ap.add_argument("--ingest_workers", type=int, default=0,
                    help="async server: shard the ingest plane across N "
                         "selector worker PROCESSES on one SO_REUSEPORT "
                         "port (asyncfl/ingest.py) — each worker runs "
                         "the admission gates and folds accepted "
                         "uploads into an exact int64 partial "
                         "aggregate; the root merges partials in "
                         "worker-id order, bitwise-equal to the "
                         "single-process fold. 0 = the single-process "
                         "BufferedFedAvgServer")
    ap.add_argument("--regions", type=int, default=0,
                    help="async server: interpose N regional "
                         "sub-aggregator PROCESSES between the ingest "
                         "workers and the root (asyncfl/region.py) — "
                         "each region owns --ingest_workers workers on "
                         "the shared SO_REUSEPORT port, folds their "
                         "partials locally and ships ONE merged partial "
                         "upstream per flush interval; the root merges "
                         "region partials in region-id order, "
                         "bitwise-equal to the flat fold. 0 = flat root")
    ap.add_argument("--ingest_shm", action="store_true",
                    help="ingest workers hand partials to their parent "
                         "over double-buffered shared-memory slabs "
                         "instead of the pickled pipe (same-host "
                         "fast path; the pipe remains the cross-host "
                         "fallback)")
    ap.add_argument("--sync_delta", action="store_true",
                    help="changed-version sync replies to opted-in "
                         "clients ship the lossless byte delta against "
                         "the client's last-synced version from the "
                         "broadcast ring (dense fallback when the base "
                         "left the ring)")
    ap.add_argument("--max_staleness", type=int, default=20,
                    help="async server: uploads staler than this many "
                         "versions are dropped at admission (with a "
                         "logged reason); also bounds the ring of "
                         "historical params kept as codec delta "
                         "references")
    ap.add_argument("--round_deadline", type=float, default=0.0,
                    help="server: per-round deadline seconds; when it "
                         "fires with >= --quorum uploads the round "
                         "aggregates over the survivors (sample-count "
                         "re-weighted) instead of hanging forever")
    ap.add_argument("--quorum", type=int, default=0,
                    help="min uploads for a deadline aggregation "
                         "(0 = simple majority when --round_deadline is "
                         "set, else all clients)")
    ap.add_argument("--heartbeat_interval", type=float, default=0.0,
                    help="clients: liveness beat period seconds "
                         "(0 = no heartbeats)")
    ap.add_argument("--heartbeat_timeout", type=float, default=0.0,
                    help="server: mark a client suspect once its "
                         "heartbeat is older than this (0 = off)")
    ap.add_argument("--wire_codec", type=str, default="none",
                    help="model-update wire codec (codec/): stages "
                         "joined by '+', e.g. none | delta | sparse | "
                         "quant | delta+sparse+quant (quant16 = bf16). "
                         "Uploads ride as tagged frames the server "
                         "decodes before aggregation; the downlink sync "
                         "stays dense (reference-chain safety)")
    ap.add_argument("--wire_topk_ratio", type=float, default=0.25,
                    help="sparse stage without masks: keep fraction for "
                         "magnitude top-k (error-feedback accumulated "
                         "per silo)")
    ap.add_argument("--wire_mask_density", type=float, default=0.0,
                    help="> 0 emulates a masked engine deployment: every "
                         "rank derives the same seeded pruning mask at "
                         "this density, silos train masked, and the "
                         "codec's sparse stage packs uploads bitmap-free "
                         "(mask handoff). 0 = dense training")
    ap.add_argument("--secure", action="store_true",
                    help="TurboAggregate additive-share aggregation over "
                         "the control plane (dense int64 share slots)")
    ap.add_argument("--secure_quant", action="store_true",
                    help="secure QUANTIZED aggregation "
                         "(privacy/secure_quant.py): uploads ride as "
                         "field-element frames in a small GF(p) — one "
                         "uintN residue per parameter plus seed-expanded "
                         "mask slots — so secure aggregation costs a "
                         "FRACTION of the dense wire instead of 6x it. "
                         "Implies the secure protocol; composes with "
                         "clip-family --defense (enforced client-side) "
                         "and with --async_server (one-phase, integer-"
                         "scaled staleness weights); see ARCHITECTURE.md "
                         "'Privacy plane' for the full matrix")
    ap.add_argument("--secure_quant_field_bits", type=int, default=16,
                    choices=(8, 16, 32),
                    help="secure_quant field width: p = largest prime "
                         "below 2^bits; the wire ships one uintN residue "
                         "per parameter (16 -> uint16)")
    ap.add_argument("--secure_quant_frac_bits", type=int, default=10,
                    help="secure_quant fixed-point fraction bits; the "
                         "aggregate headroom vs p and the cohort is "
                         "validated at startup")
    ap.add_argument("--dp_delta", type=float, default=1e-5,
                    help="target delta for the weak_dp RDP accountant's "
                         "(epsilon, delta) report (privacy/accountant.py)")
    ap.add_argument("--mpc_n_shares", type=int, default=3)
    ap.add_argument("--mpc_frac_bits", type=int, default=16)
    ap.add_argument("--model", type=str, default="3dcnn_tiny")  # nidt: allow[flag-config-cross-cli-drift] -- smoke-scale default; the multiprocess runner ships tiny CPU-safe cells
    ap.add_argument("--num_classes", type=int, default=1)
    ap.add_argument("--dataset", type=str, default="synthetic",
                    choices=("synthetic", "abcd_h5"))  # nidt: allow[flag-config-cross-cli-drift] -- smoke default + the only datasets the socket runner feeds
    ap.add_argument("--data_dir", type=str, default="")  # nidt: allow[flag-config-cross-cli-drift] -- smoke-scale default; the multiprocess runner ships tiny CPU-safe cells
    ap.add_argument("--synthetic_num_subjects", type=int, default=64)  # nidt: allow[flag-config-cross-cli-drift] -- smoke-scale default; the multiprocess runner ships tiny CPU-safe cells
    ap.add_argument("--synthetic_shape", type=int, nargs=3,
                    default=[12, 14, 12])  # nidt: allow[flag-config-cross-cli-drift] -- smoke-scale default; the multiprocess runner ships tiny CPU-safe cells
    ap.add_argument("--synthetic_signal", type=float, default=12.0)
    ap.add_argument("--batch_size", type=int, default=8)  # nidt: allow[flag-config-cross-cli-drift] -- smoke-scale default; the multiprocess runner ships tiny CPU-safe cells
    ap.add_argument("--epochs", type=int, default=1)  # nidt: allow[flag-config-cross-cli-drift] -- smoke-scale default; the multiprocess runner ships tiny CPU-safe cells
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--lr_decay", type=float, default=0.998)
    # mixed-precision train step (ISSUE 10) — mirrors the simulated
    # CLI's contract; the wire always carries fp32 master weights
    ap.add_argument("--precision", type=str, default="fp32",
                    choices=("fp32", "bf16_mixed"),
                    help="silo train-step compute dtype; master weights "
                         "(what the wire/codec/secure planes ship) stay "
                         "float32 either way (core/optim.py)")
    ap.add_argument("--loss_scale", type=float, default=1.0,
                    help="fixed loss-scale constant (bf16_mixed only; "
                         "1.0 = off)")
    ap.add_argument("--fused_update", action="store_true",
                    help="fused SGD clip/momentum/update/mask tail "
                         "(ops/fused_update.py; XLA fallback off-TPU)")
    ap.add_argument("--remat", type=str, default="auto",  # nidt: allow[flag-config-cross-cli-drift] -- choices enforced here only; the simulated CLI validates via models/
                    choices=("auto", "none", "stem", "all"),
                    help="3D-model rematerialization policy (auto = "
                         "model-family default; PROFILE.md)")
    ap.add_argument("--seed", type=int, default=1024)
    ap.add_argument("--force_cpu", action="store_true",
                    help="pin JAX to the CPU backend (e.g. several silo "
                         "processes on one machine sharing a tunneled "
                         "accelerator)")
    ap.add_argument("--compile_cache", dest="compile_cache", type=str,
                    default=None,
                    help="persistent XLA compile cache dir shared by "
                         "every silo process (each rank pays the model "
                         "compile once per MACHINE, not per process); "
                         "unset falls back to $NIDT_COMPILE_CACHE, then "
                         "/tmp/nidt_jax_cache; empty string disables")
    ap.add_argument("--rounds_per_dispatch", type=int, default=1,
                    help="accepted for config parity with the main CLI; "
                         "the cross-silo control plane synchronizes with "
                         "every silo each round, so rounds always "
                         "dispatch one at a time here")
    # observability (obs/, ISSUE 9)
    ap.add_argument("--metrics_port", type=int, default=0,
                    help="serve /metrics (Prometheus text) + /healthz "
                         "on this port for the server rank's metrics "
                         "registry (obs/http.py); 0 = off. NOTE: the "
                         "endpoint is unauthenticated and the metrics "
                         "include control-plane state (per-silo DP "
                         "epsilon, upload verdicts) — bind scope via "
                         "--metrics_host")
    ap.add_argument("--metrics_host", type=str, default="0.0.0.0",
                    help="interface the metrics endpoint binds "
                         "(default all interfaces, the Prometheus-"
                         "exporter convention; pass 127.0.0.1 on "
                         "shared hosts)")
    ap.add_argument("--peak_flops", type=float, default=0.0,
                    help="device peak flop/s for the nidt_mfu gauge's "
                         "denominator on SILO ranks (obs/compute.py; "
                         "0 = device-kind estimate / NIDT_PEAK_FLOPS). "
                         "The server rank's /healthz carries the "
                         "compute block either way — a wedged-dispatch "
                         "silo federation is distinguishable from a "
                         "slow one at the liveness probe")
    ap.add_argument("--trace_out", type=str, default="",
                    help="write this process's host-span timeline as "
                         "Chrome trace-event JSON (obs/trace.py, "
                         "Perfetto-loadable) at exit; give each rank "
                         "its own path. Under --ingest_workers N the "
                         "BARE path is the MERGED federation trace "
                         "(root + clock-aligned worker timelines + "
                         "upload flow links, obs/fanin.py) — the "
                         "primary artifact; worker processes write "
                         ".wN-suffixed local secondaries instead of "
                         "clobbering one file")
    ap.add_argument("--flight_events", type=int, default=256,
                    help="flight-recorder ring capacity (obs/flight.py) "
                         "— the last N control-plane decisions kept for "
                         "the post-mortem dump")
    ap.add_argument("--flight_out", type=str, default="",
                    help="flight-recorder dump path: written at end of "
                         "run on the server rank and on any fatal "
                         "failure (failure_context); empty = dumps off")
    # training-health plane (obs/rules.py, ISSUE 15) — server rank only
    ap.add_argument("--health_rules", type=str, default="",
                    help="JSON anomaly-rule manifest extending the "
                         "built-in set (obs/rules.py) on the SERVER "
                         "rank; unknown metric names fail at startup "
                         "against the declared-name list (obs/names.py)")
    ap.add_argument("--health_gate", action="store_true",
                    help="server rank exits nonzero when the run's "
                         "WORST health status was not ok (any anomaly "
                         "rule fired); the machine-readable verdict "
                         "rides the end-of-run JSON either way")
    ap.add_argument("--dp_epsilon_budget", type=float, default=0.0,
                    help="epsilon budget the built-in DP health rules "
                         "judge against (dp-budget-exceeded / "
                         "dp-burn-rate); 0 = no budget rules")
    ap.add_argument("--actions", type=str, default="dry_run",
                    choices=("off", "dry_run", "on"),
                    help="reflex plane (obs/actions.py, ISSUE 20) on "
                         "the SERVER rank: what a firing health rule's "
                         "declared action DOES. off = rules only "
                         "observe; dry_run (default) = would-fire "
                         "dispatches are logged/flight-recorded with "
                         "rule provenance but nothing changes; on = "
                         "actions apply (quarantine the struck silo "
                         "through the strike machinery, escalate the "
                         "defense ladder, halve the async buffer_k and "
                         "raise staleness_alpha)")
    ap.add_argument("--client_mesh", type=int, default=0,
                    help="accepted for config parity with the main CLI; "
                         "each cross-silo rank trains only its own silo, "
                         "so there is no in-process client axis to shard "
                         "(cohort sharding lives in the simulated "
                         "engines, parallel/cohort.py)")
    ap.add_argument("--recipe", type=str, default="",
                    help="autotuner recipe (tune/recipe.py): a "
                         "bench_matrix/recipes/<device_kind>.json path, "
                         "or 'auto' for the committed recipe matching "
                         "this rank's device kind. Applies as config "
                         "DEFAULTS (flags spelled here win, override "
                         "logged); the server rank arms the "
                         "mfu-below-recipe drift rule")
    args = ap.parse_args(argv)
    if args.force_cpu:
        # provision BEFORE any backend touch: --recipe auto resolves
        # the live device kind through jax.devices()
        from neuroimagedisttraining_tpu.parallel.mesh import (
            provision_virtual_devices,
        )
        provision_virtual_devices(1)
    recipe_doc = None
    if args.recipe:
        from neuroimagedisttraining_tpu.tune import recipe as tune_recipe

        try:
            recipe_doc = tune_recipe.resolve_and_load(args.recipe)
            tune_recipe.apply_recipe(
                args, recipe_doc,
                argv if argv is not None else sys.argv[1:])
        except (OSError, ValueError) as e:
            ap.error(f"--recipe: {e}")
    if args.dp_epsilon_budget < 0:
        ap.error(f"--dp_epsilon_budget must be >= 0 (got "
                 f"{args.dp_epsilon_budget})")
    if args.health_rules:
        # manifest errors (bad JSON, unknown metric names, bad
        # comparators) die at argparse on every rank that was handed
        # the flag — never as a silently-never-firing rule mid-run
        from neuroimagedisttraining_tpu.obs import names as obs_names
        from neuroimagedisttraining_tpu.obs import rules as obs_rules

        try:
            for r in obs_rules.load_rules(args.health_rules):
                r.validate(obs_names.DECLARED)
        except (OSError, ValueError, TypeError) as e:
            ap.error(f"--health_rules: {e}")
    if args.peak_flops > 0:
        # arm the MFU denominator on every rank (silo ranks dispatch
        # the training programs; the server rank's /healthz compute
        # block reports its own dispatch liveness either way)
        from neuroimagedisttraining_tpu.obs import compute as obs_compute

        obs_compute.PROFILER.set_peak_flops(args.peak_flops)
    quant_spec = None
    if args.secure_quant:
        args.secure = True  # the quantized path IS the secure protocol
        from neuroimagedisttraining_tpu.privacy import (
            QuantSpec, check_headroom,
        )

        try:
            # field-geometry headroom (aggregate range vs p, int64
            # accumulators vs the cohort) fails HERE, at argparse on
            # every rank — never as silent field wraparound mid-round
            quant_spec = QuantSpec.from_bits(
                args.secure_quant_field_bits,
                args.secure_quant_frac_bits, args.mpc_n_shares)
            check_headroom(quant_spec, args.num_clients)
        except ValueError as e:
            ap.error(str(e))
    if args.rounds_per_dispatch > 1:
        print(f"[dispatch] {dispatch_fallback_note(args.rounds_per_dispatch)}",
              flush=True)
    if args.client_mesh > 0:
        print(f"[cohort] {cohort_fallback_note(args.client_mesh)}",
              flush=True)
    if args.role == "aggregator":
        if args.n_aggregators <= 0:
            ap.error("--role aggregator requires --n_aggregators > 0 "
                     "(same value on every rank)")
        if not 0 <= args.slot_index < args.n_aggregators:
            ap.error(f"--slot_index ({args.slot_index}) must be in "
                     f"[0, {args.n_aggregators})")
    if args.n_aggregators > 0:
        # fail fast on EVERY rank: mismatched flags would otherwise leave
        # aggregator processes blocked forever (no slot, no FINISH)
        if not args.secure:
            ap.error("--n_aggregators requires --secure")
        if args.n_aggregators != args.mpc_n_shares:
            ap.error(f"--n_aggregators ({args.n_aggregators}) must equal "
                     f"--mpc_n_shares ({args.mpc_n_shares}): slot j "
                     "routes to aggregator j")
    if args.transport == "broker" and args.n_aggregators > 0:
        ap.error("--transport broker routes messages through the MQTT "
                 "topic scheme (server <-> client only); the grouped "
                 "multi-aggregator deployment needs --transport socket")
    if args.secure and (args.wire_codec != "none"
                        or args.wire_mask_density > 0):
        ap.error("--secure uploads must ride the wire as field elements: "
                 "the codec would break the GF(p) share algebra or leak "
                 "mask support. The COMPRESSED secure wire is "
                 "--secure_quant (small-field frames, "
                 "privacy/secure_quant.py) — drop --wire_codec/"
                 "--wire_mask_density and add --secure_quant")
    if args.secure_quant and args.n_aggregators > 0:
        ap.error("--secure_quant does not compose with --n_aggregators: "
                 "mask slots ride as PRG seeds, and any node holding a "
                 "client's seeds can expand every non-data slot — use "
                 "the dense --secure protocol for the grouped "
                 "deployment (see ARCHITECTURE.md 'Privacy plane')")
    if not 0.0 <= args.wire_mask_density < 1.0:
        ap.error(f"--wire_mask_density ({args.wire_mask_density}) must "
                 "be in [0, 1)")
    try:
        # fail fast on EVERY rank: only clients parse the spec at
        # runtime, and a typo'd spec crashing the clients would leave
        # the server blocked forever in the registration barrier
        from neuroimagedisttraining_tpu.codec import parse_wire_spec

        parse_wire_spec(args.wire_codec, args.wire_topk_ratio)
    except ValueError as e:
        ap.error(str(e))
    # Byzantine config (ISSUE 5) fails fast on EVERY rank too: a typo'd
    # --defense or byz: directive must die at startup, not mid-round
    try:
        from neuroimagedisttraining_tpu.core import robust
        from neuroimagedisttraining_tpu.faults import parse_fault_spec

        robust.validate_defense(args.defense)
        if args.defense in robust.ROBUST_AGGREGATORS:
            robust._check_f(args.num_clients, args.byz_f, args.defense)
        fault_spec = (parse_fault_spec(args.fault_spec)
                      if args.fault_spec else None)
    except ValueError as e:
        ap.error(str(e))
    if fault_spec is not None and fault_spec.rejoins:
        # fail at startup, not silently mid-run: the chaos wrapper
        # models a crash by latching and stopping the client PROCESS's
        # dispatch — nothing remains to revive at the rejoin round
        ap.error("--fault_spec rejoin: is not supported by the "
                 "multiprocess runner (a crashed client process cannot "
                 "revive itself; FaultyCommManager latches the crash). "
                 "Model rejoin by launching a replacement client "
                 "process (the server's late re-register path), or use "
                 "the asyncfl load harness (asyncfl/loadgen.py) whose "
                 "simulated clients honor rejoin deterministically")
    if args.secure:
        if args.quarantine_rounds > 0:
            ap.error("secure aggregation is incompatible with "
                     "--quarantine_rounds: the outlier scorer has no "
                     "per-silo plaintext to score (see ARCHITECTURE.md "
                     "'Privacy plane')")
        if args.defense != "none" and not args.secure_quant:
            ap.error("--secure (dense) is incompatible with --defense: "
                     "additive-share aggregation never reveals per-silo "
                     "updates to defend over. The clip-family defenses "
                     "(norm_diff_clipping, weak_dp) compose with "
                     "--secure_quant, enforced CLIENT-side pre-share — "
                     "add --secure_quant (see ARCHITECTURE.md 'Privacy "
                     "plane')")
        if args.secure_quant and args.defense in robust.ROBUST_AGGREGATORS:
            ap.error(f"--defense {args.defense} is incompatible with "
                     "secure aggregation (quantized included): order "
                     "statistics have no per-silo plaintext to select "
                     "over; only the clip family composes (client-side) "
                     "— see ARCHITECTURE.md 'Privacy plane'")
        if fault_spec is not None and fault_spec.any_value_faults:
            ap.error("--secure cannot simulate byz: value faults (the "
                     "share algebra hides the very values the attack "
                     "would corrupt; see cross_silo)")
    if args.async_server:
        # async incompatibilities fail at STARTUP on every rank, like
        # the secure/codec rejections — never mid-run
        if args.secure and not args.secure_quant:
            ap.error("--async_server is incompatible with dense "
                     "--secure: the two-phase secure weight exchange "
                     "(every client's normalized weight depends on every "
                     "other phase-A reporter) IS a round barrier — "
                     "exactly what the buffered asynchronous protocol "
                     "removes. --secure_quant composes: its one-phase "
                     "frames need no weight exchange (staleness weights "
                     "fold inside the field; see asyncfl/server.py)")
        if args.transport == "broker":
            ap.error("--async_server pairs with the selector socket "
                     "core (asyncfl/loop.py); the broker daemon is a "
                     "thread-per-connection transport with its own "
                     "scaling story — use --transport socket")
        if args.round_deadline > 0 or args.quorum > 0:
            ap.error("--async_server has no round barrier: "
                     "--round_deadline/--quorum do not apply (uploads "
                     "aggregate every --buffer_k arrivals; staleness is "
                     "bounded by --max_staleness instead)")
        if args.buffer_k < 0 or args.max_staleness < 0 \
                or args.staleness_alpha < 0:
            ap.error("--buffer_k/--max_staleness/--staleness_alpha "
                     "must be >= 0")
        if quant_spec is not None:
            from neuroimagedisttraining_tpu.privacy import secure_quant \
                as _sq

            k_cap = min(args.buffer_k or args.num_clients,
                        args.num_clients)
            if _sq.weighted_fold_capacity(quant_spec) <= k_cap:
                ap.error(
                    "--async_server --secure_quant folds integer-scaled "
                    "staleness weights inside the field, which needs "
                    "headroom the "
                    f"{args.secure_quant_field_bits}-bit field lacks "
                    f"for a {k_cap}-upload buffer — pass "
                    "--secure_quant_field_bits 32")
    if args.ingest_workers:
        if args.ingest_workers < 0:
            ap.error("--ingest_workers must be >= 0")
        if not args.async_server:
            ap.error("--ingest_workers shards the ASYNC ingest plane "
                     "(asyncfl/ingest.py) — add --async_server")
        if args.defense != "none" or args.quarantine_rounds:
            ap.error("--ingest_workers supports neither server-side "
                     "defenses nor quarantine: workers fold uploads "
                     "into partial aggregates, so the root never sees "
                     "per-client updates to select over or score "
                     "(matrix precedent: the buffered secure path). "
                     "Use the single-process plane (--ingest_workers 0) "
                     "or client-side clipping")
    if args.regions:
        if args.regions < 0:
            ap.error("--regions must be >= 0")
        if not args.ingest_workers:
            ap.error("--regions interposes regional sub-aggregators in "
                     "the SHARDED ingest plane — pass --ingest_workers "
                     "N (workers per region) too")
    if (args.ingest_shm or args.sync_delta) and not args.ingest_workers:
        ap.error("--ingest_shm/--sync_delta are sharded-ingest-plane "
                 "transports (asyncfl/ingest.py) — add "
                 "--ingest_workers N")
    if args.round_deadline > 0 and args.quorum == 0:
        args.quorum = args.num_clients // 2 + 1  # simple majority
    if args.heartbeat_timeout > 0 and not (
            0 < args.heartbeat_interval < args.heartbeat_timeout):
        # beats slower than the timeout would mark every HEALTHY client
        # suspect mid-round and silently truncate aggregates
        ap.error("--heartbeat_timeout requires 0 < --heartbeat_interval "
                 f"< timeout (got interval={args.heartbeat_interval}, "
                 f"timeout={args.heartbeat_timeout})")
    from neuroimagedisttraining_tpu.utils.compile_cache import (
        enable_compile_cache,
    )
    enable_compile_cache(args.compile_cache)
    # observability plane (obs/, ISSUE 9): flight ring + span tracer are
    # per-process; the /metrics endpoint starts on the server rank below
    from neuroimagedisttraining_tpu.obs import flight as obs_flight
    from neuroimagedisttraining_tpu.obs import trace as obs_trace

    # the dump PATH arms on the server rank only: silo ranks record into
    # their own rings (on a fatal failure failure_context logs the
    # ring's tail when no dump path is set), but a crashing silo
    # sharing one --flight_out arg list must never clobber the server's
    # post-mortem file
    obs_flight.configure(capacity=args.flight_events,
                         path=args.flight_out
                         if args.role == "server" else "")
    if args.trace_out:
        obs_trace.arm(args.trace_out,
                      tags={"role": args.role, "rank": args.rank})
    host_map = _parse_hosts(args.hosts)
    # (--force_cpu provisioning happens right after parse_args: the
    # --recipe auto resolution touches the backend)

    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc, FedAvgServer, SecureFedAvgClientProc,
        SecureFedAvgServer, SlotAggregatorProc,
    )

    if args.role == "aggregator":
        agg = SlotAggregatorProc(args.slot_index, args.num_clients,
                                 args.n_aggregators,
                                 base_port=args.base_port,
                                 host_map=host_map)
        print(f"[aggregator {args.slot_index}] rank {agg.rank} "
              f"aggregating slot {args.slot_index}", flush=True)
        agg.run()
        print(json.dumps({"role": "aggregator",
                          "slot_index": args.slot_index,
                          "clients_seen": len(agg.received)}), flush=True)
        return 0

    if args.role == "server":
        import jax

        # seed-deterministic init: every process derives the same model;
        # the wire mask (when configured) derives from the SAME state —
        # one model build, one jitted init
        _, gs = _seed_init_state(args)
        wire_masks = (_build_wire_masks(args, gs)
                      if args.wire_mask_density > 0 else None)
        init = {"params": jax.tree.map(np.asarray, gs.params),
                "batch_stats": jax.tree.map(np.asarray, gs.batch_stats)}
        cls = SecureFedAvgServer if args.secure else FedAvgServer
        if args.secure:
            kw = {"frac_bits": args.mpc_frac_bits,
                  "n_aggregators": args.n_aggregators,
                  "quant_spec": quant_spec}
            if args.secure_quant and args.defense != "none":
                # clip-family defense under secure_quant is enforced
                # CLIENT-side; the server keeps the geometry so the
                # weak_dp accountant can charge the ledger it reports
                kw.update(defense=args.defense,
                          norm_bound=args.norm_bound,
                          stddev=args.stddev, defense_seed=args.seed,
                          dp_delta=args.dp_delta)
        else:
            kw = {"wire_masks": wire_masks,
                  "defense": args.defense, "byz_f": args.byz_f,
                  "geomed_iters": args.geomed_iters,
                  "norm_bound": args.norm_bound,
                  "stddev": args.stddev, "defense_seed": args.seed,
                  "quarantine_rounds": args.quarantine_rounds,
                  "outlier_threshold": args.outlier_threshold,
                  "dp_delta": args.dp_delta}
        if args.async_server:
            from neuroimagedisttraining_tpu.asyncfl import (
                BufferedFedAvgServer,
            )

            if args.secure_quant:
                # the buffered server speaks one-phase secure_quant
                # natively; the dense-secure kw set does not apply
                kw = {"secure_quant": quant_spec,
                      "defense": args.defense,
                      "norm_bound": args.norm_bound,
                      "stddev": args.stddev, "defense_seed": args.seed,
                      "dp_delta": args.dp_delta}
            if args.ingest_workers:
                ikw = dict(
                    buffer_k=args.buffer_k,
                    staleness_alpha=args.staleness_alpha,
                    max_staleness=args.max_staleness,
                    base_port=args.base_port, host_map=host_map,
                    heartbeat_timeout=args.heartbeat_timeout,
                    trace_out=args.trace_out,
                    flight_out=args.flight_out,
                    use_shm=args.ingest_shm,
                    sync_delta=args.sync_delta, **kw)
                if args.regions:
                    from neuroimagedisttraining_tpu.asyncfl.region import (
                        HierarchicalIngestServer,
                    )

                    server = HierarchicalIngestServer(
                        init, args.comm_round, args.num_clients,
                        regions=args.regions,
                        workers_per_region=args.ingest_workers, **ikw)
                    topo = (f"{args.regions} regions x "
                            f"{args.ingest_workers} workers "
                            f"(hierarchical tier)")
                else:
                    from neuroimagedisttraining_tpu.asyncfl.ingest import (
                        ShardedIngestServer,
                    )

                    server = ShardedIngestServer(
                        init, args.comm_round, args.num_clients,
                        ingest_workers=args.ingest_workers, **ikw)
                    topo = (f"{args.ingest_workers} selector workers")
                print(f"[server] sharded ingest plane on port "
                      f"{args.base_port}: {topo} (SO_REUSEPORT), "
                      f"buffer_k={server.buffer_k}, staleness_alpha="
                      f"{args.staleness_alpha}, max_staleness="
                      f"{args.max_staleness}"
                      + (", shm partial hand-off" if args.ingest_shm
                         else "")
                      + (", delta sync" if args.sync_delta else ""),
                      flush=True)
            else:
                server = BufferedFedAvgServer(
                    init, args.comm_round, args.num_clients,
                    buffer_k=args.buffer_k,
                    staleness_alpha=args.staleness_alpha,
                    max_staleness=args.max_staleness,
                    base_port=args.base_port, host_map=host_map,
                    heartbeat_timeout=args.heartbeat_timeout, **kw)
                print(f"[server] asyncfl selector control plane on "
                      f"port {args.base_port}; buffer_k="
                      f"{server.buffer_k}, staleness_alpha="
                      f"{args.staleness_alpha}, max_staleness="
                      f"{args.max_staleness}", flush=True)
            broker = None
        else:
            comm, broker = _make_comm(args, 0, host_map)
            server = cls(init, args.comm_round, args.num_clients,
                         base_port=args.base_port, host_map=host_map,
                         comm=comm, round_deadline=args.round_deadline,
                         quorum=args.quorum,
                         heartbeat_timeout=args.heartbeat_timeout, **kw)
            print(f"[server] {args.transport} control plane on port "
                  f"{args.broker_port or args.base_port}; waiting for "
                  f"{args.num_clients} silos", flush=True)
        from neuroimagedisttraining_tpu.obs.http import (
            start_metrics_server,
        )
        from neuroimagedisttraining_tpu.utils.profiling import (
            failure_context,
        )

        # anomaly-rule engine on the server rank (obs/rules.py, ISSUE
        # 15): built-ins parameterized by this federation's knobs +
        # the --health_rules manifest; evaluated at every version
        # advance (asyncfl) and at each liveness probe, reported in
        # /healthz and gated at exit
        from neuroimagedisttraining_tpu.obs import health as obs_health
        from neuroimagedisttraining_tpu.obs import rules as obs_rules

        extra_rules = ()
        if recipe_doc is not None:
            from neuroimagedisttraining_tpu.tune import (
                recipe as tune_recipe,
            )
            extra_rules = tune_recipe.drift_rules(recipe_doc)
        hrules = obs_rules.configure(
            manifest_path=args.health_rules,
            dp_epsilon_budget=args.dp_epsilon_budget,
            comm_round=args.comm_round,
            max_staleness=args.max_staleness,
            extra_rules=extra_rules)
        # reflex plane (obs/actions.py, ISSUE 20): the control plane's
        # realizations of the reflex actions, registered on the LOCAL
        # bus handle (disarm precedes the result-JSON write, exactly
        # like ``hrules``). freeze_rollback/shrink_mesh have no
        # control-plane realization — a rule binding them here logs an
        # honest 'unhandled' dispatch instead of silently vanishing.
        from neuroimagedisttraining_tpu.obs import (
            actions as obs_actions,
        )

        bus = obs_actions.configure(args.actions)

        # LOCKING: rules evaluate (and therefore dispatch actions)
        # synchronously at the servers' own boundaries — cross_silo's
        # round completion and asyncfl's version advance — which run
        # UNDER ``server._rlock`` (a non-reentrant Lock). The handlers
        # below therefore never acquire it: they execute on the thread
        # that already holds it, so every mutation is serialized with
        # the aggregation state they touch. (The end-of-run boundary
        # evaluation happens after the control plane quiesced.)
        def _act_quarantine(*, rule, round_idx, value=None):
            # ride the PR 5 strike machinery's state: quarantine the
            # most-struck non-quarantined silo, same byz_f budget and
            # post-window ARG_EF_RESET debt the strike path keeps
            cand = {c: n for c, n in server._strikes.items()
                    if n > 0 and c not in server._quarantined_now()}
            if not cand:
                return {"status": "skipped",
                        "reason": "no struck silo to attribute the "
                                  "alert to"}
            if len(server._quarantined_now()) >= max(1, server.byz_f):
                return {"status": "skipped",
                        "reason": f"quarantine budget (byz_f="
                                  f"{server.byz_f}) spent"}
            c = max(cand, key=lambda k: (cand[k], -k))
            until = (server.round_idx + 1
                     + max(1, server.quarantine_rounds))
            server._quarantine_until[c] = until
            server._strikes[c] = 0
            server._ef_reset_pending.add(c)
            server.byz_stats["quarantines"].append(
                {"client": c, "from_round": server.round_idx + 1,
                 "until_round": until})
            return {"client": c, "from_round": server.round_idx + 1,
                    "until": until, "strikes": cand[c]}

        def _act_escalate(*, rule, round_idx, value=None):
            from neuroimagedisttraining_tpu.core import robust
            ladder = ("none", "norm_diff_clipping", "trimmed_mean")
            if args.secure or args.secure_quant:
                return {"status": "skipped",
                        "reason": "secure planes clip client-side; no "
                                  "server defend tail to escalate"}
            cur = server.defense
            if cur not in ladder:
                return {"status": "skipped",
                        "reason": f"operator defense {cur!r} is "
                                  "outside the escalation ladder"}
            if cur == ladder[-1]:
                return {"status": "skipped",
                        "reason": f"already at the top rung {cur!r}"}
            nxt = ladder[ladder.index(cur) + 1]
            if nxt in robust.ROBUST_AGGREGATORS:
                try:
                    robust._check_f(args.num_clients, server.byz_f,
                                    nxt)
                except ValueError as e:
                    return {"status": "skipped", "reason": str(e)}
            server.defense = nxt
            return {"from": cur, "to": nxt}

        bus.register("quarantine_silo", _act_quarantine)
        bus.register("escalate_defense", _act_escalate)
        if args.async_server:
            def _act_adapt_buffer(*, rule, round_idx, value=None):
                # staleness runaway => aggregate more eagerly (halve
                # the trigger) and discount stale arrivals harder
                old_k = server.buffer_k
                old_a = server.staleness_alpha
                new_k = max(1, (old_k + 1) // 2)
                new_a = min(old_a + 0.25, 2.0)
                if new_k == old_k and new_a == old_a:
                    return {"status": "skipped",
                            "reason": "buffer_k at its floor and "
                                      "staleness_alpha at its cap"}
                server.buffer_k = new_k
                server.staleness_alpha = new_a
                return {"buffer_k": [old_k, new_k],
                        "staleness_alpha": [old_a, new_a]}

            bus.register("adapt_buffer", _act_adapt_buffer)

        def _health() -> dict:
            # scrape-thread probe with a BOUNDED lock wait: _rlock is
            # held across whole aggregations (first-round XLA compile
            # included), and a k8s-style liveness probe with a 1-2s
            # timeout must never conclude "dead" because the server is
            # busy doing its job — a timed-out acquire reports busy,
            # which IS a liveness signal
            from neuroimagedisttraining_tpu.obs import (
                compute as obs_compute,
            )

            if not server._rlock.acquire(timeout=0.2):
                # the compute block rides even the busy report: its
                # profiler state is lock-free w.r.t. _rlock, and a
                # wedged dispatch is exactly when the probe matters
                return {"busy": True,
                        "compute": obs_compute.PROFILER.health(),
                        "health": obs_rules.health_block(),
                        # action log is bus-internal state, lock-free
                        # w.r.t. _rlock — it rides the busy report too
                        "actions": bus.actions_block()}
            try:
                # rules evaluate once per completed round at the
                # servers' own boundaries (cross_silo round completion /
                # asyncfl version advance); the probe only REPORTS
                h = {"round": int(server.round_idx),
                     "registered": len(server._registered),
                     "suspects": len(server._suspect),
                     # compute block (ISSUE 14): last dispatch age /
                     # MFU sample / recompile count — distinguishes a
                     # WEDGED-dispatch federation (age grows, counts
                     # stall) from a slow one at the liveness probe
                     "compute": obs_compute.PROFILER.health(),
                     # fast-path coverage (ISSUE 15 satellite): the
                     # fallback totals next to the compute block — a
                     # silently-degraded run reads differently from a
                     # healthy one right at the probe
                     "fallbacks": obs_health.fallback_block(
                         server.fanin.merged_snapshot()
                         if args.ingest_workers else None),
                     "health": obs_rules.health_block(),
                     # the last reflex dispatches, rule provenance
                     # included (ISSUE 20)
                     "actions": bus.actions_block()}
                if args.async_server:
                    h["buffered"] = (server._pending()
                                     if args.ingest_workers
                                     else len(server._buffer))
            finally:
                server._rlock.release()
            return h

        msrv = start_metrics_server(args.metrics_port,
                                    health_probe=_health,
                                    # sharded plane: serve the MERGED
                                    # view — root samples + worker-
                                    # labeled samples + snapshot-
                                    # staleness gauges (obs/fanin.py)
                                    registry=(server.metrics_view()
                                              if args.ingest_workers
                                              else None),
                                    host=args.metrics_host)
        if msrv is not None:
            print(f"[server] obs: /metrics + /healthz on port "
                  f"{msrv.port}"
                  + (" (merged across ingest workers)"
                     if args.ingest_workers else ""), flush=True)
        clean_exit = False
        try:
            # failure_context dumps the flight ring before re-raising —
            # a chaos run that dies leaves its post-mortem
            with failure_context(name="cross-silo server"):
                server.run()
            clean_exit = True
        finally:
            if args.ingest_workers:
                # the sharded root writes the MERGED artifacts at the
                # bare paths itself (ShardedIngestServer.dump_obs,
                # idempotent) — the per-process dumps below would
                # clobber them with root-only views
                pass
            else:
                if args.flight_out and clean_exit:
                    # on failure the failure_context dump IS the
                    # artifact — re-dumping here would relabel the
                    # crash post-mortem as a normal end of run
                    obs_flight.dump(reason="end of run")
                if args.trace_out:
                    obs_trace.dump()
            if msrv is not None:
                msrv.close()
            if not clean_exit:
                # crash path: the rule engine's lifetime is the run's
                # (the success path disarms after the final boundary
                # evaluation below)
                obs_rules.disarm()
                obs_actions.disarm()
        if broker is not None:
            broker.stop()
        norm = float(np.sqrt(sum(
            float(np.sum(np.asarray(v, np.float64) ** 2))
            for v in jax.tree.leaves(server.params))))
        stats = server.com_manager.byte_stats()
        extra = {}
        if args.async_server:
            extra = {"async_server": True,
                     # live server values, not the flags: adapt_buffer
                     # (ISSUE 20) may have changed them mid-run
                     "buffer_k": server.buffer_k,
                     "staleness_alpha": server.staleness_alpha,
                     "max_staleness": args.max_staleness,
                     "upload_audit": server.upload_audit(),
                     "staleness_taus": sorted({
                         t for h in server.history
                         for t in h.get("taus", ())})}
            if args.ingest_workers:
                extra["ingest_workers"] = args.ingest_workers
                if args.regions:
                    extra["regions"] = args.regions
                if args.ingest_shm or args.sync_delta:
                    extra["worker_xstats"] = server.worker_xstats()
                # workers own the client sockets: the wire accounting
                # lives with them, not the root's placeholder comm
                stats = server.worker_byte_stats()
        dp = server.dp_report()
        if dp is not None:
            # run-end privacy audit: per-silo (epsilon, delta) from the
            # weak_dp RDP ledger (privacy/accountant.py)
            extra["dp"] = dp
        # end-of-run health verdict (ISSUE 15): one final boundary
        # evaluation at the last completed version, then the
        # machine-readable verdict rides the result JSON (run_report
        # joins it); --health_gate turns a non-ok WORST status into a
        # nonzero exit
        if args.async_server:
            server._observe_health_boundary()
        else:
            obs_rules.observe_boundary(int(server.round_idx))
        health_verdict = hrules.verdict()
        obs_rules.disarm()
        obs_actions.disarm()  # local ``bus`` handle still readable
        extra["health"] = {
            k: health_verdict[k]
            for k in ("status", "worst_status", "alerts_total",
                      "rounds_evaluated")}
        extra["health_timeline"] = health_verdict["timeline"]
        # the reflex action log (timestamp-free: twin seeded chaos runs
        # produce byte-identical blocks) rides the result JSON
        extra["actions"] = bus.actions_block()
        print(json.dumps({"rounds_completed": len(server.history),
                          "clients": args.num_clients,
                          "secure": bool(args.secure),
                          "secure_quant": bool(args.secure_quant),
                          "transport": args.transport,
                          "wire_codec": args.wire_codec,
                          "wire_mask_density": args.wire_mask_density,
                          "suspects": sorted(server.suspect_clients()),
                          "defense": getattr(server, "defense", "none"),
                          "quarantined": sorted(
                              server.quarantined_clients()),
                          "byz_stats": server.byz_stats,
                          "final_param_norm": round(norm, 6),
                          **extra, **stats}), flush=True)
        if args.health_gate and health_verdict["worst_status"] != "ok":
            # stderr: the last stdout line stays the result JSON the
            # bench/smoke scripts parse
            print(f"[health] gate FAILED: worst status "
                  f"{health_verdict['worst_status']!r} "
                  f"({health_verdict['alerts_total']} alert(s))",
                  file=sys.stderr, flush=True)
            return 1
        return 0

    train_fn, wire_masks = _make_train_fn(args)
    cls = SecureFedAvgClientProc if args.secure else FedAvgClientProc
    if args.secure:
        kw = {"n_shares": args.mpc_n_shares,
              "frac_bits": args.mpc_frac_bits, "mpc_seed": args.seed,
              "n_aggregators": args.n_aggregators,
              "quant_spec": quant_spec,
              # async buffered plane: one-phase frames (no weight
              # exchange); clip-family defenses are enforced HERE, on
              # this silo's own update, pre-share
              "one_phase": bool(args.async_server)}
        if args.secure_quant and args.defense != "none":
            kw.update(defense=args.defense, norm_bound=args.norm_bound,
                      stddev=args.stddev, defense_seed=args.seed)
    else:
        kw = {"wire_codec": args.wire_codec,
              "wire_masks": wire_masks,
              "wire_topk_ratio": args.wire_topk_ratio,
              "sync_delta": args.sync_delta}
    if not args.secure and fault_spec is not None \
            and fault_spec.any_value_faults:
        # value faults live in the CLIENT, not the transport wrapper:
        # the silo attacks its own upload (faults/adversary.py) before
        # any encoding, keyed by the shared (seed, round, rank) schedule
        from neuroimagedisttraining_tpu.faults import FaultSchedule
        kw["fault_schedule"] = FaultSchedule(fault_spec, args.seed)
        kw["seed"] = args.seed
    comm, _ = _make_comm(args, args.rank, host_map)
    client = cls(args.rank, args.num_clients, train_fn,
                 base_port=args.base_port, host_map=host_map, comm=comm,
                 heartbeat_interval=args.heartbeat_interval, **kw)
    print(f"[silo {args.rank}] joining server", flush=True)
    from neuroimagedisttraining_tpu.utils.profiling import failure_context

    try:
        with failure_context(name=f"silo {args.rank}"):
            client.run()
    finally:
        if args.trace_out:
            obs_trace.dump()
    return 0


if __name__ == "__main__":
    sys.exit(main())
