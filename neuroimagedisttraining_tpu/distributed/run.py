"""Runnable cross-silo federation: one OS process per silo, real sockets.

The reference's distributed runtime was vestigial library code with no
entry point (SURVEY §2.3); this module makes ours drivable::

    # terminal 1 — the aggregation server (rank 0)
    python -m neuroimagedisttraining_tpu.distributed.run --role server \
        --num_clients 2 --comm_round 5 --model 3dcnn_tiny \
        --dataset synthetic --base_port 29500

    # terminals 2..N+1 — one trainer process per silo (ranks 1..N)
    python -m neuroimagedisttraining_tpu.distributed.run --role client \
        --rank 1 --num_clients 2 --comm_round 5 --model 3dcnn_tiny \
        --dataset synthetic --base_port 29500

Across machines, pass every rank's address once to all processes:
``--hosts 0=10.0.0.1,1=10.0.0.2,2=10.0.0.3`` (each rank listens on
``base_port + rank``). ``--secure`` swaps in the TurboAggregate
additive-share protocol (SecureFedAvgServer/ClientProc): clients upload
share slots of their weighted quantized updates and the server
reconstructs only the aggregate. Add ``--n_aggregators K`` (= K extra
processes with ``--role aggregator --slot_index j``, ranks
num_clients+1+j) for the grouped deployment: slot j rides to aggregator
j, each aggregator forwards only its cross-client slot total, and no
single node — server included — can reconstruct any client::

    # grouped secure aggregation: server + N silos + K aggregators
    python -m ...distributed.run --role aggregator --slot_index 0 \
        --num_clients 2 --n_aggregators 3 --secure ...

Each client trains its own site shard with the real jitted LocalTrainer
(silo k holds site ``(k-1) mod num_sites``); the server runs the
register -> broadcast -> train -> upload -> aggregate -> finish protocol
(cross_silo.py) and prints one JSON line with the final round count and
aggregate param norm. This is the cross-silo deployment shape: bulk
per-silo compute on each silo's own accelerator(s), small model payloads
on the control plane (on a TPU pod, prefer --multihost_coordinator on
the main CLI so bulk tensors ride ICI/DCN collectives instead).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _parse_hosts(spec: str) -> dict[int, str] | None:
    if not spec:
        return None
    out = {}
    for part in spec.split(","):
        r, ip = part.split("=")
        out[int(r)] = ip
    return out


def _build_shard(args, rank: int):
    """(X, y, n) numpy shard for silo ``rank`` + input sample shape."""
    from neuroimagedisttraining_tpu.data import partition as P

    if args.dataset == "synthetic":
        from neuroimagedisttraining_tpu.data.synthetic import (
            generate_synthetic_abcd,
        )

        cohort = generate_synthetic_abcd(
            num_subjects=args.synthetic_num_subjects,
            shape=tuple(args.synthetic_shape),
            num_sites=max(2, args.num_clients), seed=args.seed)
    else:
        from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5

        cohort = load_abcd_hdf5(args.data_dir, lazy=False)
    train_map, _, _ = P.site_partition(cohort["site"], seed=42)
    site = (rank - 1) % len(train_map)
    idx = train_map[site]
    X = np.asarray(cohort["X"])[idx]
    y = np.asarray(cohort["y"])[idx]
    return X, y, len(idx)


def _make_train_fn(args):
    """Silo-local training closure: jitted LocalTrainer epochs on this
    silo's shard (fedavg my_model_trainer semantics, round-decayed lr)."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.config import OptimConfig
    from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer
    from neuroimagedisttraining_tpu.models import create_model

    X, y, n = _build_shard(args, args.rank)
    optim = OptimConfig(lr=args.lr, lr_decay=args.lr_decay,
                        batch_size=args.batch_size, epochs=args.epochs)
    trainer = LocalTrainer(create_model(args.model,
                                        num_classes=args.num_classes),
                           optim, num_classes=args.num_classes)
    Xd, yd = jnp.asarray(X), jnp.asarray(y)

    @jax.jit
    def step(params, bstats, rng, lr):
        cs = ClientState(params=params, batch_stats=bstats,
                         opt_state=trainer.opt.init(params), rng=rng)
        cs, loss = trainer.local_train(
            cs, Xd, yd, n, lr, epochs=optim.epochs,
            batch_size=optim.batch_size, max_samples=Xd.shape[0])
        return cs.params, cs.batch_stats, loss

    def train_fn(params_np, round_idx):
        # server ships {params, batch_stats}; silo trains and ships back
        params = jax.tree.map(jnp.asarray, params_np["params"])
        bstats = jax.tree.map(jnp.asarray, params_np["batch_stats"])
        rng = jax.random.fold_in(jax.random.key(args.seed + 17 + args.rank),
                                 round_idx)
        lr = jnp.float32(args.lr) * jnp.float32(args.lr_decay) ** round_idx
        p, b, loss = step(params, bstats, rng, lr)
        print(f"[silo {args.rank}] round {round_idx}: "
              f"loss={float(loss):.4f} (n={n})", flush=True)
        return {"params": jax.tree.map(np.asarray, p),
                "batch_stats": jax.tree.map(np.asarray, b)}, float(n)

    return train_fn


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="neuroimagedisttraining_tpu.distributed.run",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--role", required=True,
                    choices=("server", "client", "aggregator"))
    ap.add_argument("--rank", type=int, default=0,
                    help="client rank 1..num_clients (server is 0); "
                         "aggregator j is rank num_clients+1+j")
    ap.add_argument("--slot_index", type=int, default=0,
                    help="aggregator role: which share slot this process "
                         "aggregates (0..n_aggregators-1)")
    ap.add_argument("--n_aggregators", type=int, default=0,
                    help="secure mode: route share slot j to a distinct "
                         "aggregator process instead of the server "
                         "(TurboAggregate grouped aggregation); must equal "
                         "--mpc_n_shares; 0 = single-server degenerate "
                         "mode")
    ap.add_argument("--num_clients", type=int, required=True)
    ap.add_argument("--comm_round", type=int, default=5)
    ap.add_argument("--base_port", type=int, default=29500)
    ap.add_argument("--hosts", type=str, default="",
                    help="rank=ip,... (default: all localhost)")
    ap.add_argument("--secure", action="store_true",
                    help="TurboAggregate additive-share aggregation over "
                         "the control plane")
    ap.add_argument("--mpc_n_shares", type=int, default=3)
    ap.add_argument("--mpc_frac_bits", type=int, default=16)
    ap.add_argument("--model", type=str, default="3dcnn_tiny")
    ap.add_argument("--num_classes", type=int, default=1)
    ap.add_argument("--dataset", type=str, default="synthetic",
                    choices=("synthetic", "abcd_h5"))
    ap.add_argument("--data_dir", type=str, default="")
    ap.add_argument("--synthetic_num_subjects", type=int, default=64)
    ap.add_argument("--synthetic_shape", type=int, nargs=3,
                    default=[12, 14, 12])
    ap.add_argument("--batch_size", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--lr_decay", type=float, default=0.998)
    ap.add_argument("--seed", type=int, default=1024)
    ap.add_argument("--force_cpu", action="store_true",
                    help="pin JAX to the CPU backend (e.g. several silo "
                         "processes on one machine sharing a tunneled "
                         "accelerator)")
    args = ap.parse_args(argv)
    if args.role == "aggregator":
        if args.n_aggregators <= 0:
            ap.error("--role aggregator requires --n_aggregators > 0 "
                     "(same value on every rank)")
        if not 0 <= args.slot_index < args.n_aggregators:
            ap.error(f"--slot_index ({args.slot_index}) must be in "
                     f"[0, {args.n_aggregators})")
    if args.n_aggregators > 0:
        # fail fast on EVERY rank: mismatched flags would otherwise leave
        # aggregator processes blocked forever (no slot, no FINISH)
        if not args.secure:
            ap.error("--n_aggregators requires --secure")
        if args.n_aggregators != args.mpc_n_shares:
            ap.error(f"--n_aggregators ({args.n_aggregators}) must equal "
                     f"--mpc_n_shares ({args.mpc_n_shares}): slot j "
                     "routes to aggregator j")
    host_map = _parse_hosts(args.hosts)
    if args.force_cpu:
        from neuroimagedisttraining_tpu.parallel.mesh import (
            provision_virtual_devices,
        )
        provision_virtual_devices(1)

    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc, FedAvgServer, SecureFedAvgClientProc,
        SecureFedAvgServer, SlotAggregatorProc,
    )

    if args.role == "aggregator":
        agg = SlotAggregatorProc(args.slot_index, args.num_clients,
                                 args.n_aggregators,
                                 base_port=args.base_port,
                                 host_map=host_map)
        print(f"[aggregator {args.slot_index}] rank {agg.rank} "
              f"aggregating slot {args.slot_index}", flush=True)
        agg.run()
        print(json.dumps({"role": "aggregator",
                          "slot_index": args.slot_index,
                          "clients_seen": len(agg.received)}), flush=True)
        return 0

    if args.role == "server":
        import jax

        from neuroimagedisttraining_tpu.config import OptimConfig
        from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
        from neuroimagedisttraining_tpu.models import create_model

        # seed-deterministic init: every process derives the same model
        trainer = LocalTrainer(
            create_model(args.model, num_classes=args.num_classes),
            OptimConfig(), num_classes=args.num_classes)
        shape = ((1,) + tuple(args.synthetic_shape)
                 if args.dataset == "synthetic" else None)
        if shape is None:
            from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5

            X0 = load_abcd_hdf5(args.data_dir, lazy=True)
            shape = (1,) + tuple(X0["X"].shape[1:])
            X0["file"].close()
        import jax.numpy as jnp

        gs = trainer.init_client_state(jax.random.key(args.seed),
                                       jnp.zeros(shape, jnp.float32))
        init = {"params": jax.tree.map(np.asarray, gs.params),
                "batch_stats": jax.tree.map(np.asarray, gs.batch_stats)}
        cls = SecureFedAvgServer if args.secure else FedAvgServer
        kw = ({"frac_bits": args.mpc_frac_bits,
               "n_aggregators": args.n_aggregators} if args.secure else {})
        server = cls(init, args.comm_round, args.num_clients,
                     base_port=args.base_port, host_map=host_map, **kw)
        print(f"[server] listening on port {args.base_port}; waiting for "
              f"{args.num_clients} silos", flush=True)
        server.run()
        norm = float(np.sqrt(sum(
            float(np.sum(np.asarray(v, np.float64) ** 2))
            for v in jax.tree.leaves(server.params))))
        print(json.dumps({"rounds_completed": len(server.history),
                          "clients": args.num_clients,
                          "secure": bool(args.secure),
                          "final_param_norm": round(norm, 6)}), flush=True)
        return 0

    train_fn = _make_train_fn(args)
    cls = SecureFedAvgClientProc if args.secure else FedAvgClientProc
    kw = ({"n_shares": args.mpc_n_shares, "frac_bits": args.mpc_frac_bits,
           "mpc_seed": args.seed,
           "n_aggregators": args.n_aggregators} if args.secure else {})
    client = cls(args.rank, args.num_clients, train_fn,
                 base_port=args.base_port, host_map=host_map, **kw)
    print(f"[silo {args.rank}] joining server", flush=True)
    client.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
