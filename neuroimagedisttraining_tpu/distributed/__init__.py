"""Cross-silo distributed control plane (the reference's
fedml_core/distributed, rebuilt TPU-native: msgpack messages + TCP sockets
for control, XLA collectives over ICI/DCN for bulk tensors)."""

from neuroimagedisttraining_tpu.distributed.message import Message  # noqa: F401
from neuroimagedisttraining_tpu.distributed.comm import (  # noqa: F401
    BaseCommManager, Observer, SocketCommManager,
)
from neuroimagedisttraining_tpu.distributed.managers import (  # noqa: F401
    ClientManager, DistributedManager, ServerManager,
)
from neuroimagedisttraining_tpu.distributed.cross_silo import (  # noqa: F401
    FedAvgClientProc, FedAvgServer, init_multihost,
)
