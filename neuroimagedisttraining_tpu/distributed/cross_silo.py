"""Cross-silo federated orchestration over the socket control plane.

The capability SURVEY §2.3 requires: a server/client message loop carrying
the reference protocol {register -> init/broadcast params -> local train ->
upload update -> aggregate -> sync or finish} (client_manager.py /
server_manager.py semantics), as runnable processes. Within one silo the
bulk compute path is still the jitted SPMD round program; this layer
coordinates *between* silos (separate hosts/processes), where the
reference's MPI/gRPC runtime would have lived — model payloads ride the
msgpack codec, and each silo trains with its own jitted LocalTrainer round.

``FedAvgServer.run()`` drives ``comm_round`` rounds; each
``FedAvgClientProc`` owns a ``train_fn(params, round_idx) -> (params,
num_samples)`` — silos are free to implement it with any engine. Weighted
aggregation happens on the server in float32 numpy (parity:
fedavg_api.py:102-117).

Multi-host TPU pods: use ``init_multihost`` (jax.distributed) so each silo
process joins one global JAX runtime and bulk tensors can instead ride DCN
collectives; the socket plane then only carries control messages.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.codec import wire as codec
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.managers import (
    ClientManager, ServerManager,
)
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.utils.pytree import tree_weighted_mean
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import rules as obs_rules

log = logging.getLogger("neuroimagedisttraining_tpu.cross_silo")

_weighted_mean_jit = None


def survivor_weighted_mean(trees: list, ns: list[float]):
    """Sample-count-weighted mean over whatever subset of clients
    reported — THE jitted engine aggregation (utils/pytree
    ``tree_weighted_mean``, the op ``FederatedEngine.aggregate`` lowers
    to for frac-sampled rounds), so a deadline-truncated cross-silo
    round is bitwise-identical to an engine round over the same survivor
    set (pinned in tests/test_faults.py)."""
    global _weighted_mean_jit
    if _weighted_mean_jit is None:
        _weighted_mean_jit = jax.jit(tree_weighted_mean)
    stacked = jax.tree.map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *trees)
    out = _weighted_mean_jit(stacked, jnp.asarray(ns, jnp.float32))
    return jax.tree.map(lambda x: np.asarray(x), out)


#: one compiled defended-aggregation program per (defense, f, iters,
#: bound) config — the server aggregates with the SAME jitted
#: core/robust.py dispatch the simulated engines trace into their round
#: bodies, so a cross-silo defended round matches an engine round over
#: the same survivor set
_defended_jit_cache: dict = {}


def survivor_defended_mean(trees: list, ns: list[float], reference, *,
                           defense: str, byz_f: int = 1,
                           geomed_iters: int = 8, norm_bound: float = 5.0,
                           stddev: float = 0.0, rngs=None):
    """Defended aggregation over whatever subset of clients reported:
    ``--defense`` dispatches through ``robust.aggregate_with_defense``
    (clip family per client then the weighted mean; order-statistic
    family replaces the mean). ``reference`` is the round's broadcast
    model — the clip/sanitize baseline the engines use. ``weak_dp``
    additionally needs ``rngs`` ([C] stacked per-client PRNG keys, one
    per reporting silo) and a noise ``stddev``."""
    from neuroimagedisttraining_tpu.core import robust

    key = (defense, int(byz_f), int(geomed_iters), float(norm_bound),
           float(stddev))
    fn = _defended_jit_cache.get(key)
    if fn is None:
        if defense == "weak_dp":
            def agg(stacked, w, ref, rngs):
                return robust.aggregate_with_defense(
                    stacked, ref, w, defense=defense,
                    norm_bound=norm_bound, stddev=stddev, rngs=rngs,
                    byz_f=byz_f, geomed_iters=geomed_iters)
        else:
            def agg(stacked, w, ref):
                return robust.aggregate_with_defense(
                    stacked, ref, w, defense=defense,
                    norm_bound=norm_bound, byz_f=byz_f,
                    geomed_iters=geomed_iters)

        fn = _defended_jit_cache[key] = jax.jit(agg)
    stacked = jax.tree.map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *trees)
    args = (stacked, jnp.asarray(ns, jnp.float32),
            jax.tree.map(jnp.asarray, reference))
    if defense == "weak_dp":
        if rngs is None:
            raise ValueError("weak_dp needs per-client rngs")
        args = args + (rngs,)
    out = fn(*args)
    return jax.tree.map(lambda x: np.asarray(x), out)


def tree_all_finite(tree) -> bool:
    """Host-side: every leaf of ``tree`` is NaN/Inf-free. The server's
    hard gate on decoded uploads — one non-finite frame folded into the
    weighted mean poisons the aggregate for every honest silo."""
    return all(np.isfinite(np.asarray(x, np.float64)).all()
               for x in jax.tree.leaves(tree))


def update_outlier_flags(trees: list, reference, *,
                         norm_mult: float = 4.0,
                         cos_thresh: float = -0.5):
    """Per-silo anomaly flags over one round's decoded uploads: silo i is
    flagged when its update delta (vs the round's broadcast
    ``reference``) has norm > ``norm_mult`` x the cohort median, or
    cosine < ``cos_thresh`` against the mean delta of the OTHER silos
    (a sign-flipped upload scores ~-1 there; leave-one-out keeps a big
    attacker from dragging the comparison direction toward itself).
    Host numpy float64 — this is control-plane scoring over a handful of
    silos, not the jitted aggregation. Returns ``(flags, norms)``."""
    vecs = [np.concatenate([
        (np.asarray(a, np.float64) - np.asarray(b, np.float64)).ravel()
        for a, b in zip(jax.tree.leaves(t), jax.tree.leaves(reference))])
        for t in trees]
    V = np.stack(vecs)
    norms = np.linalg.norm(V, axis=1)
    med = float(np.median(norms))
    total = V.sum(axis=0)
    n = len(trees)
    flags = []
    for i in range(n):
        flag = med > 0 and norms[i] > norm_mult * med
        if not flag and n >= 3 and norms[i] > 0:
            others = (total - V[i]) / (n - 1)
            o_norm = np.linalg.norm(others)
            if o_norm > 0:
                cos = float(V[i] @ others) / (norms[i] * o_norm)
                flag = cos < cos_thresh
        flags.append(bool(flag))
    return flags, norms


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int) -> None:
    """Join this process to a multi-host JAX runtime (DCN collectives).
    Thin wrapper so silos opt in with one call; requires all processes to
    call it before any backend touch.

    On real TPU pods this makes every host's chips part of one global mesh
    (libtpu handles cross-host wiring) so the client axis spans hosts and
    aggregation rides ICI/DCN. NOTE: it cannot be smoke-tested in this
    build's CPU backend — two CPU processes each come up with
    process_count=1 (multiprocess CPU clustering is disabled in this jax
    build; verified empirically), so the cross-process capability test
    lives in the socket control plane instead
    (tests/test_distributed.py::test_cross_silo_multiprocess_smoke)."""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def _to_numpy_tree(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


class FedAvgServer(ServerManager):
    """Rank 0. Aggregates client updates sample-weighted per round.

    Fault tolerance (all opt-in; defaults reproduce the strict
    wait-for-everyone protocol):

    - ``round_deadline`` > 0 arms a per-round timer. When it fires with
      at least ``quorum`` uploads, the server aggregates over the
      survivors with sample-count re-weighting (the same jitted
      ``tree_weighted_mean`` the engines use for frac-sampled rounds)
      and marks the missing clients suspect; with fewer than ``quorum``
      it re-arms and keeps waiting — quorum is a hard floor, never
      silently lowered.
    - uploads are tagged with ``round_idx``: stale uploads (a straggler
      finishing after the deadline aggregated without it) and duplicate
      frames (a chaotic transport re-delivering) can never double-count.
    - ``heartbeat_timeout`` > 0 starts a monitor that marks clients
      suspect once their heartbeat goes stale — a crashed client is
      flagged within ~``timeout + timeout/4`` even mid-round.
    - a suspect client that re-registers is shipped the current round's
      model directly (late rejoin) and leaves the suspect set; a fresh
      upload or heartbeat also clears suspicion.

    Wire codec (ISSUE 3): uploads may arrive as tagged codec frames
    (codec/wire.py) instead of dense pytrees; ``_on_model`` decodes them
    BEFORE the weighted aggregation, against ``self.params`` — the
    round's broadcast model, which the round-tag accept gate guarantees
    is the delta reference the sender used. The DOWNLINK sync stays
    dense by design: a late-rejoining or deadline-skipped client has no
    agreed delta reference, and a dense broadcast means the reference
    chain can never desync under chaos (drops/dups/restarts).
    ``wire_masks`` is the engine mask handoff for shared-mask frames —
    the same pruning mask the encoding silos hold (e.g. SalientGrads'
    phase-1 global mask), letting them ship surviving values with no
    bitmap at all.

    Byzantine robustness (ISSUE 5):

    - decoded uploads that carry NaN/Inf are HARD-REJECTED before they
      can touch the aggregation (counted in ``byz_stats``, the sender
      treated like any other straggler by the deadline/quorum path) —
      this guard is unconditional, independent of ``defense``.
    - ``defense`` selects the aggregation rule (core/robust.py): the
      clip family transforms per silo before the weighted mean; the
      order-statistic family (trimmed_mean/median/krum/multi_krum/
      geometric_median) replaces the mean and tolerates up to ``byz_f``
      arbitrary silos. Validated at construction — an unknown name can
      never surface mid-round. ``defense="none"`` keeps the exact
      ``survivor_weighted_mean`` path (the engine-parity pin).
    - ``quarantine_rounds`` > 0 arms server-side DETECTION: every
      aggregation scores the survivors' update deltas (norm vs the
      cohort median, cosine vs the leave-one-out mean —
      ``update_outlier_flags``); flagged silos accrue strikes (one
      clean round forgives one strike), and ``outlier_threshold``
      strikes quarantine the silo for ``quarantine_rounds`` rounds —
      its uploads are dropped at accept time and it leaves the
      round-completion expected set, the same exclusion path the PR 2
      heartbeat-suspicion machinery uses for corpses. At most ``byz_f``
      silos are quarantined at once (the defense's own threat budget);
      the first sync after a silo's window ends carries
      ``ARG_EF_RESET``, clearing the silo's codec error-feedback stack
      (the EF mass it accumulated against dropped frames corresponds to
      nothing the server ever aggregated).
    """

    def __init__(self, init_params, comm_round: int, num_clients: int,
                 world_size: int | None = None, round_deadline: float = 0.0,
                 quorum: int = 0, heartbeat_timeout: float = 0.0,
                 wire_masks=None, defense: str = "none", byz_f: int = 1,
                 geomed_iters: int = 8, norm_bound: float = 5.0,
                 stddev: float = 0.05, defense_seed: int = 0,
                 quarantine_rounds: int = 0, outlier_threshold: int = 2,
                 dp_delta: float = 1e-5, **kw):
        from neuroimagedisttraining_tpu.core import robust

        super().__init__(rank=0, world_size=world_size or num_clients + 1,
                         **kw)
        # defense config fails loudly HERE (startup), never mid-round
        self.defense = robust.validate_defense(defense)
        self.byz_f = int(byz_f)
        self.geomed_iters = int(geomed_iters)
        self.norm_bound = float(norm_bound)
        self.stddev = float(stddev)
        #: weak_dp noise stream root: per-round keys fold_in from here so
        #: the noise is deterministic given (defense_seed, round, silo)
        self.defense_seed = int(defense_seed)
        if self.defense in robust.ROBUST_AGGREGATORS:
            robust._check_f(num_clients, self.byz_f, self.defense)
        self.quarantine_rounds = int(quarantine_rounds)
        self.outlier_threshold = int(outlier_threshold)
        #: value-anomaly strike counters (suspicion for BAD VALUES, the
        #: analogue of the heartbeat suspicion set for dead silos)
        self._strikes: dict[int, int] = {}
        #: client -> first round index AFTER its quarantine window
        self._quarantine_until: dict[int, int] = {}
        #: silos owed an ARG_EF_RESET on their next post-window sync
        self._ef_reset_pending: set[int] = set()
        self.byz_stats = {"nonfinite_rejected": 0, "outlier_flags": 0,
                          "quarantines": []}
        #: weak_dp RDP ledger (privacy/accountant.py): per-silo Renyi
        #: moments accumulated on every weak_dp aggregation the silo's
        #: upload entered, converted to (epsilon, dp_delta) at report
        #: time. Host numpy under _rlock — never touches a trace.
        self.dp_delta = float(dp_delta)
        self._dp_rdp: dict[int, np.ndarray] = {}
        self._dp_round_info: dict | None = None
        self.params = _to_numpy_tree(init_params)
        self.wire_masks = (_to_numpy_tree(wire_masks)
                           if wire_masks is not None else None)
        self.comm_round = comm_round
        self.num_clients = num_clients
        self.round_deadline = float(round_deadline)
        self.quorum = int(quorum) if quorum > 0 else num_clients
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.round_idx = 0
        self._registered: set[int] = set()
        self._updates: dict[int, tuple] = {}
        #: silos whose THIS-round upload was hard-rejected (non-finite):
        #: they have reported — there is nothing to wait for — so they
        #: leave the round's expected set (without this, a NaN-uploading
        #: silo with fresh heartbeats deadlocks a no-deadline federation:
        #: its frame bounces but the round keeps waiting for it forever)
        self._rejected_round: set[int] = set()
        self.history: list[dict] = []
        self._done = threading.Event()
        #: guards all round state: handlers run on the dispatch thread,
        #: the deadline timer and heartbeat monitor on their own threads
        self._rlock = threading.Lock()
        self._started = False
        self._suspect: set[int] = set()
        self._last_beat: dict[int, float] = {}
        self._timer: threading.Timer | None = None
        #: bumped on every arm/cancel: a fired callback that was blocked
        #: on the lock while the round (or secure phase) moved on must
        #: become a no-op — round_idx alone cannot distinguish the
        #: secure A->B transition within one round
        self._deadline_gen = 0
        # ---- obs plane (ISSUE 9): every metric below publishes from
        # the server's existing accept/aggregate handlers (dispatch and
        # timer threads, under _rlock) — control-plane host code only,
        # never a trace. The flight recorder gets every control-plane
        # DECISION (drop/strike/quarantine/deadline/rejoin/ef-reset);
        # the registry gets the numbers a scrape wants live.
        self._obs_uploads = obs_metrics.counter(
            obs_names.SYNC_UPLOADS,
            "sync-server upload admission verdicts",
            labelnames=("outcome",))
        self._obs_round_wall = obs_metrics.histogram(
            obs_names.SYNC_ROUND_WALL,
            "wall time from a round's sync broadcast to its completion",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0))
        self._obs_quorum_wait = obs_metrics.histogram(
            obs_names.SYNC_QUORUM_WAIT,
            "wall time from a round's FIRST accepted upload to its "
            "aggregation (how long the earliest silo waited on the "
            "barrier)",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0))
        self._obs_round_gauge = obs_metrics.gauge(
            obs_names.SERVER_ROUND, "current server round/version index")
        self._obs_suspects = obs_metrics.gauge(
            obs_names.SERVER_SUSPECTS, "clients currently marked suspect")
        self._obs_strikes = obs_metrics.counter(
            obs_names.BYZ_STRIKES, "value-anomaly strikes issued")
        self._obs_quarantines = obs_metrics.counter(
            obs_names.BYZ_QUARANTINES, "silo quarantines entered")
        #: wall anchors for round_wall / quorum_wait (monotonic; None
        #: until the first broadcast / first upload of the round)
        self._round_t0: float | None = None
        self._first_upload_t: float | None = None

    @property
    def fault_tolerant(self) -> bool:
        return self.round_deadline > 0 or self.heartbeat_timeout > 0

    def suspect_clients(self) -> set[int]:
        with self._rlock:
            return set(self._suspect)

    # ---- Byzantine detection / quarantine (ISSUE 5) ----

    def _quarantined_now(self) -> set[int]:
        """Under ``_rlock``: silos inside an active quarantine window."""
        return {c for c, until in self._quarantine_until.items()
                if self.round_idx < until}

    def quarantined_clients(self) -> set[int]:
        with self._rlock:
            return self._quarantined_now()

    def _strike(self, c: int, why: str) -> None:
        """Under ``_rlock``: one value-anomaly strike against silo
        ``c``; at ``outlier_threshold`` strikes the silo is quarantined
        — unless the byz_f budget of concurrent quarantines is already
        spent (quarantining more silos than the threat model's f would
        let a clever attacker starve the federation of honest silos)."""
        self._strikes[c] = self._strikes.get(c, 0) + 1
        self.byz_stats["outlier_flags"] += 1
        self._obs_strikes.inc()
        obs_flight.record("strike", client=c, count=self._strikes[c],
                          threshold=self.outlier_threshold, why=why,
                          round=self.round_idx)
        log.warning("server: value-anomaly strike %d/%d against silo %d "
                    "(%s)", self._strikes[c], self.outlier_threshold, c,
                    why)
        if self._strikes[c] < self.outlier_threshold:
            return
        if len(self._quarantined_now()) >= max(1, self.byz_f):
            log.warning("server: silo %d hit the strike threshold but "
                        "the quarantine budget (byz_f=%d) is spent",
                        c, self.byz_f)
            return
        until = self.round_idx + 1 + self.quarantine_rounds
        self._quarantine_until[c] = until
        self._strikes[c] = 0
        self._ef_reset_pending.add(c)
        self._obs_quarantines.inc()
        obs_flight.record("quarantine", client=c,
                          from_round=self.round_idx + 1,
                          until_round=until)
        self.byz_stats["quarantines"].append(
            {"client": c, "from_round": self.round_idx + 1,
             "until_round": until})
        log.warning("server: QUARANTINED silo %d for rounds [%d, %d) — "
                    "its uploads are excluded from aggregation; its "
                    "first post-window sync will carry ef_reset", c,
                    self.round_idx + 1, until)

    # ---- weak_dp accounting (privacy/, ISSUE 8) ----

    def _note_weak_dp(self, senders: list[int],
                      ws: list[float]) -> dict | None:
        """Under ``_rlock``: charge one weak_dp round to every silo whose
        upload entered this aggregation. The mechanism per round is a
        full-participation (q=1) Gaussian with effective multiplier
        ``weak_dp_noise_multiplier`` over the ACTUAL round weights; RDP
        composes additively per silo, so deadline-truncated rounds
        charge only the survivors. Returns the round's observability
        record (clip bound, sigma, z, per-silo epsilon) for history — or
        None when the configured geometry provides no DP to account
        (stddev/norm_bound <= 0, a valid no-noise ablation: warn once,
        never die mid-aggregation on a dispatch/timer thread)."""
        from neuroimagedisttraining_tpu.privacy import accountant as acct

        if self.stddev <= 0 or self.norm_bound <= 0:
            if not getattr(self, "_warned_dp_disabled", False):
                self._warned_dp_disabled = True
                log.warning(
                    "weak_dp with stddev=%s/norm_bound=%s adds no "
                    "accountable noise — epsilon is infinite; the RDP "
                    "ledger records nothing", self.stddev,
                    self.norm_bound)
            return None
        try:
            z = acct.weak_dp_noise_multiplier(self.stddev,
                                              self.norm_bound, ws)
        except ValueError as e:
            # degenerate round weights (all-zero survivors, a NaN n the
            # admission gates let through): skip the charge with a
            # warning — this runs on dispatch/timer threads, where an
            # escape would hang the federation
            log.warning("weak_dp ledger: skipping round %d charge "
                        "(%s)", self.round_idx, e)
            return None
        step = acct.rdp_gaussian(1.0, z)
        eps = {}
        eps_gauge = obs_metrics.gauge(
            obs_names.DP_EPSILON_SILO,
            "running weak_dp epsilon per silo (server RDP ledger, "
            "privacy/accountant.py)", labelnames=("silo",))
        # burn RATE alongside the running total (ISSUE 15 satellite):
        # what THIS round cost each silo — the series a budget
        # burn-rate rule can watch; label scheme matches the engine
        # ledger's source-labeled registration (engines/base.py)
        burn_gauge = obs_metrics.gauge(
            obs_names.DP_EPSILON_PER_ROUND,
            "epsilon spent by the last accounted round (the budget "
            "burn rate --dp_epsilon_budget is judged against)",
            labelnames=("source",))
        for c in senders:
            prev = self._dp_rdp.get(c)
            prev_eps = (acct.rdp_to_epsilon(prev,
                                            delta=self.dp_delta)[0]
                        if prev is not None else 0.0)
            self._dp_rdp[c] = self._dp_rdp.get(c, 0.0) + step
            eps[c] = acct.rdp_to_epsilon(self._dp_rdp[c],
                                         delta=self.dp_delta)[0]
            eps_gauge.labels(silo=c).set(float(eps[c]))
            burn_gauge.labels(source=f"silo{c}").set(
                float(eps[c] - prev_eps))
        return {"norm_bound": self.norm_bound, "stddev": self.stddev,
                "noise_multiplier": round(z, 6), "delta": self.dp_delta,
                "epsilon_per_silo": {c: round(e, 4)
                                     for c, e in eps.items()}}

    def dp_report(self) -> dict | None:
        """Run-end per-silo (epsilon, delta) from the weak_dp ledger, or
        None when the defense never charged a round."""
        from neuroimagedisttraining_tpu.privacy import accountant as acct

        with self._rlock:
            if not self._dp_rdp:
                return None
            return {"defense": "weak_dp", "delta": self.dp_delta,
                    "norm_bound": self.norm_bound, "stddev": self.stddev,
                    "epsilon_per_silo": {
                        c: round(acct.rdp_to_epsilon(
                            rdp, delta=self.dp_delta)[0], 4)
                        for c, rdp in sorted(self._dp_rdp.items())}}

    def _score_survivors(self, senders: list[int], trees: list) -> None:
        """Under ``_rlock``: norm/cosine outlier scoring over this
        round's accepted uploads -> strikes. A silo that scores clean
        this round is forgiven one prior strike (transient turbulence —
        a bad batch, an lr spike — should not accumulate forever)."""
        if self.quarantine_rounds <= 0 or len(senders) < 3:
            return
        flags, norms = update_outlier_flags(trees, self.params)
        for c, flag, nrm in zip(senders, flags, norms):
            if flag:
                self._strike(c, f"update-delta outlier, |u|={nrm:.3g} "
                                f"round {self.round_idx}")
            elif self._strikes.get(c, 0) > 0:
                self._strikes[c] -= 1

    def run(self) -> None:
        if self.heartbeat_timeout > 0:
            threading.Thread(target=self._monitor_loop, daemon=True).start()
        super().run()

    # ---- handlers ----

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_REGISTER, self._on_register)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_SEND_MODEL, self._on_model)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_HEARTBEAT, self._on_heartbeat)

    def _on_register(self, msg: M.Message) -> None:
        with self._rlock:
            c = msg.sender_id
            self._registered.add(c)
            self._suspect.discard(c)
            self._last_beat[c] = time.monotonic()
            if not self._started:
                if len(self._registered) == self.num_clients:
                    self._started = True
                    self._broadcast_sync(M.MSG_TYPE_S2C_INIT_CONFIG)
            else:
                # late rejoin: ship the CURRENT round state directly so a
                # restarted silo re-enters without waiting a full round
                obs_flight.record("rejoin", client=c,
                                  round=self.round_idx)
                log.info("server: client %d re-registered; shipping "
                         "round %d state", c, self.round_idx)
                self._send_sync_to(M.MSG_TYPE_S2C_SYNC_MODEL, c)

    def _on_heartbeat(self, msg: M.Message) -> None:
        with self._rlock:
            self._last_beat[msg.sender_id] = time.monotonic()
            self._suspect.discard(msg.sender_id)

    def _accept_update(self, msg: M.Message) -> bool:
        """Round-tag + duplicate gate (call under ``_rlock``): True iff
        this upload belongs to the current round and is the sender's
        first. Stale rounds and re-delivered frames never double-count."""
        r = msg.get(M.ARG_ROUND_IDX)
        if r is not None and int(r) != self.round_idx:
            self._obs_uploads.inc(outcome="stale")
            obs_flight.record("drop_stale", client=msg.sender_id,
                              tagged_round=int(r), round=self.round_idx)
            log.warning("server: dropping stale upload from %d "
                        "(round %s, current %d)", msg.sender_id, r,
                        self.round_idx)
            return False
        if msg.sender_id in self._updates:
            self._obs_uploads.inc(outcome="duplicate")
            obs_flight.record("drop_duplicate", client=msg.sender_id,
                              round=self.round_idx)
            log.warning("server: dropping duplicate upload from %d "
                        "(round %d)", msg.sender_id, self.round_idx)
            return False
        if msg.sender_id in self._quarantined_now():
            self._obs_uploads.inc(outcome="quarantined")
            obs_flight.record("drop_quarantined", client=msg.sender_id,
                              round=self.round_idx)
            log.warning("server: dropping upload from QUARANTINED silo "
                        "%d (round %d; window ends at round %d)",
                        msg.sender_id, self.round_idx,
                        self._quarantine_until[msg.sender_id])
            return False
        return True

    def _on_model(self, msg: M.Message) -> None:
        with self._rlock:
            if self._done.is_set() or not self._accept_update(msg):
                return
            # decode BEFORE aggregation: self.params is still the round's
            # broadcast model here (it only advances in
            # _aggregate_and_advance), so it IS the sender's delta
            # reference; the accept gate above already rejected any frame
            # from another round. Dense uploads pass through untouched.
            try:
                decoded = codec.decode_update(msg.get(M.ARG_MODEL_PARAMS),
                                              like=self.params,
                                              reference=self.params,
                                              masks=self.wire_masks)
            except Exception as e:  # noqa: BLE001 — an undecodable frame
                # (version skew, mask-config mismatch, zlib.error /
                # msgpack OutOfData from bit rot the transport let
                # through) is a DROPPED upload, not a dead dispatch
                # thread — the deadline/quorum machinery treats the
                # sender like any other straggler. Narrow catches here
                # would let a malformed body kill server.run() (the
                # dispatch loop has no guard of its own).
                self._obs_uploads.inc(outcome="undecodable")
                obs_flight.record("drop_undecodable",
                                  client=msg.sender_id,
                                  round=self.round_idx, error=str(e))
                log.warning("server: dropping undecodable upload from %d "
                            "(round %d): %s", msg.sender_id,
                            self.round_idx, e)
                return
            # non-finite hard gate (unconditional, before any defense):
            # one NaN/Inf frame folded into the mean poisons every silo.
            # The sender is treated like a straggler by deadline/quorum,
            # and the rejection counts as a value-anomaly strike — a
            # silo shipping NaNs every round earns its quarantine.
            if not tree_all_finite(decoded):
                self.byz_stats["nonfinite_rejected"] += 1
                self._obs_uploads.inc(outcome="nonfinite")
                obs_flight.record("reject_nonfinite",
                                  client=msg.sender_id,
                                  round=self.round_idx)
                log.warning("server: REJECTING non-finite (NaN/Inf) "
                            "upload from silo %d (round %d; %d rejected "
                            "so far)", msg.sender_id, self.round_idx,
                            self.byz_stats["nonfinite_rejected"])
                if self.quarantine_rounds > 0:
                    self._strike(msg.sender_id, "non-finite upload")
                # the silo HAS reported — nothing left to wait for this
                # round; drop it from the expected set so a no-deadline
                # federation cannot deadlock on its bounced frame
                self._rejected_round.add(msg.sender_id)
                self._maybe_complete()
                return
            if not self._updates:
                self._first_upload_t = time.monotonic()
            self._updates[msg.sender_id] = (
                decoded, float(msg.get(M.ARG_NUM_SAMPLES)))
            self._obs_uploads.inc(outcome="accepted")
            self._last_beat[msg.sender_id] = time.monotonic()
            self._suspect.discard(msg.sender_id)
            self._maybe_complete()

    def _maybe_complete(self) -> None:
        """Under ``_rlock``: aggregate as soon as every non-suspect,
        non-quarantined client has reported (and the quorum floor holds)
        — suspects are picked up by the deadline path if they resurface;
        quarantined silos' uploads are dropped at accept time, so
        waiting for them would deadlock the round."""
        expected = (set(range(1, self.num_clients + 1)) - self._suspect
                    - self._quarantined_now() - self._rejected_round)
        have = set(self._updates)
        if not have and not expected and self._rejected_round:
            # every live silo reported and EVERY upload bounced at the
            # non-finite gate: nothing to aggregate and nobody left to
            # wait for — advance with the global model unchanged (the
            # rejected silos train again from the next sync) instead of
            # hanging the federation on its own rejection set (with no
            # deadline nothing else fires: the rejected silos keep
            # heartbeating, so the suspicion monitor never will)
            log.warning("server: round %d has ZERO accepted uploads "
                        "(%d rejected as non-finite) - rebroadcasting "
                        "the unchanged global model", self.round_idx,
                        len(self._rejected_round))
            if self._timer is not None:
                self._timer.cancel()
            self._rejected_round.clear()
            self._complete_round(0, survivors=[])
            return
        if not have or not expected <= have or len(have) < min(
                self.quorum, self._effective_cohort()):
            return
        self._aggregate_and_advance()

    def _effective_cohort(self) -> int:
        """Under ``_rlock``: cohort size the quorum floor applies to —
        quarantined silos can never report, and hard-rejected uploads
        never will be accepted this round, so holding the floor at
        ``num_clients`` would hang a small federation whose quorum was
        sized for the full cohort."""
        return max(1, self.num_clients - len(self._quarantined_now()
                                             | self._rejected_round))

    def _aggregate_and_advance(self) -> None:
        """Under ``_rlock``: defended aggregation over whoever reported.
        ``defense="none"`` keeps the exact jitted
        ``survivor_weighted_mean`` (fedavg_api.py:102-117 semantics, the
        engine-parity pin in tests/test_faults.py); any other defense
        dispatches through the SAME core/robust.py program the simulated
        engines trace into their round bodies. Outlier scoring runs
        FIRST, so a silo quarantined this round is excluded from this
        very aggregation."""
        from neuroimagedisttraining_tpu.core import robust

        if self._timer is not None:
            self._timer.cancel()
        senders = sorted(self._updates)
        trees = [self._updates[s][0] for s in senders]
        self._score_survivors(senders, trees)
        q = self._quarantined_now()
        if q & set(senders):
            senders = [s for s in senders if s not in q]
            trees = [self._updates[s][0] for s in senders]
        ws = [self._updates[s][1] for s in senders]
        # deadline truncation can shrink the survivor set below the
        # aggregator's breakdown requirement; an undefended round beats
        # a dead server — the SAME feasibility rule the engines resolve
        # at trace time (core/robust.py::effective_defense)
        defense = robust.effective_defense(
            self.defense, len(senders), self.byz_f, warn=log.warning)
        if defense == "none":
            self.params = survivor_weighted_mean(trees, ws)
        else:
            rngs = None
            if defense == "weak_dp":
                # deterministic per-(seed, round, silo) noise keys, the
                # same fold_in discipline the attack/engine streams use
                base = jax.random.fold_in(
                    jax.random.key(self.defense_seed), self.round_idx)
                rngs = jax.vmap(
                    lambda s: jax.random.fold_in(base, s))(
                    jnp.asarray(senders, jnp.uint32))
                self._dp_round_info = self._note_weak_dp(senders, ws)
            self.params = survivor_defended_mean(
                trees, ws, self.params, defense=defense,
                byz_f=self.byz_f, geomed_iters=self.geomed_iters,
                norm_bound=self.norm_bound, stddev=self.stddev,
                rngs=rngs)
        self._updates.clear()
        self._rejected_round.clear()
        self._complete_round(len(senders), survivors=senders)

    # ---- deadline / heartbeat machinery ----

    def _arm_deadline(self) -> None:
        if self.round_deadline <= 0 or self._done.is_set():
            return
        if self._timer is not None:
            self._timer.cancel()
        self._deadline_gen += 1
        self._timer = threading.Timer(
            self.round_deadline, self._on_deadline,
            args=(self.round_idx, self._deadline_gen))
        self._timer.daemon = True
        self._timer.start()

    def _deadline_stale(self, round_for: int, gen: int) -> bool:
        """Under ``_rlock``: True iff this callback belongs to a window
        that was superseded while the callback waited for the lock."""
        return (self._done.is_set() or self.round_idx != round_for
                or gen != self._deadline_gen)

    def _mark_missing_suspect(self, have: set[int]) -> None:
        """Under ``_rlock``: clients that missed the deadline become
        suspect — unless their heartbeat is still fresh (a straggler,
        not a corpse; it may catch up next round) or they are
        quarantined (their uploads were dropped by design)."""
        for c in (set(range(1, self.num_clients + 1)) - have
                  - self._quarantined_now()):
            if self._beat_stale(c):
                log.warning("server: marking client %d suspect "
                            "(missed round %d deadline)", c, self.round_idx)
                self._suspect.add(c)
                obs_flight.record("suspect", client=c,
                                  round=self.round_idx,
                                  why="missed deadline")
        self._obs_suspects.set(len(self._suspect))

    def _beat_stale(self, c: int) -> bool:
        if self.heartbeat_timeout <= 0:
            return True  # no liveness signal configured: missing == dead
        last = self._last_beat.get(c)
        return last is None or (time.monotonic() - last
                                > self.heartbeat_timeout)

    def _on_deadline(self, round_for: int, gen: int) -> None:
        with self._rlock:
            if self._deadline_stale(round_for, gen):
                return
            obs_flight.record("deadline", round=round_for,
                              have=len(self._updates),
                              quorum=min(self.quorum, self.num_clients))
            if self._updates and len(self._updates) >= min(
                    self.quorum, self.num_clients):
                self._mark_missing_suspect(set(self._updates))
                log.warning("server: round %d deadline - aggregating %d/%d "
                            "survivors", round_for, len(self._updates),
                            self.num_clients)
                self._aggregate_and_advance()
            else:
                self._arm_deadline()  # below quorum: keep waiting

    def _monitor_loop(self) -> None:
        poll = max(0.05, self.heartbeat_timeout / 4)
        while not self._done.wait(poll):
            now = time.monotonic()
            with self._rlock:
                if self._done.is_set():
                    return
                for c, last in list(self._last_beat.items()):
                    if (now - last > self.heartbeat_timeout
                            and c not in self._suspect):
                        log.warning("server: heartbeat from client %d "
                                    "stale (%.2fs) - marking suspect",
                                    c, now - last)
                        self._suspect.add(c)
                        obs_flight.record(
                            "suspect", client=c, round=self.round_idx,
                            why=f"heartbeat stale {now - last:.2f}s")
                        self._obs_suspects.set(len(self._suspect))
                if self._started:
                    # a new suspect may have been the only missing
                    # uploader — the round can complete right now
                    self._maybe_complete()

    def _complete_round(self, n_clients: int,
                        survivors: list[int] | None = None) -> None:
        """Shared end-of-round transition: record history, advance, then
        either finish the federation or broadcast the next sync."""
        entry = {"round": self.round_idx, "clients": n_clients}
        now = time.monotonic()
        if self._round_t0 is not None:
            self._obs_round_wall.observe(now - self._round_t0)
        if self._first_upload_t is not None:
            self._obs_quorum_wait.observe(now - self._first_upload_t)
        self._first_upload_t = None
        obs_flight.record("round_complete", round=self.round_idx,
                          clients=n_clients,
                          survivors=list(survivors or []))
        if survivors is not None:
            entry["survivors"] = list(survivors)
        if self._dp_round_info is not None:
            # weak_dp observability (ISSUE 8 satellite): the clip bound,
            # sigma, and running per-silo epsilon this round applied
            entry["weak_dp"] = self._dp_round_info
            self._dp_round_info = None
        if self._suspect:
            entry["suspects"] = sorted(self._suspect)
        q = self._quarantined_now()
        if q:
            entry["quarantined"] = sorted(q)
        self.history.append(entry)
        self.round_idx += 1
        self._obs_round_gauge.set(self.round_idx)
        self._obs_suspects.set(len(self._suspect))
        # training-health boundary (ISSUE 15): every completed round is
        # a host boundary — the armed anomaly rules must see ONE
        # evaluation per round (debounce/window semantics are
        # round-indexed), not whatever cadence a /healthz poller
        # happens to scrape at; unarmed processes no-op
        obs_rules.observe_boundary(self.round_idx)
        if self.round_idx >= self.comm_round:
            if self._timer is not None:
                self._timer.cancel()
            self._broadcast_finish()
            self._done.set()
            self.finish()
        else:
            self._broadcast_sync(M.MSG_TYPE_S2C_SYNC_MODEL)

    # ---- sends ----

    def _send_tolerant(self, msg: M.Message) -> None:
        """In fault-tolerant mode a broadcast target may be dead — use a
        short retry budget and fold failures into suspicion instead of
        crashing the dispatch/timer thread. Legacy mode keeps the strict
        raise-on-unreachable contract.

        NOTE: these sends run under ``_rlock`` (the callers are round
        transitions). A dead same-host peer refuses instantly, so the
        lock hold is sub-second; a WAN peer whose packets are BLACKHOLED
        (no RST) can pin the lock for up to retries x the 10 s connect
        timeout — an accepted tradeoff until broadcasts move to a
        dedicated sender thread."""
        if not self.fault_tolerant:
            self.send_message(msg)
            return
        try:
            try:
                self.com_manager.send_message(msg, retries=3,
                                              retry_delay=0.05)
            except TypeError:  # transport without retry knobs (broker)
                self.com_manager.send_message(msg)
        except (ConnectionError, OSError) as e:
            log.warning("server: client %d unreachable (%s) - marking "
                        "suspect", msg.receiver_id, e)
            self._suspect.add(msg.receiver_id)

    def _send_sync_to(self, msg_type: str, c: int) -> None:
        msg = M.Message(msg_type, 0, c)
        msg.add(M.ARG_MODEL_PARAMS, self.params)
        msg.add(M.ARG_ROUND_IDX, self.round_idx)
        msg.add(M.ARG_CLIENT_INDEX, c - 1)
        if (c in self._ef_reset_pending
                and c not in self._quarantined_now()):
            # first sync after the quarantine window: the silo's codec
            # error-feedback accumulated against frames this server
            # DROPPED — that mass corresponds to nothing aggregated, so
            # re-injecting it would smear stale quarantine-era residuals
            # into honest post-window uploads
            msg.add(M.ARG_EF_RESET, True)
            self._ef_reset_pending.discard(c)
            obs_flight.record("ef_reset", client=c, round=self.round_idx)
            log.info("server: silo %d quarantine window over - sync "
                     "carries ef_reset", c)
        self._send_tolerant(msg)

    def _broadcast_sync(self, msg_type: str) -> None:
        for c in range(1, self.num_clients + 1):
            self._send_sync_to(msg_type, c)
        self._round_t0 = time.monotonic()  # round-wall anchor (obs)
        self._arm_deadline()

    def _broadcast_finish(self) -> None:
        for c in range(1, self.num_clients + 1):
            self._send_tolerant(M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))


class SecureFedAvgServer(FedAvgServer):
    """Secure-aggregation server: clients upload additive SHARE SLOTS of
    their weight-scaled quantized update instead of plaintext params
    (engine parity: TurboAggregateEngine.secure_aggregate; ref
    turboaggregate/mpc_function.py:214-224 Gen_Additive_SS). The round is
    two-phase: clients first report their sample counts in the clear
    (metadata the plain protocol exposes anyway); the server replies with
    each client's NORMALIZED FedAvg weight w_c = n_c / sum n, and clients
    then share ``quantize(w_c * params)`` — with w_c <= 1 the field values
    stay within the fixed-point range regardless of cohort size. The
    server folds each arriving share set into per-slot accumulators
    (slot-major, mod p) and combines slots only once every weighted
    client has reported — or once the deadline+quorum path truncates the
    cohort, in which case the dropped clients' shares were never folded
    (atomic discard) and the dequantized sum is re-weighted over the
    survivors. Either way no stored server-side intermediate equals an
    individual client's update.

    Trust model: with ``n_aggregators == 0`` (the paper's single-
    aggregator degenerate case) each client's n_shares slots transit THIS
    server, which is trusted not to combine one client's slots before
    folding them into the accumulators. With ``n_aggregators == K > 0``
    the grouped deployment the reference's TurboAggregate describes
    (TA_trainer.py:38-85) runs for real: clients send slot j to
    aggregator-j's OS process (``SlotAggregatorProc``), each aggregator
    folds ITS slot across all clients and forwards one cross-client
    total, and this server only ever sees K totals — no single node holds
    enough to reconstruct any client (server included).

    Secure QUANTIZED mode (``quant_spec`` — privacy/secure_quant.py,
    ISSUE 8): phase B uploads become field-element frames in a small
    GF(p) (one wire-dtype residue per parameter + seed-expanded mask
    slots) instead of int64 share stacks, folded slot-major by a
    ``SlotAccumulator`` with the same atomic-discard dropout semantics
    — and bitwise-equal to the plain quantized ``tree_weighted_mean``
    over the survivor set. Quant mode lifts the clip-family defense
    rejection (each silo clips/noises its OWN update pre-share, and the
    weak_dp ledger charges here); order statistics, quarantine, the
    codec, and the grouped aggregator deployment remain out — the full
    matrix lives in ARCHITECTURE.md "Privacy plane"."""

    def __init__(self, init_params, comm_round: int, num_clients: int,
                 frac_bits: int = 16, n_aggregators: int = 0,
                 record_trace: bool = False, quant_spec=None, **kw):
        from neuroimagedisttraining_tpu.core import robust

        defense = kw.get("defense", "none")
        if quant_spec is None and (defense != "none"
                                   or kw.get("quarantine_rounds", 0)):
            # secure-DENSE aggregation is a LINEAR sum over additive
            # shares: the server never observes an individual silo's
            # update, so there is nothing for an order-statistic defense
            # to select over, nothing for the outlier scorer to score,
            # and even clipping would have to run client-side (each silo
            # clips its own update BEFORE sharing — the
            # TurboAggregateEngine composition). The QUANTIZED path
            # (--secure_quant) realizes exactly that composition for the
            # clip family; the full matrix lives in ARCHITECTURE.md
            # "Privacy plane".
            raise ValueError(
                "SecureFedAvgServer supports neither --defense nor "
                "quarantine in dense mode: additive-share aggregation "
                "never reveals per-silo updates to defend over. The "
                "clip-family defenses compose with --secure_quant "
                "(enforced CLIENT-side, pre-share); see ARCHITECTURE.md "
                "'Privacy plane'")
        if quant_spec is not None and (
                defense in robust.ROBUST_AGGREGATORS
                or kw.get("quarantine_rounds", 0)):
            raise ValueError(
                "secure_quant supports neither order-statistic defenses "
                "nor quarantine: the server still only ever sees masked "
                "field elements — there are no per-silo updates to "
                "select over or score. Clip-family defenses "
                "(norm_diff_clipping, weak_dp) run client-side, "
                "pre-share; see ARCHITECTURE.md 'Privacy plane'")
        if kw.get("wire_masks") is not None:
            # Secure aggregation stays structurally DENSE: each upload
            # is masked GF(p) material. Sparsification would leak the
            # client's mask support — the very structure the masking
            # hides — and the codec's float stages would destroy the
            # share algebra. Bandwidth comes from --secure_quant's small
            # field + seed-expanded masks instead (privacy/).
            raise ValueError(
                "SecureFedAvgServer is incompatible with the wire codec "
                "(shares are uniform field elements; encoding them would "
                "break the share algebra or leak mask support — use "
                "--secure_quant for the compressed secure wire)")
        if quant_spec is not None and n_aggregators:
            raise ValueError(
                "secure_quant does not compose with --n_aggregators: its "
                "mask slots ride as PRG seeds, and any node holding a "
                "client's seeds can expand every non-data slot — the "
                "grouped deployment's no-single-node property would be "
                "void. Use the dense --secure protocol for grouped "
                "aggregation (see ARCHITECTURE.md 'Privacy plane')")
        super().__init__(init_params, comm_round, num_clients,
                         world_size=num_clients + 1 + n_aggregators, **kw)
        self.quant_spec = quant_spec
        if quant_spec is not None:
            from neuroimagedisttraining_tpu.privacy import check_headroom

            # accumulator + aggregate-range headroom vs p and the cohort
            # fails HERE (startup), never as silent field wraparound
            check_headroom(quant_spec, num_clients)
        self.frac_bits = frac_bits
        self.n_aggregators = n_aggregators
        #: secure-quant slot accumulator (one per round, lazily built)
        self._sq_acc = None
        #: when record_trace, every post-fold slot-accumulator state
        self.sq_trace: list = [] if record_trace else None
        self._slot_acc: dict | None = None
        self._n_by_client: dict[int, float] = {}
        self._slot_totals: dict[int, dict] = {}
        #: phase within the round: "A" collecting sample counts, "B"
        #: collecting share uploads (deadline behavior differs per phase)
        self._phase = "A"
        #: normalized weight sent to each phase-A reporter this round —
        #: kept so a phase-B dropout can be re-weighted post-dequantize
        self._weights_sent: dict[int, float] = {}
        #: clients whose complete share set was folded this round; a
        #: client is in the aggregate iff it is here — shares from a
        #: dropped client are discarded atomically (its single upload
        #: message either folds whole or, when stale/duplicate, not at
        #: all — there is no partial slot fold)
        self._folded: set[int] = set()
        #: when record_trace, every aggregator total this server saw —
        #: model-sized per round, so tests-only
        self.record_trace = record_trace
        self.received_totals: list = []

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_NUM_SAMPLES, self._on_num_samples)
        self.register_message_receive_handler(
            M.MSG_TYPE_A2S_SLOT_TOTAL, self._on_slot_total)

    # ---- phase A: sample counts -> normalized weights ----

    def _on_num_samples(self, msg: M.Message) -> None:
        with self._rlock:
            r = msg.get(M.ARG_ROUND_IDX)
            if ((r is not None and int(r) != self.round_idx)
                    or self._phase != "A"
                    or msg.sender_id in self._n_by_client):
                log.warning("server: dropping stale/duplicate sample "
                            "count from %d", msg.sender_id)
                return
            self._n_by_client[msg.sender_id] = float(
                msg.get(M.ARG_NUM_SAMPLES))
            self._last_beat[msg.sender_id] = time.monotonic()
            self._suspect.discard(msg.sender_id)
            self._maybe_complete()

    def _send_agg_weights(self) -> None:
        """Under ``_rlock``: close phase A — normalize weights over the
        reporters and open phase B with a fresh deadline window."""
        total = max(sum(self._n_by_client.values()), 1e-12)
        self._weights_sent = {c: n / total
                              for c, n in self._n_by_client.items()}
        for c, w in self._weights_sent.items():
            out = M.Message(M.MSG_TYPE_S2C_AGG_WEIGHTS, 0, c)
            out.add(M.ARG_AGG_WEIGHT, w)
            out.add(M.ARG_ROUND_IDX, self.round_idx)
            self._send_tolerant(out)
        self._n_by_client.clear()
        self._phase = "B"
        self._arm_deadline()

    # ---- phase B: slot-major share accumulation ----

    def _on_model(self, msg: M.Message) -> None:
        with self._rlock:
            if self._done.is_set():
                return
            r = msg.get(M.ARG_ROUND_IDX)
            if (self._phase != "B"
                    or (r is not None and int(r) != self.round_idx)
                    or msg.sender_id in self._folded
                    or msg.sender_id not in self._weights_sent):
                log.warning("server: dropping stale/duplicate/unweighted "
                            "share upload from %d (round %s, current %d)",
                            msg.sender_id, r, self.round_idx)
                return
            self._fold_shares(msg)
            self._last_beat[msg.sender_id] = time.monotonic()
            self._suspect.discard(msg.sender_id)
            self._maybe_complete()

    def _maybe_complete(self) -> None:
        """Under ``_rlock``: phase-aware early completion — advance as
        soon as every non-suspect expected peer has reported (quorum
        floor still holds). Called from the upload handlers and from the
        heartbeat monitor when suspicion changes."""
        floor = min(self.quorum, self.num_clients)
        if self._phase == "A":
            expected = set(range(1, self.num_clients + 1)) - self._suspect
            have = set(self._n_by_client)
            if have and expected <= have and len(have) >= floor:
                self._send_agg_weights()
        else:
            expected = set(self._weights_sent) - self._suspect
            if (self._folded and expected <= self._folded
                    and len(self._folded) >= floor):
                self._finalize_secure()

    def _fold_shares(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        if self.quant_spec is not None:
            from neuroimagedisttraining_tpu.privacy import SlotAccumulator

            if self._sq_acc is None:
                # like=self.params locks the expected leaf structure, so
                # a structurally skewed frame (version-skewed silo) is
                # rejected BEFORE any accumulator mutation — the fold
                # stays atomic even for the round's first frame
                self._sq_acc = SlotAccumulator(self.quant_spec,
                                               trace=self.sq_trace,
                                               like=self.params)
            try:
                # atomic: the frame folds whole or not at all (Bonawitz
                # discard — a validation failure leaves the accumulators
                # untouched and the sender a straggler for the
                # deadline/quorum machinery, like an undecodable codec
                # frame on the plain server)
                self._sq_acc.fold(msg.get(M.ARG_MODEL_PARAMS))
            except (ValueError, KeyError, TypeError) as e:
                log.warning("server: dropping invalid secure-quant frame "
                            "from %d (round %d): %s", msg.sender_id,
                            self.round_idx, e)
                return
            self._folded.add(msg.sender_id)
            return
        shares_tree = msg.get(M.ARG_MODEL_PARAMS)  # leaves: [n_shares, ...]
        if self._slot_acc is None:
            self._slot_acc = jax.tree.map(
                lambda s: np.asarray(s, np.int64) % mpc.P_DEFAULT,
                shares_tree)
        else:
            self._slot_acc = jax.tree.map(
                lambda acc, s: (acc + np.asarray(s, np.int64))
                % mpc.P_DEFAULT, self._slot_acc, shares_tree)
        self._folded.add(msg.sender_id)

    def _finalize_secure(self) -> None:
        """Under ``_rlock``: combine slots and dequantize. When every
        phase-A reporter folded, the slot total IS the weighted mean
        (weights sum to 1 client-side). When a reporter dropped between
        phases, the survivors' weights sum to W < 1 — re-weight by 1/W
        post-dequantize so the aggregate stays a true weighted mean over
        the survivor set (Bonawitz-style dropout tolerance)."""
        from neuroimagedisttraining_tpu.ops import mpc

        if self._timer is not None:
            self._timer.cancel()
        w_sum = sum(self._weights_sent.get(c, 0.0) for c in self._folded)
        rescale = (1.0 / w_sum
                   if self._folded != set(self._weights_sent) and w_sum > 0
                   else 1.0)
        if self.quant_spec is not None:
            from neuroimagedisttraining_tpu.privacy.secure_quant import (
                leaf_scales,
            )

            # self.params is still THE round's broadcast reference here
            # (it only advances below), so these scales are the very
            # ones every uploading client derived from its sync
            self.params = self._sq_acc.finalize(
                like=self.params, rescale=rescale,
                scales=leaf_scales(self.params))
            self._sq_acc = None
        else:
            self.params = jax.tree.map(
                lambda slots, old: (rescale * mpc.dequantize(
                    np.mod(slots.sum(axis=0), mpc.P_DEFAULT),
                    frac_bits=self.frac_bits)).astype(
                        np.asarray(old).dtype),
                self._slot_acc, self.params)
            self._slot_acc = None
        survivors = sorted(self._folded)
        if self.quant_spec is not None and self.defense == "weak_dp" \
                and survivors:
            # the noise was added CLIENT-side (pre-share), but its
            # geometry is config — the server still owns the ledger and
            # the per-silo epsilon report
            self._dp_round_info = self._note_weak_dp(
                survivors, [self._weights_sent.get(c, 0.0)
                            for c in survivors])
        self._folded = set()
        self._weights_sent = {}
        self._phase = "A"
        self._complete_round(len(survivors), survivors=survivors)

    def _on_deadline(self, round_for: int, gen: int) -> None:
        with self._rlock:
            if self._deadline_stale(round_for, gen):
                return
            floor = min(self.quorum, self.num_clients)
            if self._phase == "A":
                if self._n_by_client and len(self._n_by_client) >= floor:
                    self._mark_missing_suspect(set(self._n_by_client))
                    log.warning("server: round %d phase-A deadline - "
                                "weighting %d/%d reporters", round_for,
                                len(self._n_by_client), self.num_clients)
                    self._send_agg_weights()
                else:
                    self._arm_deadline()
            else:
                if self._folded and len(self._folded) >= floor:
                    self._mark_missing_suspect(set(self._folded))
                    log.warning("server: round %d phase-B deadline - "
                                "aggregating %d/%d survivors", round_for,
                                len(self._folded), self.num_clients)
                    self._finalize_secure()
                else:
                    self._arm_deadline()

    # ---- phase B': aggregator slot totals (n_aggregators > 0) ----
    # NOTE: the grouped deployment needs ALL K slot totals to
    # reconstruct (one missing slot destroys the additive sharing), so
    # deadline/quorum applies to the degenerate single-server mode only;
    # with aggregators a dropped client stalls the aggregators' fold —
    # a documented limitation, not silently wrong math.

    def _on_slot_total(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        with self._rlock:
            total = msg.get(M.ARG_MODEL_PARAMS)
            if self.record_trace:
                self.received_totals.append(total)
            self._slot_totals[int(msg.get(M.ARG_SLOT_INDEX))] = total
            if len(self._slot_totals) < self.n_aggregators:
                return
            totals = [self._slot_totals[j]
                      for j in sorted(self._slot_totals)]
            self.params = jax.tree.map(
                lambda old, *slots: mpc.dequantize(
                    np.mod(sum(np.asarray(s, np.int64) for s in slots),
                           mpc.P_DEFAULT),
                    frac_bits=self.frac_bits).astype(
                        np.asarray(old).dtype),
                self.params, *totals)
            self._slot_totals.clear()
            # close the round's phase state so the next round's sample
            # counts pass the phase-A gate
            self._weights_sent = {}
            self._folded = set()
            self._phase = "A"
            self._complete_round(self.num_clients)

    def _broadcast_finish(self) -> None:
        super()._broadcast_finish()
        for j in range(self.n_aggregators):
            self.send_message(M.Message(M.MSG_TYPE_S2C_FINISH, 0,
                                        self.num_clients + 1 + j))


class SlotAggregatorProc(ClientManager):
    """Aggregator j (rank ``num_clients + 1 + j``): receives ONLY slot j
    of every client's additive sharing per round, folds the slots mod p
    across clients, and forwards the single cross-client total to the
    server — TurboAggregate's grouped aggregation
    (turboaggregate/TA_trainer.py:38-85): one share slot reveals nothing
    about a client (it is uniform in GF(p)), and the forwarded total only
    reveals the cross-client sum of that slot."""

    def __init__(self, slot_index: int, num_clients: int,
                 n_aggregators: int, record_trace: bool = False, **kw):
        super().__init__(rank=num_clients + 1 + slot_index,
                         world_size=num_clients + 1 + n_aggregators, **kw)
        self.slot_index = slot_index
        self.num_clients = num_clients
        self._acc = None
        self._clients_in = 0
        #: when record_trace, every share received keyed by sender rank —
        #: model-sized per client per round, so tests-only (they assert
        #: what this process COULD learn); senders are always counted
        self.record_trace = record_trace
        self.received: dict[int, list] = {}

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            M.MSG_TYPE_C2A_SEND_SLOT, self._on_slot)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_FINISH, lambda msg: self.finish())

    def _on_slot(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        slot = msg.get(M.ARG_MODEL_PARAMS)
        lst = self.received.setdefault(msg.sender_id, [])
        if self.record_trace:
            lst.append(slot)
        if self._acc is None:
            self._acc = jax.tree.map(
                lambda s: np.asarray(s, np.int64) % mpc.P_DEFAULT, slot)
        else:
            self._acc = jax.tree.map(
                lambda a, s: (a + np.asarray(s, np.int64)) % mpc.P_DEFAULT,
                self._acc, slot)
        self._clients_in += 1
        if self._clients_in < self.num_clients:
            return
        out = M.Message(M.MSG_TYPE_A2S_SLOT_TOTAL, self.rank, 0)
        out.add(M.ARG_MODEL_PARAMS, self._acc)
        out.add(M.ARG_SLOT_INDEX, self.slot_index)
        self.send_message(out)
        self._acc = None
        self._clients_in = 0


class FedAvgClientProc(ClientManager):
    """Rank >= 1. Trains via the injected ``train_fn`` on every sync.

    ``heartbeat_interval`` > 0 starts a liveness thread beating to the
    server every interval — the signal the server's suspicion machinery
    (``heartbeat_timeout``) consumes. Uploads echo the sync's round
    index so the server can reject stale/duplicate frames.

    ``wire_codec`` encodes every model upload (codec/wire.py): delta vs
    the sync just received, mask-sparse against ``wire_masks`` (shipped
    bitmap-free — the server holds the same mask via its own
    ``wire_masks``, the engine mask handoff), or top-k sparse with this
    silo's persistent error-feedback accumulator ``_wire_ef`` threaded
    across rounds (dropped mass and quantization error re-enter the next
    round's residual, EF-SGD semantics). A dropped upload loses one
    round's kept mass like any dense upload would; the EF state itself
    never desyncs because it lives entirely on this sender. A sync
    carrying ``ARG_EF_RESET`` (the server's post-quarantine signal)
    clears the accumulator before this round trains.

    ``fault_schedule`` + ``seed`` (ISSUE 5): when the schedule carries
    ``byz:`` value faults, this silo transforms its OWN upload through
    ``faults/adversary.attack_update`` before any encoding — the
    attacker controls what its silo encodes, the server defends on what
    it decodes. The transform is the same jax math the simulated
    engines vmap over their client axis, keyed by (seed, round, rank),
    so one seed produces one attack trace in both federations."""

    #: monotone upload counter (ARG_UPLOAD_SEQ, class-level default so
    #: partially-constructed test doubles inherit it): lets the async
    #: buffered server (asyncfl/) distinguish a transport-duplicated
    #: frame from an honest repeat contribution; the sync server
    #: ignores it (round-tag dedup)
    _upload_seq = 0

    def __init__(self, rank: int, num_clients: int,
                 train_fn: Callable, world_size: int | None = None,
                 heartbeat_interval: float = 0.0, wire_codec: str = "none",
                 wire_masks=None, wire_topk_ratio: float = 0.25,
                 fault_schedule=None, seed: int = 0,
                 sync_delta: bool = False, **kw):
        super().__init__(rank=rank, world_size=world_size or num_clients + 1,
                         **kw)
        self.num_clients = num_clients
        self.train_fn = train_fn
        self.heartbeat_interval = float(heartbeat_interval)
        self.final_params = None
        self._hb_stop = threading.Event()
        self._wire_spec = codec.parse_wire_spec(wire_codec, wire_topk_ratio)
        self.wire_masks = (_to_numpy_tree(wire_masks)
                           if wire_masks is not None else None)
        self._wire_ef = None  # per-silo error-feedback accumulator
        #: last full model body received, reused when a cached-sync
        #: reply (version unchanged; asyncfl/ingest.py) omits the body
        self._last_sync_params = None
        #: opt into lossless delta sync bodies (ISSUE 18): changed-
        #: version replies may then ship the byte delta against the
        #: version named in ``_last_sync_version`` instead of the tree
        self.sync_delta = bool(sync_delta)
        self._last_sync_version = -1
        #: value-fault schedule (None, or a FaultSchedule whose spec may
        #: schedule THIS rank to upload Byzantine values)
        self.fault_schedule = fault_schedule
        self.seed = int(seed)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_SYNC_MODEL, self._on_sync)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_FINISH, self._on_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        reg = M.Message(M.MSG_TYPE_C2S_REGISTER, self.rank, 0)
        # exactly-once dedup (ISSUE 18): this process lifetime IS the
        # incarnation — a restarted silo gets a fresh one (fresh seq
        # space), a reconnecting one keeps it, so a post-migration
        # ingest worker installs the root's accepted-seq floor for this
        # incarnation before replying
        reg.add(M.ARG_CLIENT_INCARNATION, os.getpid())
        if self.sync_delta:
            reg.add(M.ARG_SYNC_DELTA_OK, True)
        # the server process may still be initializing (model build + jit
        # compile) when this silo is ready — give the FIRST contact a
        # generous retry window on transports that support it (capped
        # exponential backoff: ~0.25s ramping to 2s, ~5 min total)
        try:
            self.com_manager.send_message(reg, retries=150,
                                          retry_delay=0.25)
        except TypeError:  # transport without retry knobs (e.g. broker)
            self.com_manager.send_message(reg)
        if self.heartbeat_interval > 0:
            threading.Thread(target=self._heartbeat_loop,
                             daemon=True).start()
        self.com_manager.handle_receive_message()
        self._hb_stop.set()  # loop exited (finish or simulated crash)

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval):
            beat = M.Message(M.MSG_TYPE_C2S_HEARTBEAT, self.rank, 0)
            try:
                try:
                    self.com_manager.send_message(beat, retries=1)
                except TypeError:
                    self.com_manager.send_message(beat)
            except Exception:  # noqa: BLE001 — liveness is best-effort;
                # a missed beat (server busy/gone) must not kill the loop
                pass

    def _resolve_sync_params(self, msg: M.Message, round_idx: int):
        """The cached-sync contract (sharded ingest plane,
        asyncfl/ingest.py): an upload answered at an UNCHANGED version
        omits the model body — this silo already holds that exact tree
        from its previous sync. A body-less sync before any full sync
        is a protocol error (the ingest worker always ships the full
        model on register and on every version change); returns None
        for that dropped-sync case.

        Delta bodies (ISSUE 18, ``sync_delta`` opted in): a changed-
        version reply may carry the lossless byte delta against the
        version this silo last synced; it decodes against the held
        base bitwise. A delta naming any OTHER base is a protocol
        error handled LOUDLY (drop, never apply to a wrong base)."""
        params = msg.get(M.ARG_MODEL_PARAMS)
        if params is None:
            if self._last_sync_params is None:
                log.error("silo %d: body-less sync at version %d with no "
                          "cached model - dropping the sync", self.rank,
                          round_idx)
                return None
            return self._last_sync_params
        if codec.is_sync_delta_frame(params):
            base_v = int(params.get("base", -1))
            if (self._last_sync_params is None
                    or base_v != self._last_sync_version):
                log.error(
                    "silo %d: sync delta at version %d names base %d "
                    "but this silo holds %d - dropping the sync",
                    self.rank, round_idx, base_v,
                    self._last_sync_version)
                return None
            params = codec.decode_sync_delta(params,
                                             self._last_sync_params)
        self._last_sync_params = params
        self._last_sync_version = int(round_idx)
        return params

    def _on_sync(self, msg: M.Message) -> None:
        round_idx = int(msg.get(M.ARG_ROUND_IDX))
        params = self._resolve_sync_params(msg, round_idx)
        if params is None:
            return
        if msg.get(M.ARG_EF_RESET):
            log.info("silo %d: server requested ef_reset (round %d) - "
                     "clearing the codec error-feedback accumulator",
                     self.rank, round_idx)
            self._wire_ef = None
        new_params, n = self.train_fn(params, round_idx)
        payload = _to_numpy_tree(new_params)
        if self.fault_schedule is not None:
            # value-fault hook BEFORE encoding: a Byzantine silo encodes
            # its attacked update like any honest payload (the defense
            # runs server-side on the decoded frame)
            from neuroimagedisttraining_tpu.faults import adversary

            payload = adversary.attack_update(
                self.fault_schedule, self.seed, round_idx, self.rank,
                payload, _to_numpy_tree(params))
        if self._wire_spec is not None:
            # the delta reference is the sync we JUST trained from — the
            # server holds the identical tree for this round tag
            upload_finite = tree_all_finite(payload)
            payload, ef_next = codec.encode_update(
                self._wire_spec, payload,
                reference=_to_numpy_tree(params),
                masks=self.wire_masks, ef=self._wire_ef,
                mask_on_wire=False)
            # a non-finite upload bounces at the server's hard gate, and
            # absorbing its NaN residual would park NaN in the EF stack
            # FOREVER (every later encode consumes it — a one-round
            # value fault becomes permanent rejection). The consumed EF
            # corresponds to a frame that was never aggregated, so drop
            # the stack — the same invariant as the server's
            # post-quarantine ARG_EF_RESET.
            self._wire_ef = ef_next if upload_finite else None
        out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        out.add(M.ARG_MODEL_PARAMS, payload)
        out.add(M.ARG_NUM_SAMPLES, float(n))
        out.add(M.ARG_ROUND_IDX, round_idx)
        out.add(M.ARG_UPLOAD_SEQ, self._upload_seq)
        # wire trace context (ISSUE 13): the client originates the flow
        # — every downstream hop (worker admission, root aggregate)
        # links its events to this id, so one upload reads as a
        # causally-connected track in the merged trace
        ctx = obs_trace.make_trace_ctx(self.rank, self._upload_seq)
        out.add(M.ARG_TRACE_CTX, ctx)
        self._upload_seq += 1
        if obs_trace.TRACER.armed:
            with obs_trace.span("client_upload", round=round_idx):
                obs_trace.flow("upload", obs_trace.flow_id_of(ctx), "s",
                               round=round_idx)
                self.send_message(out)
        else:
            self.send_message(out)

    def _on_finish(self, msg: M.Message) -> None:
        self.final_params = None  # server holds the aggregate
        self._hb_stop.set()
        self.finish()


class SecureFedAvgClientProc(FedAvgClientProc):
    """Client for ``SecureFedAvgServer``: after local training it reports
    ``n_c`` in the clear, waits for its normalized weight w_c, then
    uploads additive shares of ``quantize(w_c * params)``. w_c <= 1 keeps
    the fixed-point embedding exact (|x| * 2^frac_bits < p/2) for any
    cohort size; the server reconstructs only the weighted mean."""

    def __init__(self, rank: int, num_clients: int, train_fn: Callable,
                 n_shares: int = 3, frac_bits: int = 16, mpc_seed: int = 0,
                 n_aggregators: int = 0, quant_spec=None,
                 one_phase: bool = False, defense: str = "none",
                 norm_bound: float = 5.0, stddev: float = 0.05,
                 defense_seed: int = 0, **kw):
        from neuroimagedisttraining_tpu.core import robust

        if n_aggregators and n_aggregators != n_shares:
            raise ValueError(
                f"n_aggregators ({n_aggregators}) must equal n_shares "
                f"({n_shares}): slot j routes to aggregator j")
        if n_aggregators and quant_spec is not None:
            raise ValueError(
                "secure_quant does not compose with --n_aggregators "
                "(seed-expanded mask slots; see SecureFedAvgServer)")
        if kw.get("wire_codec", "none") != "none" or \
                kw.get("wire_masks") is not None:
            raise ValueError(
                "SecureFedAvgClientProc is incompatible with the wire "
                "codec: secure uploads must ride the wire as field "
                "elements (see SecureFedAvgServer — encoding breaks the "
                "GF(p) share algebra or leaks mask support; "
                "--secure_quant IS the compressed secure wire)")
        sched = kw.get("fault_schedule")
        if sched is not None and sched.spec.any_value_faults:
            raise ValueError(
                "byz: value faults cannot be simulated under --secure: "
                "the secure client's upload path shares BEFORE any "
                "value hook could run, and the server has no plaintext "
                "updates to defend — the attack would go both "
                "uninjected and undefended (see ARCHITECTURE.md)")
        if one_phase and quant_spec is None:
            raise ValueError(
                "one_phase (the async buffered protocol) requires a "
                "quant_spec: the dense two-phase weight exchange IS a "
                "round barrier (see asyncfl/server.py)")
        if defense != "none":
            robust.validate_defense(defense)
            if quant_spec is None or defense not in robust.CLIP_DEFENSES:
                raise ValueError(
                    f"client-side defense {defense!r} composes only with "
                    "secure_quant and only for the clip family "
                    "(norm_diff_clipping, weak_dp) — each silo clips/"
                    "noises its OWN update before sharing; see "
                    "ARCHITECTURE.md 'Privacy plane'")
        super().__init__(rank, num_clients, train_fn,
                         world_size=num_clients + 1 + n_aggregators, **kw)
        self.n_shares = n_shares
        self.frac_bits = frac_bits
        self.n_aggregators = n_aggregators
        self.quant_spec = quant_spec
        self.one_phase = bool(one_phase)
        self.defense = defense
        self.norm_bound = float(norm_bound)
        self.stddev = float(stddev)
        self.defense_seed = int(defense_seed)
        self._rng = np.random.default_rng(mpc_seed * 7919 + rank)
        self._trained = None  # params awaiting the weight reply
        self._sync_ref = None  # the sync tree (client-side clip baseline)

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_AGG_WEIGHTS, self._on_weights)

    def _client_side_defense(self, trained, round_idx: int):
        """Clip-family enforcement at the only place secure aggregation
        allows it — the silo's own update, BEFORE quantize/share (the
        TurboAggregateEngine composition). THE core/robust.py transforms
        run verbatim (``norm_diff_clip``, then ``add_weak_dp_noise``
        from a jax key folded from (defense_seed, round, rank) — the
        config-threaded stream discipline nidtlint's dp-key-discipline
        rule enforces), so a secure-quant silo applies bit-for-bit the
        defense a plain server would have."""
        if self.defense == "none" or self._sync_ref is None:
            return trained
        from neuroimagedisttraining_tpu.core import robust

        ref = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32),
                           self._sync_ref)
        out = robust.norm_diff_clip(
            jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), trained),
            ref, self.norm_bound)
        if self.defense == "weak_dp":
            key = jax.random.fold_in(jax.random.fold_in(
                jax.random.key(self.defense_seed), round_idx), self.rank)
            out = robust.add_weak_dp_noise(out, key, self.stddev)
        return _to_numpy_tree(out)

    def _sq_upload(self, payload, round_idx, weight: float) -> None:
        """Encode one secure-quant field-element frame and ship it (the
        one upload message of this round — folds whole or not at all).
        Per-leaf scales derive from the sync reference — the identical
        tree the server holds for this round tag, so both ends compute
        the identical scales with nothing extra on the wire."""
        from neuroimagedisttraining_tpu.privacy import encode_secure_quant
        from neuroimagedisttraining_tpu.privacy.secure_quant import (
            leaf_scales,
        )

        frame = encode_secure_quant(payload, weight, self.quant_spec,
                                    self._rng,
                                    scales=leaf_scales(self._sync_ref))
        out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        out.add(M.ARG_MODEL_PARAMS, frame)
        if round_idx is not None:
            out.add(M.ARG_ROUND_IDX, int(round_idx))
        out.add(M.ARG_UPLOAD_SEQ, self._upload_seq)
        self._upload_seq += 1
        self.send_message(out)

    def _on_sync(self, msg: M.Message) -> None:
        round_idx = int(msg.get(M.ARG_ROUND_IDX))
        params = self._resolve_sync_params(msg, round_idx)
        if params is None:  # dropped cached-sync protocol error
            return
        new_params, n = self.train_fn(params, round_idx)
        self._sync_ref = _to_numpy_tree(params)
        trained = self._client_side_defense(_to_numpy_tree(new_params),
                                            round_idx)
        if self.one_phase:
            # async buffered protocol: no phase-A weight exchange (it IS
            # a round barrier) — ship the UNWEIGHTED quantized update +
            # n in the clear; the server folds integer-scaled staleness
            # weights inside the field (asyncfl/server.py)
            from neuroimagedisttraining_tpu.privacy import (
                encode_secure_quant,
            )

            frame = encode_secure_quant(trained, 1.0, self.quant_spec,
                                        self._rng)
            out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
            out.add(M.ARG_MODEL_PARAMS, frame)
            out.add(M.ARG_NUM_SAMPLES, float(n))
            out.add(M.ARG_ROUND_IDX, round_idx)
            out.add(M.ARG_UPLOAD_SEQ, self._upload_seq)
            self._upload_seq += 1
            self.send_message(out)
            return
        self._trained = trained
        out = M.Message(M.MSG_TYPE_C2S_NUM_SAMPLES, self.rank, 0)
        out.add(M.ARG_NUM_SAMPLES, float(n))
        out.add(M.ARG_ROUND_IDX, round_idx)
        self.send_message(out)

    def _on_weights(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        round_idx = msg.get(M.ARG_ROUND_IDX)
        w = float(msg.get(M.ARG_AGG_WEIGHT))
        if self.quant_spec is not None:
            payload, self._trained = self._trained, None
            self._sq_upload(payload, round_idx, w)
            return
        shares_tree = jax.tree.map(
            lambda x: mpc.additive_shares(
                mpc.quantize(w * np.asarray(x, np.float64),
                             frac_bits=self.frac_bits),
                self.n_shares, rng=self._rng),
            self._trained)
        self._trained = None
        if self.n_aggregators:
            # slot j -> aggregator j (rank num_clients+1+j): no single
            # node ever holds two of this client's slots
            for j in range(self.n_aggregators):
                out = M.Message(M.MSG_TYPE_C2A_SEND_SLOT, self.rank,
                                self.num_clients + 1 + j)
                out.add(M.ARG_MODEL_PARAMS,
                        jax.tree.map(lambda s: s[j], shares_tree))
                out.add(M.ARG_SLOT_INDEX, j)
                if round_idx is not None:
                    out.add(M.ARG_ROUND_IDX, int(round_idx))
                self.send_message(out)
            return
        out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        out.add(M.ARG_MODEL_PARAMS, shares_tree)
        if round_idx is not None:
            out.add(M.ARG_ROUND_IDX, int(round_idx))
        self.send_message(out)
