"""Cross-silo federated orchestration over the socket control plane.

The capability SURVEY §2.3 requires: a server/client message loop carrying
the reference protocol {register -> init/broadcast params -> local train ->
upload update -> aggregate -> sync or finish} (client_manager.py /
server_manager.py semantics), as runnable processes. Within one silo the
bulk compute path is still the jitted SPMD round program; this layer
coordinates *between* silos (separate hosts/processes), where the
reference's MPI/gRPC runtime would have lived — model payloads ride the
msgpack codec, and each silo trains with its own jitted LocalTrainer round.

``FedAvgServer.run()`` drives ``comm_round`` rounds; each
``FedAvgClientProc`` owns a ``train_fn(params, round_idx) -> (params,
num_samples)`` — silos are free to implement it with any engine. Weighted
aggregation happens on the server in float32 numpy (parity:
fedavg_api.py:102-117).

Multi-host TPU pods: use ``init_multihost`` (jax.distributed) so each silo
process joins one global JAX runtime and bulk tensors can instead ride DCN
collectives; the socket plane then only carries control messages.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np
import jax

from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.managers import (
    ClientManager, ServerManager,
)


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int) -> None:
    """Join this process to a multi-host JAX runtime (DCN collectives).
    Thin wrapper so silos opt in with one call; requires all processes to
    call it before any backend touch.

    On real TPU pods this makes every host's chips part of one global mesh
    (libtpu handles cross-host wiring) so the client axis spans hosts and
    aggregation rides ICI/DCN. NOTE: it cannot be smoke-tested in this
    build's CPU backend — two CPU processes each come up with
    process_count=1 (multiprocess CPU clustering is disabled in this jax
    build; verified empirically), so the cross-process capability test
    lives in the socket control plane instead
    (tests/test_distributed.py::test_cross_silo_multiprocess_smoke)."""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def _to_numpy_tree(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


class FedAvgServer(ServerManager):
    """Rank 0. Aggregates client updates sample-weighted per round."""

    def __init__(self, init_params, comm_round: int, num_clients: int,
                 world_size: int | None = None, **kw):
        super().__init__(rank=0, world_size=world_size or num_clients + 1,
                         **kw)
        self.params = _to_numpy_tree(init_params)
        self.comm_round = comm_round
        self.num_clients = num_clients
        self.round_idx = 0
        self._registered: set[int] = set()
        self._updates: dict[int, tuple] = {}
        self.history: list[dict] = []
        self._done = threading.Event()

    # ---- handlers ----

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_REGISTER, self._on_register)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_SEND_MODEL, self._on_model)

    def _on_register(self, msg: M.Message) -> None:
        self._registered.add(msg.sender_id)
        if len(self._registered) == self.num_clients:
            self._broadcast_sync(M.MSG_TYPE_S2C_INIT_CONFIG)

    def _on_model(self, msg: M.Message) -> None:
        self._updates[msg.sender_id] = (
            msg.get(M.ARG_MODEL_PARAMS), float(msg.get(M.ARG_NUM_SAMPLES)))
        if len(self._updates) < self.num_clients:
            return
        # weighted FedAvg (fedavg_api.py:102-117)
        trees, ws = zip(*self._updates.values())
        w = np.asarray(ws, np.float64)
        w = w / w.sum()
        self.params = jax.tree.map(
            lambda *leaves: sum(
                wi * np.asarray(leaf, np.float32)
                for wi, leaf in zip(w, leaves)).astype(
                    np.asarray(leaves[0]).dtype),
            *trees)
        self._updates.clear()
        self._complete_round(int(len(ws)))

    def _complete_round(self, n_clients: int) -> None:
        """Shared end-of-round transition: record history, advance, then
        either finish the federation or broadcast the next sync."""
        self.history.append({"round": self.round_idx,
                             "clients": n_clients})
        self.round_idx += 1
        if self.round_idx >= self.comm_round:
            self._broadcast_finish()
            self._done.set()
            self.finish()
        else:
            self._broadcast_sync(M.MSG_TYPE_S2C_SYNC_MODEL)

    # ---- sends ----

    def _broadcast_sync(self, msg_type: str) -> None:
        for c in range(1, self.num_clients + 1):
            msg = M.Message(msg_type, 0, c)
            msg.add(M.ARG_MODEL_PARAMS, self.params)
            msg.add(M.ARG_ROUND_IDX, self.round_idx)
            msg.add(M.ARG_CLIENT_INDEX, c - 1)
            self.send_message(msg)

    def _broadcast_finish(self) -> None:
        for c in range(1, self.num_clients + 1):
            self.send_message(M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))


class SecureFedAvgServer(FedAvgServer):
    """Secure-aggregation server: clients upload additive SHARE SLOTS of
    their weight-scaled quantized update instead of plaintext params
    (engine parity: TurboAggregateEngine.secure_aggregate; ref
    turboaggregate/mpc_function.py:214-224 Gen_Additive_SS). The round is
    two-phase: clients first report their sample counts in the clear
    (metadata the plain protocol exposes anyway); the server replies with
    each client's NORMALIZED FedAvg weight w_c = n_c / sum n, and clients
    then share ``quantize(w_c * params)`` — with w_c <= 1 the field values
    stay within the fixed-point range regardless of cohort size. The
    server folds each arriving share set into per-slot accumulators
    (slot-major, mod p) and combines slots only once ALL clients have
    reported — so no stored server-side intermediate equals an individual
    client's update.

    Trust model: with ``n_aggregators == 0`` (the paper's single-
    aggregator degenerate case) each client's n_shares slots transit THIS
    server, which is trusted not to combine one client's slots before
    folding them into the accumulators. With ``n_aggregators == K > 0``
    the grouped deployment the reference's TurboAggregate describes
    (TA_trainer.py:38-85) runs for real: clients send slot j to
    aggregator-j's OS process (``SlotAggregatorProc``), each aggregator
    folds ITS slot across all clients and forwards one cross-client
    total, and this server only ever sees K totals — no single node holds
    enough to reconstruct any client (server included)."""

    def __init__(self, init_params, comm_round: int, num_clients: int,
                 frac_bits: int = 16, n_aggregators: int = 0,
                 record_trace: bool = False, **kw):
        super().__init__(init_params, comm_round, num_clients,
                         world_size=num_clients + 1 + n_aggregators, **kw)
        self.frac_bits = frac_bits
        self.n_aggregators = n_aggregators
        self._slot_acc: dict | None = None
        self._n_by_client: dict[int, float] = {}
        self._n_clients_in = 0
        self._slot_totals: dict[int, dict] = {}
        #: when record_trace, every aggregator total this server saw —
        #: model-sized per round, so tests-only
        self.record_trace = record_trace
        self.received_totals: list = []

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_NUM_SAMPLES, self._on_num_samples)
        self.register_message_receive_handler(
            M.MSG_TYPE_A2S_SLOT_TOTAL, self._on_slot_total)

    # ---- phase A: sample counts -> normalized weights ----

    def _on_num_samples(self, msg: M.Message) -> None:
        self._n_by_client[msg.sender_id] = float(
            msg.get(M.ARG_NUM_SAMPLES))
        if len(self._n_by_client) < self.num_clients:
            return
        total = max(sum(self._n_by_client.values()), 1e-12)
        for c, n in self._n_by_client.items():
            out = M.Message(M.MSG_TYPE_S2C_AGG_WEIGHTS, 0, c)
            out.add(M.ARG_AGG_WEIGHT, n / total)
            out.add(M.ARG_ROUND_IDX, self.round_idx)
            self.send_message(out)
        self._n_by_client.clear()

    # ---- phase B: slot-major share accumulation ----

    def _on_model(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        shares_tree = msg.get(M.ARG_MODEL_PARAMS)  # leaves: [n_shares, ...]
        if self._slot_acc is None:
            self._slot_acc = jax.tree.map(
                lambda s: np.asarray(s, np.int64) % mpc.P_DEFAULT,
                shares_tree)
        else:
            self._slot_acc = jax.tree.map(
                lambda acc, s: (acc + np.asarray(s, np.int64))
                % mpc.P_DEFAULT, self._slot_acc, shares_tree)
        self._n_clients_in += 1
        if self._n_clients_in < self.num_clients:
            return
        # weights already sum to 1 client-side, so the slot total IS the
        # weighted mean
        self.params = jax.tree.map(
            lambda slots, old: mpc.dequantize(
                np.mod(slots.sum(axis=0), mpc.P_DEFAULT),
                frac_bits=self.frac_bits).astype(np.asarray(old).dtype),
            self._slot_acc, self.params)
        self._slot_acc = None
        n_in, self._n_clients_in = self._n_clients_in, 0
        self._complete_round(n_in)

    # ---- phase B': aggregator slot totals (n_aggregators > 0) ----

    def _on_slot_total(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        total = msg.get(M.ARG_MODEL_PARAMS)
        if self.record_trace:
            self.received_totals.append(total)
        self._slot_totals[int(msg.get(M.ARG_SLOT_INDEX))] = total
        if len(self._slot_totals) < self.n_aggregators:
            return
        totals = [self._slot_totals[j] for j in sorted(self._slot_totals)]
        self.params = jax.tree.map(
            lambda old, *slots: mpc.dequantize(
                np.mod(sum(np.asarray(s, np.int64) for s in slots),
                       mpc.P_DEFAULT),
                frac_bits=self.frac_bits).astype(np.asarray(old).dtype),
            self.params, *totals)
        self._slot_totals.clear()
        self._complete_round(self.num_clients)

    def _broadcast_finish(self) -> None:
        super()._broadcast_finish()
        for j in range(self.n_aggregators):
            self.send_message(M.Message(M.MSG_TYPE_S2C_FINISH, 0,
                                        self.num_clients + 1 + j))


class SlotAggregatorProc(ClientManager):
    """Aggregator j (rank ``num_clients + 1 + j``): receives ONLY slot j
    of every client's additive sharing per round, folds the slots mod p
    across clients, and forwards the single cross-client total to the
    server — TurboAggregate's grouped aggregation
    (turboaggregate/TA_trainer.py:38-85): one share slot reveals nothing
    about a client (it is uniform in GF(p)), and the forwarded total only
    reveals the cross-client sum of that slot."""

    def __init__(self, slot_index: int, num_clients: int,
                 n_aggregators: int, record_trace: bool = False, **kw):
        super().__init__(rank=num_clients + 1 + slot_index,
                         world_size=num_clients + 1 + n_aggregators, **kw)
        self.slot_index = slot_index
        self.num_clients = num_clients
        self._acc = None
        self._clients_in = 0
        #: when record_trace, every share received keyed by sender rank —
        #: model-sized per client per round, so tests-only (they assert
        #: what this process COULD learn); senders are always counted
        self.record_trace = record_trace
        self.received: dict[int, list] = {}

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            M.MSG_TYPE_C2A_SEND_SLOT, self._on_slot)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_FINISH, lambda msg: self.finish())

    def _on_slot(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        slot = msg.get(M.ARG_MODEL_PARAMS)
        lst = self.received.setdefault(msg.sender_id, [])
        if self.record_trace:
            lst.append(slot)
        if self._acc is None:
            self._acc = jax.tree.map(
                lambda s: np.asarray(s, np.int64) % mpc.P_DEFAULT, slot)
        else:
            self._acc = jax.tree.map(
                lambda a, s: (a + np.asarray(s, np.int64)) % mpc.P_DEFAULT,
                self._acc, slot)
        self._clients_in += 1
        if self._clients_in < self.num_clients:
            return
        out = M.Message(M.MSG_TYPE_A2S_SLOT_TOTAL, self.rank, 0)
        out.add(M.ARG_MODEL_PARAMS, self._acc)
        out.add(M.ARG_SLOT_INDEX, self.slot_index)
        self.send_message(out)
        self._acc = None
        self._clients_in = 0


class FedAvgClientProc(ClientManager):
    """Rank >= 1. Trains via the injected ``train_fn`` on every sync."""

    def __init__(self, rank: int, num_clients: int,
                 train_fn: Callable, world_size: int | None = None, **kw):
        super().__init__(rank=rank, world_size=world_size or num_clients + 1,
                         **kw)
        self.num_clients = num_clients
        self.train_fn = train_fn
        self.final_params = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_SYNC_MODEL, self._on_sync)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_FINISH, self._on_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        reg = M.Message(M.MSG_TYPE_C2S_REGISTER, self.rank, 0)
        # the server process may still be initializing (model build + jit
        # compile) when this silo is ready — give the FIRST contact a
        # generous retry window on transports that support it
        try:
            self.com_manager.send_message(reg, retries=1200,
                                          retry_delay=0.25)
        except TypeError:  # transport without retry knobs (e.g. broker)
            self.com_manager.send_message(reg)
        self.com_manager.handle_receive_message()

    def _on_sync(self, msg: M.Message) -> None:
        params = msg.get(M.ARG_MODEL_PARAMS)
        round_idx = int(msg.get(M.ARG_ROUND_IDX))
        new_params, n = self.train_fn(params, round_idx)
        out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        out.add(M.ARG_MODEL_PARAMS, _to_numpy_tree(new_params))
        out.add(M.ARG_NUM_SAMPLES, float(n))
        self.send_message(out)

    def _on_finish(self, msg: M.Message) -> None:
        self.final_params = None  # server holds the aggregate
        self.finish()


class SecureFedAvgClientProc(FedAvgClientProc):
    """Client for ``SecureFedAvgServer``: after local training it reports
    ``n_c`` in the clear, waits for its normalized weight w_c, then
    uploads additive shares of ``quantize(w_c * params)``. w_c <= 1 keeps
    the fixed-point embedding exact (|x| * 2^frac_bits < p/2) for any
    cohort size; the server reconstructs only the weighted mean."""

    def __init__(self, rank: int, num_clients: int, train_fn: Callable,
                 n_shares: int = 3, frac_bits: int = 16, mpc_seed: int = 0,
                 n_aggregators: int = 0, **kw):
        if n_aggregators and n_aggregators != n_shares:
            raise ValueError(
                f"n_aggregators ({n_aggregators}) must equal n_shares "
                f"({n_shares}): slot j routes to aggregator j")
        super().__init__(rank, num_clients, train_fn,
                         world_size=num_clients + 1 + n_aggregators, **kw)
        self.n_shares = n_shares
        self.frac_bits = frac_bits
        self.n_aggregators = n_aggregators
        self._rng = np.random.default_rng(mpc_seed * 7919 + rank)
        self._trained = None  # params awaiting the weight reply

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_AGG_WEIGHTS, self._on_weights)

    def _on_sync(self, msg: M.Message) -> None:
        params = msg.get(M.ARG_MODEL_PARAMS)
        round_idx = int(msg.get(M.ARG_ROUND_IDX))
        new_params, n = self.train_fn(params, round_idx)
        self._trained = _to_numpy_tree(new_params)
        out = M.Message(M.MSG_TYPE_C2S_NUM_SAMPLES, self.rank, 0)
        out.add(M.ARG_NUM_SAMPLES, float(n))
        self.send_message(out)

    def _on_weights(self, msg: M.Message) -> None:
        from neuroimagedisttraining_tpu.ops import mpc

        w = float(msg.get(M.ARG_AGG_WEIGHT))
        shares_tree = jax.tree.map(
            lambda x: mpc.additive_shares(
                mpc.quantize(w * np.asarray(x, np.float64),
                             frac_bits=self.frac_bits),
                self.n_shares, rng=self._rng),
            self._trained)
        self._trained = None
        if self.n_aggregators:
            # slot j -> aggregator j (rank num_clients+1+j): no single
            # node ever holds two of this client's slots
            for j in range(self.n_aggregators):
                out = M.Message(M.MSG_TYPE_C2A_SEND_SLOT, self.rank,
                                self.num_clients + 1 + j)
                out.add(M.ARG_MODEL_PARAMS,
                        jax.tree.map(lambda s: s[j], shares_tree))
                out.add(M.ARG_SLOT_INDEX, j)
                self.send_message(out)
            return
        out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        out.add(M.ARG_MODEL_PARAMS, shares_tree)
        self.send_message(out)
