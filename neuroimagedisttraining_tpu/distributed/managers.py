"""Handler-registry client/server managers (the control-plane event loops).

Parity: fedml_core/distributed/communication/client/client_manager.py:13-73
and server/server_manager.py:13-68 — a manager owns a comm backend, exposes
``register_message_receive_handler(msg_type, fn)``, runs a receive loop that
dispatches by message type, and ``finish()`` tears the loop down (the
reference's MPI teardown is COMM_WORLD.Abort(); ours is a clean stop).
"""

from __future__ import annotations

from typing import Callable

from neuroimagedisttraining_tpu.distributed.comm import (
    BaseCommManager, Observer, SocketCommManager,
)
from neuroimagedisttraining_tpu.distributed.message import Message


class DistributedManager(Observer):
    """Common base of ClientManager/ServerManager (both have identical
    shape in the reference; only registered handlers differ)."""

    def __init__(self, rank: int, world_size: int,
                 comm: BaseCommManager | None = None,
                 host_map: dict[int, str] | None = None,
                 base_port: int | None = None):
        kw = {} if base_port is None else {"base_port": base_port}
        self.rank = rank
        self.world_size = world_size
        self.com_manager = comm or SocketCommManager(rank, world_size,
                                                     host_map=host_map, **kw)
        self.com_manager.add_observer(self)
        self._handlers: dict[str, Callable[[Message], None]] = {}

    def register_message_receive_handler(
            self, msg_type: str, handler: Callable[[Message], None]) -> None:
        self._handlers[msg_type] = handler

    def receive_message(self, msg_type: str, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            raise KeyError(
                f"rank {self.rank}: no handler for message type "
                f"{msg_type!r} (have {sorted(self._handlers)})")
        handler(msg)

    def send_message(self, msg: Message) -> None:
        self.com_manager.send_message(msg)

    def run(self) -> None:
        """Register handlers then block dispatching until finish()."""
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self) -> None:
        raise NotImplementedError

    def finish(self) -> None:
        self.com_manager.stop_receive_message()


class ClientManager(DistributedManager):
    pass


class ServerManager(DistributedManager):
    pass
