"""Transport-agnostic communication abstraction + the TCP socket backend.

Abstraction parity: fedml_core/distributed/communication/base_com_manager.py:7-27
(``BaseCommManager``: send_message / add_observer / handle_receive_message /
stop_receive_message) and observer.py:4-7 (``Observer.receive_message``).

Backend re-design: the reference ships MPI (daemon send/recv threads +
0.3 s polling loop, mpi/com_manager.py:13-98), a gRPC manager that cannot
import in the fork, and MQTT. None of those suit a TPU-pod deployment; the
bulk path there is XLA collectives over ICI/DCN (parallel/mesh.py), and the
control plane only carries small coordination messages. This backend is a
dependency-free TCP transport: length-prefixed msgpack frames, one listener
thread per process, blocking dispatch via a queue (no polling sleep), clean
shutdown via sentinel (the reference kills threads with
PyThreadState_SetAsyncExc, mpi_send_thread.py:47-53 — unsound; we join).

Rank->address resolution mirrors the gRPC backend's ip-config table
(grpc_comm_manager.py:53-74): {rank: (host, base_port + rank)}.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from abc import ABC, abstractmethod

from neuroimagedisttraining_tpu.distributed.message import Message
from neuroimagedisttraining_tpu.obs import names as obs_names

BASE_PORT = 50000  # parity: gRPC backend's 50000 + rank (grpc_server.py)


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type: str, msg: Message) -> None: ...


class BaseCommManager(ABC):
    """5-method contract (base_com_manager.py:7-27)."""

    @abstractmethod
    def send_message(self, msg: Message) -> None: ...

    @abstractmethod
    def add_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def remove_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def handle_receive_message(self) -> None: ...

    @abstractmethod
    def stop_receive_message(self) -> None: ...


def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class QueueDispatchMixin:
    """Shared receive-side machinery for every transport: observer list,
    blocking message queue, sentinel shutdown. Subclasses feed the queue
    from their listener thread via ``_enqueue`` and call ``_stop_dispatch``
    on teardown.

    Also owns the transport-agnostic BYTE ACCOUNTING the wire-codec A/B
    reads (`scripts/run_wire_bench.sh`): subclasses report each frame's
    on-the-wire size via ``_count_sent``/``_count_recv`` (listener thread
    and sender threads race, hence the dedicated lock) and
    ``byte_stats()`` returns the totals."""

    _STOP = object()

    def _init_dispatch(self) -> None:
        from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

        self._observers: list[Observer] = []
        self._q: queue.Queue = queue.Queue()
        self._stats_lock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.frames_sent = 0
        self.frames_recv = 0
        # obs mirror (ISSUE 9): the SAME on-the-wire sizes publish into
        # the process-global metrics registry, labeled by this
        # transport's rank, so one /metrics scrape carries what
        # byte_stats() reports (equality pinned in tests/test_obs.py —
        # counters here and attributes above increment in lockstep, no
        # second measurement, no double counting)
        rank = str(getattr(self, "rank", getattr(self, "client_id", "?")))
        lab = dict(rank=rank)
        self._obs_bytes_sent = obs_metrics.counter(
            obs_names.COMM_BYTES_SENT,
            "bytes put on the wire by this transport (frame incl. "
            "length prefix)", labelnames=("rank",)).labels(**lab)
        self._obs_bytes_recv = obs_metrics.counter(
            obs_names.COMM_BYTES_RECV,
            "bytes received off the wire by this transport",
            labelnames=("rank",)).labels(**lab)
        self._obs_frames_sent = obs_metrics.counter(
            obs_names.COMM_FRAMES_SENT, "frames sent",
            labelnames=("rank",)).labels(**lab)
        self._obs_frames_recv = obs_metrics.counter(
            obs_names.COMM_FRAMES_RECV, "frames received",
            labelnames=("rank",)).labels(**lab)

    def _count_sent(self, n: int) -> None:
        with self._stats_lock:
            self.bytes_sent += int(n)
            self.frames_sent += 1
        self._obs_bytes_sent.inc(int(n))
        self._obs_frames_sent.inc()

    def _count_recv(self, n: int) -> None:
        with self._stats_lock:
            self.bytes_recv += int(n)
            self.frames_recv += 1
        self._obs_bytes_recv.inc(int(n))
        self._obs_frames_recv.inc()

    def byte_stats(self) -> dict[str, int]:
        with self._stats_lock:
            return {"bytes_sent": self.bytes_sent,
                    "bytes_recv": self.bytes_recv,
                    "frames_sent": self.frames_sent,
                    "frames_recv": self.frames_recv}

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _enqueue(self, msg: Message) -> None:
        self._q.put(msg)

    def handle_receive_message(self) -> None:
        """Blocking dispatch loop (the reference polls with a 0.3 s sleep,
        mpi/com_manager.py:71-79; a blocking queue needs no sleep)."""
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            for obs in list(self._observers):
                obs.receive_message(item.msg_type, item)

    def _stop_dispatch(self) -> None:
        self._q.put(self._STOP)


class SocketCommManager(QueueDispatchMixin, BaseCommManager):
    """Point-to-point TCP manager for one rank.

    Every rank listens on ``base_port + rank``; ``send_message`` opens a
    short-lived connection to the receiver's port and writes one
    length-prefixed frame. ``handle_receive_message`` blocks dispatching
    queued messages to observers until ``stop_receive_message``.
    """

    def __init__(self, rank: int, world_size: int,
                 host_map: dict[int, str] | None = None,
                 base_port: int = BASE_PORT):
        self.rank = rank
        self.world_size = world_size
        self.base_port = base_port
        self.host_map = host_map or {r: "127.0.0.1"
                                     for r in range(world_size)}
        self._init_dispatch()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("0.0.0.0", base_port + rank))
        self._server.listen(world_size * 2)
        self._running = True
        self._listener = threading.Thread(target=self._listen_loop,
                                          daemon=True)
        self._listener.start()

    # ---- receive side ----

    def _listen_loop(self) -> None:
        import logging

        log = logging.getLogger("neuroimagedisttraining_tpu.comm")
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # socket closed during shutdown
            # one bad peer (RST mid-frame, corrupt payload) must not kill
            # the rank's only listener thread — log and keep serving
            try:
                with conn:
                    header = _recv_exact(conn, 8)
                    if header is None:
                        continue
                    (length,) = struct.unpack("!Q", header)
                    raw = _recv_exact(conn, length)
                    if raw is None:
                        continue
                self._count_recv(length + 8)
                self._enqueue(Message.from_bytes(raw))
            except Exception as e:  # noqa: BLE001 — any bad peer data
                # (wrong schema -> TypeError/KeyError, msgpack OutOfData,
                # RST -> OSError) must not kill the only listener thread
                log.warning("rank %d: dropped malformed/aborted frame: %s",
                            self.rank, e)

    def stop_receive_message(self) -> None:
        self._running = False
        self._stop_dispatch()
        try:
            self._server.close()
        except OSError:
            pass

    # ---- send side ----

    def send_message(self, msg: Message, retries: int = 7,
                     retry_delay: float = 0.1,
                     max_delay: float = 2.0) -> None:
        """Send one frame with capped exponential backoff between
        connection attempts: attempt ``i`` sleeps
        ``min(max_delay, retry_delay * 2**i)``. A fixed interval hammers
        a restarting peer with connect storms; backoff spreads the same
        patience over far fewer attempts. The default budget
        (~5 s: 0.1+0.2+0.4+0.8+1.6+2.0) matches the historical
        50 x 0.1 s fixed-interval wait; callers wanting a longer window
        (e.g. first contact while the server jit-compiles) pass bigger
        ``retries``."""
        import time

        from neuroimagedisttraining_tpu.distributed.message import (
            frame_bytes,
        )

        frame = frame_bytes(msg)
        addr = (self.host_map[msg.receiver_id],
                self.base_port + msg.receiver_id)
        last_err: Exception | None = None
        for attempt in range(retries):  # receiver may not be listening yet
            try:
                with socket.create_connection(addr, timeout=10.0) as conn:
                    conn.sendall(frame)  # nidt: allow[lock-send] -- conn is a fresh per-frame connection local to this call; no concurrent writer exists
                self._count_sent(len(frame))
                return
            except OSError as e:
                last_err = e
                if attempt + 1 < retries:
                    time.sleep(min(max_delay,
                                   retry_delay * (2.0 ** attempt)))
        raise ConnectionError(
            f"rank {self.rank} could not reach rank {msg.receiver_id} "
            f"at {addr}: {last_err}")
