"""Free-port allocation for multi-rank socket runs.

The socket transport listens on ``base_port + rank`` (comm.py) — a fixed
``BASE_PORT`` collides the moment two suites (or two CI shards) run on
one host. ``free_port_block`` hands out a contiguous block that is (a)
proven bindable by actually binding every port, and (b) taken from
BELOW the kernel's ephemeral port range (``ip_local_port_range``), so
an unrelated outbound connection can never transiently grab a port
inside the block between allocation and use — the failure mode of
anchoring at a kernel-assigned ephemeral port, where every short-lived
``send_message`` connection in the process draws local ports from the
same pool.

Concurrent allocators (parallel CI shards) start their scans at
pid-derived offsets and are disambiguated by the bind probe; the probe
uses plain binds (no SO_REUSEADDR) so a block still in TIME_WAIT from a
previous test is skipped rather than handed out twice.
"""

from __future__ import annotations

import itertools
import os
import socket

_SCAN_LO = 20000  # below this live well-known/registered services
_CALL_SEQ = itertools.count()


def _ephemeral_low(default: int = 32768) -> int:
    """First port of the kernel's local (outbound) port range."""
    try:
        with open("/proc/sys/net/ipv4/ip_local_port_range") as f:
            return int(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        return default


def _block_bindable(base: int, n: int) -> bool:
    held: list[socket.socket] = []
    try:
        for i in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("127.0.0.1", base + i))
            held.append(s)
        return True
    except OSError:
        return False
    finally:
        for s in held:
            s.close()


def free_port_block(n: int, tries: int = 256) -> int:
    """Return a base port such that ``base .. base + n - 1`` were all
    bindable a moment ago and sit outside the kernel's outbound port
    pool."""
    if n <= 0:
        raise ValueError(f"need a positive block size, got {n}")
    hi = _ephemeral_low() - n - 1
    if hi > _SCAN_LO:
        span = hi - _SCAN_LO
        start = (os.getpid() * 7919 + next(_CALL_SEQ) * (n + 3)) % span
        for i in range(tries):
            base = _SCAN_LO + (start + i * (n + 3)) % span
            if _block_bindable(base, n):
                return base
    # degenerate configuration (tiny/absent ephemeral range): fall back
    # to kernel-assigned anchors — rare enough that the transient
    # outbound-port hazard is acceptable
    for _ in range(tries):
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as probe:
            probe.bind(("127.0.0.1", 0))
            base = probe.getsockname()[1]
        if base + n < 65535 and _block_bindable(base, n):
            return base
    raise RuntimeError(f"could not find {n} contiguous free ports "
                       f"in {tries} tries")
