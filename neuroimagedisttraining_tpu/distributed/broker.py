"""Broker-based pub/sub transport (the reference's MQTT role, dependency-free).

Semantics parity with ``MqttCommManager`` (mqtt_comm_manager.py:14-126):

- server (id 0) subscribes ``<topic><client_id>`` for every client 1..N and
  publishes to ``<topic>0_<receiver_id>`` (mqtt_comm_manager.py:59-69,
  101-117);
- client ``c`` subscribes ``<topic>0_<c>`` and publishes to ``<topic><c>``.

Instead of an external MQTT broker + paho, the broker here is an in-repo
TCP fan-out daemon (one thread per connection, topic -> subscriber map):
peers keep ONE persistent connection carrying length-prefixed SUB/PUB
frames. Payloads are the framework's msgpack ``Message`` envelope
(distributed/message.py), not JSON — tensors stay binary. The broker
retains the last message per topic (MQTT ``retain``), so a subscriber
that arrives after a publish still receives the latest state — without
this a blind broadcast races the SUB frame and deadlocks the protocol.

Concurrency contract: every outbound socket has a write lock (a frame is
written atomically even when several serve threads fan out to the same
subscriber); retained delivery happens under the new subscriber's write
lock taken BEFORE registration is published, so a concurrent live PUB
cannot be overtaken by the stale retained frame.

This is the third transport behind the ``BaseCommManager`` ABC
(comm.py:39-55), swappable with ``SocketCommManager`` point-to-point.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading

from neuroimagedisttraining_tpu.distributed.comm import (
    BaseCommManager,
    QueueDispatchMixin,
    _recv_exact,
)
from neuroimagedisttraining_tpu.distributed.message import Message

_OP_SUB = 0
_OP_PUB = 1
_HDR = struct.Struct("!BHQ")  # op, topic_len, payload_len

log = logging.getLogger("neuroimagedisttraining_tpu.broker")


def _write_frame(conn: socket.socket, op: int, topic: str,
                 payload: bytes = b"") -> None:
    t = topic.encode()
    conn.sendall(_HDR.pack(op, len(t), len(payload)) + t + payload)  # nidt: allow[lock-send] -- frame-atomicity helper: every caller holds the destination socket's write lock (contract above)


def _read_frame(conn: socket.socket) -> tuple[int, str, bytes] | None:
    hdr = _recv_exact(conn, _HDR.size)
    if hdr is None:
        return None
    op, tlen, plen = _HDR.unpack(hdr)
    t = _recv_exact(conn, tlen)
    if t is None:
        return None
    payload = _recv_exact(conn, plen) if plen else b""
    if plen and payload is None:
        return None
    return op, t.decode(), payload


class MessageBroker:
    """Topic fan-out daemon: SUB registers the connection under a topic,
    PUB forwards the frame to every subscriber of that topic and retains
    it for late subscribers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(64)
        self.port = self._server.getsockname()[1]
        self._subs: dict[str, list[socket.socket]] = {}
        self._retained: dict[str, bytes] = {}
        self._wlocks: dict[socket.socket, threading.Lock] = {}
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            # bounded sends broker-side too: a hung subscriber (stopped
            # reader, full TCP buffer) must not wedge the serving thread
            # that is fanning out under that subscriber's write lock —
            # same rationale as BrokerCommManager's SO_SNDTIMEO. Send-only:
            # recv must still block indefinitely for idle subscribers.
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            struct.pack("ll", 30, 0))
            with self._lock:
                self._conns.add(conn)
                self._wlocks[conn] = threading.Lock()
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _send_to(self, conn: socket.socket, topic: str,
                 payload: bytes) -> bool:
        """Atomic frame write under the connection's write lock."""
        wlock = self._wlocks.get(conn)
        if wlock is None:
            return False
        try:
            with wlock:
                _write_frame(conn, _OP_PUB, topic, payload)
            return True
        except OSError:
            return False

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                frame = _read_frame(conn)
                if frame is None:
                    break
                op, topic, payload = frame
                if op == _OP_SUB:
                    # hold the subscriber's write lock ACROSS registration
                    # + retained delivery: a live PUB that sees the new
                    # subscription must queue behind the retained frame,
                    # so the newest message is never overtaken by a stale
                    # retained one
                    wlock = self._wlocks.get(conn)  # stop()/_drop may race
                    if wlock is None:
                        break
                    with wlock:
                        with self._lock:
                            self._subs.setdefault(topic, []).append(conn)
                            late = self._retained.get(topic)
                        if late is not None:
                            try:
                                _write_frame(conn, _OP_PUB, topic, late)
                            except OSError:
                                break
                elif op == _OP_PUB:
                    with self._lock:
                        targets = list(self._subs.get(topic, ()))
                        self._retained[topic] = payload
                    for t in targets:
                        if not self._send_to(t, topic, payload):
                            # a failed send (timeout mid-frame or OSError)
                            # may leave the subscriber's byte stream torn
                            # mid-length-prefix — every later frame on ANY
                            # topic would be misparsed. Tear the whole
                            # connection down, not just this subscription.
                            self._drop(t)
        except OSError:
            pass  # conn closed under us (peer died / broker.stop()) —
            # normal teardown, not a serve-thread crash to report
        finally:
            self._drop(conn)

    def _drop(self, conn: socket.socket) -> None:
        with self._lock:
            for subs in self._subs.values():
                if conn in subs:
                    subs.remove(conn)
            self._wlocks.pop(conn, None)
            self._conns.discard(conn)
        try:
            conn.close()
        except OSError:
            pass

    def stop(self) -> None:
        """Tear down the listener AND every live connection (their serve
        threads exit on the closed socket)."""
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            self._drop(c)


class BrokerCommManager(QueueDispatchMixin, BaseCommManager):
    """Pub/sub comm manager over a ``MessageBroker`` with the reference's
    MQTT topic scheme; same 5-method contract as ``SocketCommManager``."""

    def __init__(self, host: str, port: int, topic: str = "fedml",
                 client_id: int = 0, client_num: int = 0):
        self.client_id = client_id
        self.client_num = client_num
        self._topic = topic
        self._init_dispatch()
        self._conn = socket.create_connection((host, port), timeout=30.0)
        # receives must block indefinitely (an idle subscription is normal
        # — clearing the connect timeout keeps the reader alive), but sends
        # stay bounded via SO_SNDTIMEO so a wedged broker (full TCP buffer)
        # surfaces an error instead of deadlocking publishers on _send_lock
        self._conn.settimeout(None)
        self._conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                              struct.pack("ll", 30, 0))
        self._send_lock = threading.Lock()
        if client_id == 0:  # server: one inbound topic per client
            for cid in range(1, client_num + 1):
                self._subscribe(f"{topic}{cid}")
        else:  # client: the server->me topic
            self._subscribe(f"{topic}0_{client_id}")
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _subscribe(self, t: str) -> None:
        with self._send_lock:
            _write_frame(self._conn, _OP_SUB, t)

    def _read_loop(self) -> None:
        while True:
            try:
                frame = _read_frame(self._conn)
            except OSError:
                frame = None
            if frame is None:
                # broker gone or stream closed: unblock the dispatch loop
                # instead of hanging it forever
                log.warning("peer %d: broker connection closed",
                            self.client_id)
                self._stop_dispatch()
                return
            try:
                self._count_recv(len(frame[2]))
                self._enqueue(Message.from_bytes(frame[2]))
            except Exception as e:  # noqa: BLE001 — framing is intact, so
                # a bad payload is droppable without desyncing the stream
                log.warning("peer %d: dropped malformed payload: %s",
                            self.client_id, e)

    # ---- BaseCommManager contract ----

    def send_message(self, msg: Message) -> None:
        if self.client_id == 0:
            t = f"{self._topic}0_{msg.receiver_id}"
        else:
            t = f"{self._topic}{self.client_id}"
        raw = msg.to_bytes()
        with self._send_lock:
            try:
                _write_frame(self._conn, _OP_PUB, t, raw)
            except OSError:
                # a failed/timed-out sendall may have written a PARTIAL
                # frame — the stream is desynced and must not be reused;
                # closing also stops the reader, which unblocks dispatch
                try:
                    self._conn.close()
                except OSError:
                    pass
                raise
        # count only frames that actually reached the wire (parity with
        # the socket transport's after-sendall accounting): chaos-killed
        # sends must not inflate the A/B byte numbers
        self._count_sent(len(raw))

    def stop_receive_message(self) -> None:
        self._stop_dispatch()
        try:
            self._conn.close()
        except OSError:
            pass
