"""FedProx: FedAvg with a global-model proximal term in the local objective.

BASELINE.json configs[3] names "FedProx + robust aggregation
(fedml_core/robustness) under Byzantine clients"; the reference repo ships
the robustness half (fedml_core/robustness/robust_aggregation.py:32-55) but
no fedprox engine, so the round shape here is FedAvg's
(fedml_api/standalone/fedavg/fedavg_api.py:40-117) with the FedProx local
objective

    min_w  F_c(w) + (mu/2) * ||w - w_global||^2

handled by proximal-gradient splitting: after every SGD step on F_c, pull
``w -= lr * mu * (w - w_global)`` — the exact update the reference's Ditto
trainer applies for its personal proximal term
(fedml_api/standalone/ditto/my_model_trainer.py:63-64), here referenced to
the round's INCOMING global model (FedProx) rather than Ditto's concurrent
global track. ``mu`` reuses the reference's ``lamda`` flag.

Aggregation, sampling, evaluation, the final fine-tune pass, streaming, and
the robust defenses (``--defense_type norm_diff_clipping`` / ``weak_dp``)
are inherited from the FedAvg engine unchanged — composing FedProx with
Byzantine-client clipping is exactly the blueprint config.
"""

from __future__ import annotations

from neuroimagedisttraining_tpu.engines.fedavg import FedAvgEngine


class FedProxEngine(FedAvgEngine):
    name = "fedprox"
    supports_streaming = True

    def _prox_kwargs(self, global_params) -> dict:
        # inside the vmapped per-client closure the unbatched global
        # reference broadcasts as a constant (same pattern as Ditto's
        # personal track, engines/ditto.py)
        return {"prox_lamda": float(self.cfg.fed.lamda),
                "prox_ref": global_params}
