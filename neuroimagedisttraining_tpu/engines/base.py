"""Shared federated-simulation substrate for all algorithm engines.

Every reference engine has the same shape (SURVEY.md §2.4): constructor takes
the dataset + trainer, ``.train()`` runs ``comm_round`` rounds of
{sample clients -> local train -> aggregate -> evaluate}. Here that shape is
factored once: subclasses provide jitted round programs; this base provides
model/state initialization, reference-parity client sampling
(np.random.seed(round_idx), fedavg_api.py:92-100), full-cohort evaluation
(global + personalized, sailentgrads_api.py:231-285), metrics logging, and
the ``stat_info`` accumulators (sailentgrads_api.py:334-346).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.codec import wire as codec_wire
from neuroimagedisttraining_tpu.config import ExperimentConfig
from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.losses import binary_auc
from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer
from neuroimagedisttraining_tpu.core.optim import round_lr
from neuroimagedisttraining_tpu.data.federate import FederatedData
from neuroimagedisttraining_tpu.faults import adversary
from neuroimagedisttraining_tpu.faults.schedule import (
    FaultSchedule, parse_fault_spec,
)
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.obs import actions as obs_actions
from neuroimagedisttraining_tpu.obs import compute as obs_compute
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import health as obs_health
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import rules as obs_rules
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.parallel import cohort
from neuroimagedisttraining_tpu.parallel.mesh import (
    client_sharding, make_mesh, replicated_sharding,
)
from neuroimagedisttraining_tpu.utils import checkpoint as ckpt
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger, get_logger
from neuroimagedisttraining_tpu.utils import pytree as pt

PyTree = Any


class FederatedEngine:
    """Base class: owns config, trainer, data, mesh, logging, eval."""

    name = "base"
    supports_streaming = False  # engines opt in (need all-client state
    # resident otherwise)
    #: engines whose round program applies the wire codec's lossy
    #: roundtrip to client uploads before aggregation (codec/, ISSUE 3);
    #: others must reject --wire_codec loudly instead of silently
    #: training dense while reporting encoded-bytes accounting of 0
    supports_wire_codec = False
    #: engines whose round program routes client uploads through
    #: faults/adversary.py when the fault schedule carries ``byz:``
    #: value faults (ISSUE 5); others must reject such a spec loudly
    #: instead of silently simulating an attack-free federation
    supports_byz_faults = False
    #: defenses this engine's round program can realize; anything else
    #: in --defense fails at STARTUP, never mid-round (ISSUE 5
    #: satellite). Base engines aggregate with a plain weighted mean and
    #: support no defense at all.
    supported_defenses: tuple = ("none",)
    #: engines whose round body can run its local-training stage under
    #: the cohort-sharded client mesh (``--client_mesh``, ISSUE 6,
    #: parallel/cohort.py); others fall back to the unsharded round with
    #: a logged reason (same pattern as fused-dispatch fallback)
    supports_cohort_sharding = False
    #: engines whose round program realizes the --dp_clip/--dp_sigma
    #: round-level DP transform (clip each client's update delta, add
    #: Gaussian noise from config-folded jax keys — privacy/, ISSUE 8);
    #: others must reject the flags loudly instead of silently training
    #: without the noise the accountant would be charging for
    supports_dp = False
    #: engines whose declared round routes the builder's DEFAULT
    #: sanitize/defend/aggregate tail — exactly the engines where
    #: ``--secure_quant`` can swap that tail for the in-process secure
    #: QUANTIZED aggregation stage (ROADMAP 1(b),
    #: program.secure_quant_aggregate); engines with a custom aggregate
    #: stage (or none) have no server fold for the field algebra to
    #: protect and must reject the flag loudly
    supports_secure_quant = False
    #: engines whose STREAMING driver can run fused K-round windows
    #: (ISSUE 10): the window's shards are prefetched as one [K, S, ...]
    #: stack (data/stream.py prefetch_window) and the scanned round body
    #: consumes one round per step — window k+1's host read + device_put
    #: ride behind window k's scan. Others keep the round-granular
    #: streamed feed and collapse to K=1 with the logged streaming
    #: reason.
    supports_fused_streaming = False

    def __init__(self, cfg: ExperimentConfig, fed_data: FederatedData | None,
                 trainer: LocalTrainer, mesh=None,
                 logger: ExperimentLogger | None = None, stream=None):
        """``fed_data``: device-resident federation, or None when running in
        streaming mode with a ``StreamingFederation`` (cohort > HBM)."""
        self.cfg = cfg
        self.data = fed_data
        self.stream = stream
        self.trainer = trainer
        self.mesh = mesh
        self.log = logger or ExperimentLogger(cfg.log_dir, cfg.data.dataset,
                                              cfg.identity())
        self._console = get_logger()
        # reflex plane (ISSUE 20, obs/actions.py): engine-side state the
        # registered action handlers mutate. Initialized EARLY — the
        # ctor below may build round programs, and the builder's
        # aggregate tail reads ``active_defense()`` at trace time.
        # Quarantine windows are (from_round, until_round) pairs keyed
        # by client: a pure function of the round index, so
        # ``record_privacy``'s cohort re-derivation replays exactly the
        # cohorts training used (windows only ever start AFTER the
        # round that fired them).
        self._quarantine_windows: dict[int, list[tuple[int, int]]] = {}
        self._sampled_by_round: dict[int, np.ndarray] = {}
        self._last_health_rows: dict[int, dict] = {}
        self._defense_override: str | None = None
        self._healthy_pin: dict | None = None
        self._pending_rollback: dict | None = None
        self._preempts_done: set[int] = set()
        if stream is not None and not self.supports_streaming:
            from neuroimagedisttraining_tpu.engines import ENGINES
            ok = sorted({c.name for c in ENGINES.values()
                         if c.supports_streaming})
            raise ValueError(
                f"algorithm {self.name!r} does not support --streaming "
                "(its round needs every client's DATA device-resident, not "
                f"just its state); streaming currently supports: {ok}")
        if fed_data is not None:
            self.num_clients = int(fed_data.num_clients)  # incl. mesh padding
            self._n_train_host = np.asarray(fed_data.n_train)
        elif stream is not None:
            self.num_clients = int(stream.num_clients)
            self._n_train_host = np.asarray(stream.n_train)
        else:
            raise ValueError("need fed_data or stream")
        self.real_clients = int(np.sum(self._n_train_host > 0))
        # deterministic fault injection (faults/): the SAME seeded
        # schedule that drives the multiprocess federation filters the
        # simulated round's cohort, so one config seed replays one fault
        # trace in both worlds (engine client index c == rank c + 1)
        spec = (parse_fault_spec(cfg.fed.fault_spec)
                if cfg.fed.fault_spec else None)
        self.fault_schedule = (FaultSchedule(spec, cfg.seed)
                               if spec is not None and spec.any_faults
                               else None)
        if spec is not None and spec.any_value_faults \
                and not self.supports_byz_faults:
            from neuroimagedisttraining_tpu.engines import ENGINES
            ok = sorted({c.name for c in ENGINES.values()
                         if c.supports_byz_faults})
            raise ValueError(
                f"algorithm {self.name!r} does not simulate byz: value "
                "faults (its round program does not route client "
                "uploads through faults/adversary.py, so the spec "
                f"would silently run attack-free); supported: {ok}")
        # defense validation at STARTUP (ISSUE 5 satellite): an unknown
        # --defense name, or one this engine's round cannot realize,
        # must fail here — not as a trace error mid-round
        robust.validate_defense(cfg.fed.defense_type)
        if cfg.fed.defense_type not in self.supported_defenses:
            raise ValueError(
                f"algorithm {self.name!r} does not support --defense "
                f"{cfg.fed.defense_type!r}; this engine supports: "
                f"{', '.join(self.supported_defenses)}")
        if cfg.fed.defense_type in robust.ROBUST_AGGREGATORS:
            # surface breakdown-point violations (2f >= n, n < f+3)
            # before any data loads rather than at first-trace time
            robust._check_f(cfg.fed.client_num_per_round,
                            cfg.fed.byz_f, cfg.fed.defense_type)
        # round-level DP (--dp_clip/--dp_sigma, privacy/ ISSUE 8) fails
        # at STARTUP on engines whose round never applies the transform:
        # an unapplied noise config with a running accountant would
        # report epsilon for privacy nobody got
        if cfg.fed.dp_sigma < 0 or cfg.fed.dp_clip < 0:
            raise ValueError(
                f"dp_sigma/dp_clip must be >= 0 (got "
                f"{cfg.fed.dp_sigma}/{cfg.fed.dp_clip})")
        if cfg.fed.dp_sigma > 0 and cfg.fed.dp_clip <= 0:
            raise ValueError(
                "--dp_sigma needs --dp_clip > 0: the clip bound IS the "
                "sensitivity the noise multiplier is stated against "
                "(privacy/accountant.py)")
        if (cfg.fed.dp_sigma > 0 or cfg.fed.dp_clip > 0) \
                and not self.supports_dp:
            from neuroimagedisttraining_tpu.engines import ENGINES
            ok = sorted({c.name for c in ENGINES.values()
                         if c.supports_dp})
            raise ValueError(
                f"algorithm {self.name!r} does not apply the "
                "--dp_clip/--dp_sigma round-level DP transform (its "
                "round program would train un-noised while the "
                f"accountant reported epsilon); supported: {ok}")
        #: privacy ledger (privacy/accountant.py): per-round RDP of the
        #: armed noise path — weak_dp defense (subsampled cohorts) or
        #: the engine DP transform (full participation) — recorded
        #: through ``record_privacy`` at host boundaries
        self._dp_rdp = None
        self._dp_recorded_through = -1
        # wire codec (codec/, ISSUE 3): the lossy value transform the
        # cross-silo wire would apply to this engine's uploads, run
        # in-sim before aggregation so round metrics reflect the encoded
        # deployment; engines that own pruning masks hand them to the
        # codec via wire_masks() (mask handoff)
        self.wire_spec = codec_wire.parse_wire_spec(
            cfg.fed.wire_codec, cfg.fed.wire_topk_ratio)
        if self.wire_spec is not None and not self.supports_wire_codec:
            from neuroimagedisttraining_tpu.engines import ENGINES
            ok = sorted({c.name for c in ENGINES.values()
                         if c.supports_wire_codec})
            raise ValueError(
                f"algorithm {self.name!r} does not simulate --wire_codec "
                "(its round program does not pass client uploads through "
                "the codec roundtrip, so the flag would silently train "
                f"dense); supported: {ok}. Masked engines still expose "
                "wire_masks() for the cross-silo plane "
                "(distributed/run.py), where the codec runs for real.")
        if self.wire_spec is not None and stream is not None:
            raise ValueError(
                "--wire_codec currently simulates the encoded wire on "
                "the device-resident path only; streaming rounds "
                "(--streaming) keep the dense in-mesh aggregation — the "
                "real encoded transport lives in distributed/run.py")
        # in-process secure QUANTIZED aggregation (privacy/, ROADMAP
        # 1(b)): --secure_quant swaps the builder's sanitize/defend/
        # aggregate tail for the jitted GF(p) integer-weight fold
        # (program.secure_quant_aggregate) — bitwise the host
        # SlotAccumulator fold at the same (p, frac_bits, weights).
        # Every incompatibility fails HERE (startup), never mid-round.
        self.sq_spec = None
        self.sq_weight_shift = 0
        if cfg.fed.secure_quant:
            from neuroimagedisttraining_tpu.privacy import (
                QuantSpec, check_headroom,
            )
            from neuroimagedisttraining_tpu.privacy.secure_quant import (
                WEIGHT_FRAC_BITS, weighted_fold_capacity,
            )

            if not self.supports_secure_quant:
                from neuroimagedisttraining_tpu.engines import ENGINES
                ok = sorted({c.name for c in ENGINES.values()
                             if c.supports_secure_quant})
                raise ValueError(
                    f"algorithm {self.name!r} does not simulate "
                    "--secure_quant: its round has no default "
                    "server-side aggregation tail for the field fold to "
                    f"replace; supported: {ok}. The encoded secure wire "
                    "itself lives on the cross-silo/async planes "
                    "(distributed/run.py)")
            if self.wire_spec is not None:
                raise ValueError(
                    "--secure_quant does not compose with --wire_codec: "
                    "the codec's float stages would corrupt the GF(p) "
                    "residue embedding (field-element frames, not model "
                    "floats) — ARCHITECTURE.md 'Privacy plane'")
            if cfg.fed.defense_type in robust.ROBUST_AGGREGATORS:
                raise ValueError(
                    f"--defense {cfg.fed.defense_type} does not compose "
                    "with --secure_quant (no per-client plaintext to "
                    "select over); the clip family (norm_diff_clipping, "
                    "weak_dp) composes CLIENT-side pre-quantize — "
                    "ARCHITECTURE.md 'Privacy plane'")
            spec = QuantSpec.from_bits(cfg.fed.secure_quant_field_bits,
                                       cfg.fed.secure_quant_frac_bits)
            check_headroom(spec, cfg.fed.client_num_per_round)
            # the one-phase integer-weight fold (the async server's and
            # the sharded ingest plane's algebra): pick the largest
            # STATIC weight shift whose worst-case mass keeps the
            # aggregate inside the field's centered range — per-round
            # weights then fold exactly for the whole run
            cap = weighted_fold_capacity(spec)
            cohort = max(1, int(cfg.fed.client_num_per_round))
            shift = None
            for s in range(WEIGHT_FRAC_BITS, -1, -1):
                if cohort * (1 << s) < cap:
                    shift = s
                    break
            if shift is None:
                raise ValueError(
                    f"--secure_quant field too small for the in-process "
                    f"integer-weight fold: a {cohort}-client cohort "
                    f"exceeds the {cfg.fed.secure_quant_field_bits}-bit "
                    f"field's capacity of {cap:.1f} weight units — pass "
                    "--secure_quant_field_bits 32 (the same requirement "
                    "as the buffered one-phase path; ARCHITECTURE.md "
                    "'Privacy plane')")
            self.sq_spec = spec
            self.sq_weight_shift = int(shift)
            # materialize the static per-leaf scales NOW, outside any
            # trace: a lazy first touch would run the jitted model init
            # inside the round trace (tracer leaves -> leaf_scales'
            # host max() raises TracerArrayConversionError)
            _ = self.sq_scales
        self.stat_info: dict[str, Any] = {
            "sum_comm_params": 0.0, "sum_training_flops": 0.0,
            "sum_comm_bytes": 0.0, "sum_comm_bytes_dense": 0.0,
            "nonfinite_uploads": 0.0,
            "global_test_acc": [], "person_test_acc": [],
            "final_masks": [],
        }
        self._dense_upload_nbytes: int | None = None
        #: device-side non-finite-upload counts queued per round; synced
        #: in one batched device_get at host boundaries (_flush_nonfinite)
        self._nonfinite_pending: list = []
        #: in-dispatch training-health stats queued per dispatch (ISSUE
        #: 15): ``(k, stacked, {stat: device array})`` entries the
        #: builder's dispatch wrapper appends; drained in the SAME
        #: batched device_get as the non-finite counts — never a
        #: per-round sync
        self._health_pending: list = []
        #: monotonic sequence / round watermark of the metrics JSONL
        #: sink (ISSUE 15 satellite: every record carries a round +
        #: seq so run_report joins series without timestamp heuristics)
        self._metrics_seq = 0
        self._metrics_last_round: int | None = None
        # cohort sharding (--client_mesh, ISSUE 6): hard config errors
        # fail here; engines/modes whose rounds cannot shard announce the
        # unsharded fallback ONCE, up front (the fused-dispatch pattern)
        self._cohort_on = False
        cm = int(cfg.fed.client_mesh)
        if cm > 0:
            if mesh is None:
                raise ValueError(
                    f"--client_mesh {cm} requested but no device mesh was "
                    "constructed — build the engine with a mesh (the CLIs "
                    "do this automatically; tests: make_mesh())")
            if cm != mesh.devices.size:
                raise ValueError(
                    f"--client_mesh {cm} does not match the constructed "
                    f"{mesh.devices.size}-device mesh; pass a matching "
                    "--client_mesh / --mesh_shape / --virtual_devices "
                    "combination (the sampled-client axis shards over "
                    "EVERY mesh device)")
            key = self.program.cohort_fallback_key()
            if key is None:
                self._cohort_on = True
                self.log.info(
                    "client_mesh=%d: cohort sharding armed — the sampled-"
                    "client axis of every round program shards over the "
                    "%d-device mesh (pad rows zero-weighted, aggregation "
                    "on all-gathered stacks; parallel/cohort.py)",
                    cm, mesh.devices.size)
            else:
                # announced ONCE, up front, AND counted: the structured
                # nidt_fallback_total{plane,engine,reason} counter makes
                # fast-path coverage scrapeable (engines/program.py)
                self.log.info(
                    "client_mesh=%d requested; running the unsharded "
                    "round program: %s", cm,
                    round_program.report_fallback(self.name, key))
        # fused multi-round dispatch (ISSUE 4): engines that cannot fuse
        # announce the collapse to K=1 ONCE, up front, so a config asking
        # for amortized dispatch never silently degrades
        if cfg.fed.rounds_per_dispatch > 1:
            key = self.fused_fallback_key()
            if key is not None:
                self.log.info(
                    "rounds_per_dispatch=%d requested; dispatching one "
                    "round at a time: %s",
                    cfg.fed.rounds_per_dispatch,
                    round_program.report_fallback(self.name, key))

    # ---------- state init ----------

    def sample_input(self) -> jax.Array:
        if self.data is not None:
            shape = self.data.X_train.shape[2:]
        else:
            shape = self.stream.sample_shape
        return jnp.zeros((1,) + tuple(shape), jnp.float32)

    def init_global_state(self) -> ClientState:
        rng = jax.random.key(self.cfg.seed)
        return self.trainer.init_client_state(rng, self.sample_input())

    def broadcast_states(self, cs: ClientState, n: int) -> ClientState:
        """Replicate one state across a leading client axis of size n."""
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n,) + x.shape).copy()
            if hasattr(x, "shape") else x, cs)

    def per_client_rngs(self, round_idx: int, idx: np.ndarray) -> jax.Array:
        # +1 so the pre-training phase (round_idx=-1, SNIP scoring) folds a
        # valid uint32
        base = jax.random.fold_in(jax.random.key(self.cfg.seed + 17),
                                  round_idx + 1)
        return jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.asarray(idx, jnp.uint32))

    # ---------- sampling (reference parity) ----------

    def client_sampling(self, round_idx: int) -> np.ndarray:
        """np.random.seed(round_idx); choice without replacement
        (fedavg_api.py:92-100). Sampling is over REAL clients only; mesh
        padding clients never train."""
        total = self.real_clients
        per_round = min(self.cfg.fed.client_num_per_round, total)
        if total == per_round:
            sampled = np.arange(total)
        else:
            # nidt: allow[determinism-global-random] -- reference-parity
            # sampling shim: MUST replay the legacy global stream
            # (fedavg_api.py:92-100) to keep client cohorts bit-identical
            np.random.seed(round_idx)  # nidt: allow[determinism-global-random] -- reference-parity shim (fedavg_api.py:92-100)
            sampled = np.sort(np.random.choice(range(total), per_round,  # nidt: allow[determinism-global-random] -- reference-parity shim (fedavg_api.py:92-100)
                                               replace=False))
        if self.fault_schedule is not None:
            # crashed clients drop out of the cohort; the weighted
            # aggregation over the survivor set re-weights by sample
            # count exactly as a frac-sampled round would
            sampled = self.fault_schedule.survivors(round_idx, sampled)
        if self._quarantine_windows:
            # reflex quarantine (ISSUE 20): clients inside an active
            # window drop out of the cohort, same re-weighting as a
            # crash. If every sampled client is quarantined the filter
            # is skipped — an empty round has no reference semantics
            # (the survivors() rule).
            keep = np.asarray(
                [not self._is_quarantined(int(c), round_idx)
                 for c in np.asarray(sampled)], bool)
            if keep.any():
                sampled = np.asarray(sampled)[keep]
        self._stash_bounded(self._sampled_by_round, int(round_idx),
                            np.asarray(sampled))
        if len(sampled) == 0:
            # ADVICE r5: an empty cohort used to surface as a bare
            # IndexError from stream_sampling's ``sampled[-1]`` pad fill
            # (or as shape-0 gathers in the resident round) — fail with
            # the configuration that caused it instead
            raise ValueError(
                f"round {round_idx}: the sampled client set is empty — "
                f"client_num_per_round={per_round} and the fault "
                f"schedule ({self.cfg.fed.fault_spec!r}) left no "
                "survivors; raise --frac / --client_num_in_total or "
                "reduce the crash coverage in --fault_spec")
        return sampled

    def stream_sampling(self, round_idx: int,
                        sampled: np.ndarray | None = None
                        ) -> tuple[np.ndarray, int]:
        """``(padded_ids, n_real)`` for the streamed sharded feed: the
        round's sampled set padded to tile the mesh (the north-star config
        — 100 clients, frac 0.1 — samples 10 clients onto an 8-device
        grid). Pad entries prefer mesh-padding clients (rows
        [real_clients, num_clients), n_train == 0) and then repeat the
        last sampled id; either way the feed zeroes their fetched sample
        counts (``n_real``), so pads train as masked no-ops and weigh 0 in
        aggregation. Engines that scatter per-client state by sampled id
        must route through ``scatter_sampled_rows`` (pad entries dropped).
        Pass ``sampled`` when the round's set was already computed."""
        if sampled is None:
            sampled = self.client_sampling(round_idx)
        if len(sampled) == 0:
            raise ValueError(
                f"round {round_idx}: stream_sampling got an empty "
                "sampled set — no clients to pad the mesh tile from "
                "(see client_sampling: fault schedules can empty the "
                "cohort; this is a configuration error, not a crash)")
        if self.mesh is None:
            return sampled, len(sampled)
        return cohort.pad_cohort(sampled, self.real_clients,
                                 self.num_clients, self.mesh.devices.size)

    def scatter_sampled_rows(self, all_tree, new_tree, sampled_idx, real):
        """Write the sampled clients' new rows into the [C, ...] stacked
        state. Pad entries (``real`` False — stream_sampling's mesh-tiling
        pads, possibly DUPLICATE ids of a real client) are redirected to
        an out-of-range index and dropped (``mode="drop"``), so no pad
        write can land on — let alone clobber, via scatter's last-wins
        duplicate resolution — a real client's freshly trained row."""
        idx = jnp.where(real, sampled_idx, self.num_clients)
        return jax.tree.map(
            lambda allp, newp: allp.at[idx].set(newp, mode="drop"),
            all_tree, new_tree)

    # ---------- evaluation ----------

    @functools.cached_property
    def _eval_global_jit(self):
        trainer = self.trainer

        def eval_all(params, bstats, X, y, n):
            def per_client(Xc, yc, nc):
                valid = jnp.arange(Xc.shape[0]) < nc
                m = trainer.evaluate(params, bstats, Xc, yc, valid)
                auc = binary_auc(m["scores"], yc, valid)
                return m["test_correct"], m["test_loss"], m["test_total"], auc

            return jax.vmap(per_client)(X, y, n)

        return jax.jit(eval_all)

    @functools.cached_property
    def _eval_personal_jit(self):
        trainer = self.trainer

        def eval_all(params, bstats, X, y, n):
            def per_client(p, b, Xc, yc, nc):
                valid = jnp.arange(Xc.shape[0]) < nc
                m = trainer.evaluate(p, b, Xc, yc, valid)
                auc = binary_auc(m["scores"], yc, valid)
                return m["test_correct"], m["test_loss"], m["test_total"], auc

            return jax.vmap(per_client)(params, bstats, X, y, n)

        return jax.jit(eval_all)

    def _summarize(self, correct, loss, total, auc, n) -> dict[str, float]:
        """Average of per-client ratios over clients with data — parity with
        the reference's mean-over-clients metric (sailentgrads_api.py:266-285)."""
        correct, loss, total, auc, n = map(np.asarray,
                                           (correct, loss, total, auc, n))
        mask = n > 0
        if not np.any(mask):  # e.g. CI mode and client 0 has no test data
            return {"acc": 0.0, "loss": 0.0, "auc": 0.0, "acc_pooled": 0.0}
        accs = correct[mask] / np.maximum(total[mask], 1)
        losses = loss[mask] / np.maximum(total[mask], 1)
        return {
            "acc": float(np.mean(accs)),
            "loss": float(np.mean(losses)),
            "auc": float(np.mean(auc[mask])),
            "acc_pooled": float(correct[mask].sum() / max(total[mask].sum(), 1)),
        }

    def eval_global(self, params, bstats, split: str = "test") -> dict[str, float]:
        X = getattr(self.data, f"X_{split}")
        y = getattr(self.data, f"y_{split}")
        n = getattr(self.data, f"n_{split}")
        if self.cfg.fed.ci:  # CI escape hatch: client 0 only
            X, y, n = X[:1], y[:1], n[:1]
        # eval is a host boundary (the _summarize numpy reads block on
        # the device), so it is also a span: dispatch + sync wall time
        with obs_trace.span("eval_global", split=split):
            out = self._eval_global_jit(params, bstats, X, y, n)
            return self._summarize(*out,
                                   n=n if not self.cfg.fed.ci else n[:1])

    def eval_personalized(self, states: ClientState, split: str = "test"
                          ) -> dict[str, float]:
        X = getattr(self.data, f"X_{split}")
        y = getattr(self.data, f"y_{split}")
        n = getattr(self.data, f"n_{split}")
        params, bstats = states.params, states.batch_stats
        if self.cfg.fed.ci:  # CI escape hatch gates BOTH eval paths
            # (ref sailentgrads_api.py:260-265)
            X, y, n = X[:1], y[:1], n[:1]
            params = pt.tree_stack_index(params, slice(0, 1))
            bstats = pt.tree_stack_index(bstats, slice(0, 1))
        with obs_trace.span("eval_personalized", split=split):
            out = self._eval_personal_jit(params, bstats, X, y, n)
            return self._summarize(*out, n=n)

    # ---------- checkpoint / resume (SURVEY §5.4 rebuild requirement) ----------

    def _ckpt_active(self) -> bool:
        return bool(self.cfg.checkpoint_dir) and self.cfg.checkpoint_every > 0

    def maybe_checkpoint(self, round_idx: int, state: dict) -> None:
        """Save engine round state after ``round_idx`` completed, every
        ``checkpoint_every`` rounds (and always on the last round). All
        per-round randomness derives from the round index (per_client_rngs,
        client_sampling), so {state, round} is a complete resume point."""
        if not self._ckpt_active():
            return
        last = round_idx == self.cfg.fed.comm_round - 1
        if (round_idx + 1) % self.cfg.checkpoint_every == 0 or last:
            state = dict(state)
            state["stat_info"] = {
                k: v for k, v in self.stat_info.items()
                if isinstance(v, (int, float, list))}
            ckpt.save_checkpoint(self.cfg.checkpoint_dir, round_idx, state)
            self.log.info("checkpoint saved: round %d -> %s", round_idx,
                          self.cfg.checkpoint_dir)

    def restore_checkpoint(self) -> tuple[int, dict | None]:
        """Returns (start_round, state|None): the round to resume AT and the
        restored state of the last completed round."""
        if not self._ckpt_active():
            return 0, None
        loaded = ckpt.load_checkpoint(self.cfg.checkpoint_dir)
        if loaded is None:
            return 0, None
        round_idx, state = loaded
        self.stat_info.update(state.pop("stat_info", {}))
        # restored leaves arrive as host numpy; COPY them into
        # runtime-owned device buffers before they reach a round program.
        # The round programs donate their state arguments (ISSUE 4), and
        # handing numpy memory into a donated position is memory-unsafe:
        # the numpy->device conversion (device_put included) can borrow
        # the numpy buffer zero-copy on CPU, after which the donation
        # lets XLA write outputs into — and then free — memory that
        # numpy still owns (silently corrupt resumes, eventually heap
        # corruption; caught by tests/test_dispatch.py's resume pin).
        # ``jnp.array`` always copies from numpy, yielding an owned
        # buffer the donation may consume.
        state = {k: jax.tree.map(
            lambda x: jnp.array(x) if isinstance(x, np.ndarray) else x, v)
            for k, v in state.items()}
        self.log.info("resuming from checkpoint: round %d", round_idx + 1)
        return round_idx + 1, state

    # ---------- buffer donation (ISSUE 4) ----------

    #: Every round/consensus program donates the state pytrees it
    #: consumes (per-client stacks, broadcast params, EF accumulators),
    #: so XLA reuses their buffers for the matching outputs instead of
    #: double-buffering input and output state. The driver contract:
    #: NOTHING may read a donated argument after the dispatch (the
    #: runtime deletes the buffers; nidtlint's donation-discipline rules
    #: check the callers lexically). Tests/benches that replay the same
    #: buffers through one program twice set ``_donate = False`` BEFORE
    #: the program's first access (the jits are built lazily and read
    #: this flag at build time).
    _donate = True

    def _donate_argnums(self, *nums: int) -> tuple[int, ...]:
        """``donate_argnums`` for a round/consensus program; ``()`` when
        donation is disabled on this engine instance."""
        return tuple(nums) if self._donate else ()

    # ---------- the declared round program (ISSUE 11) ----------

    @functools.cached_property
    def sq_scales(self) -> dict:
        """Static per-leaf power-of-two scales for the in-process
        secure-quant stage, derived ONCE from the seed-deterministic
        init model (privacy.leaf_scales — BatchNorm raw-moment leaves
        would otherwise saturate the small field). Static for the run —
        the fused scan's carry changes per round, so per-round reference
        scales would force a host boundary; the fixed-scale contract is
        the async one-phase protocol's (frames fold unscaled against a
        startup bound there; scaled against the init here)."""
        from neuroimagedisttraining_tpu.privacy import leaf_scales

        gs = self.init_global_state()
        ref = {"params": jax.tree.map(np.asarray, gs.params),
               "batch_stats": jax.tree.map(np.asarray, gs.batch_stats)}
        return leaf_scales(ref)

    @functools.cached_property
    def program(self) -> "round_program.RoundProgram":
        """The engine's compiled round-program builder
        (engines/program.py): every fused/sharded/donated dispatch
        variant, window planning, and fallback reporting. Built from the
        engine's :meth:`round_stages` declaration (None for engines that
        keep hand-driven per-round loops — they still get the unified
        fallback reporting)."""
        return round_program.RoundProgram(self, self.round_stages())

    def round_stages(self):
        """The engine's declared round stages
        (:class:`engines.program.RoundStages`), or None when the engine
        has no declarable round body (host-side state between rounds).
        Declaring stages is what puts an engine on the fused/sharded/
        donated fast path — the builder owns the machinery."""
        return None

    # ---------- fused multi-round dispatch (ISSUE 4) ----------

    def fused_fallback_key(self) -> str | None:
        """REASONS key for why this engine dispatches one round at a
        time even when ``--rounds_per_dispatch K`` asks for fused
        windows — or None when the declared stages support the K-round
        ``lax.scan`` driver. Engines with genuinely host-driven rounds
        override with their table key (engines/program.py REASONS is the
        single source of truth; ad-hoc reason strings are a lint
        finding)."""
        return self.program.fused_fallback_key()

    def fused_fallback_reason(self) -> str | None:
        """The logged message for :meth:`fused_fallback_key` (None when
        the fused driver arms) — kept for drivers and tests that match
        on the message text."""
        key = self.fused_fallback_key()
        return None if key is None else round_program.reason(key)

    def _dispatch_window(self, round_idx: int) -> int:
        """Window length starting at ``round_idx`` (delegates to the
        program's planner — hooks land on window boundaries)."""
        return self.program.dispatch_window(round_idx)

    # ---------- cohort sharding (--client_mesh, ISSUE 6) ----------

    def cohort_fallback_key(self) -> str | None:
        """REASONS key for why this engine runs the unsharded round even
        when ``--client_mesh`` asks for the cohort-sharded client mesh.
        The base answer covers every engine without declared stages (or
        whose stages cannot shard); engines with a structurally
        different sharding story (dispfl/turbo) override with their
        table key. Mode checks (mesh shape, streaming, batch order) live
        in the program builder."""
        return "no-sharded-body"

    def cohort_fallback_reason(self) -> str | None:
        """The logged message for the program's cohort fallback key
        (None when the sharded path arms)."""
        key = self.program.cohort_fallback_key()
        return None if key is None else round_program.reason(key)

    def _cohort_pad(self, sampled: np.ndarray) -> tuple[np.ndarray, int]:
        """``(padded_ids, n_real)`` for a cohort-sharded resident round:
        the sampled set padded to tile the client mesh (the shared
        ``pad_cohort`` rule — zero-sample pool first, then repeat)."""
        return cohort.pad_cohort(np.asarray(sampled), self.real_clients,
                                 self.num_clients, self.mesh.devices.size)

    def _cohort_round_prog(self, sampled: np.ndarray):
        """``(gather_ids, round_prog)`` for one resident round: the
        mesh-padded id set + the sharded round program when cohort
        sharding is armed; the sampled set + the unsharded
        ``_round_jit`` otherwise (shared by the fedavg-family and
        salientgrads drivers)."""
        if self._cohort_on:
            ids, n_real = self._cohort_pad(sampled)
            return ids, self._sharded_round_jit(n_real)
        return sampled, self._round_jit

    #: when True, the sharded round programs lower their local-training
    #: stage to the SEQUENTIAL C-loop on one device instead of the
    #: mesh-sharded loops — the bitwise reference tests/test_cohort.py
    #: and the bench's slope baseline pin the sharded path against (set
    #: BEFORE the first program access; the jits read it at build time)
    _cohort_sequential = False

    def _cohort_map(self, fn, *stacked):
        """The round body's local-training stage on the sharded path:
        the unbatched per-client loop, shard_mapped over the client mesh
        and all-gathered back to replicated full stacks — or the same
        loop on one device when ``_cohort_sequential`` asks for the
        sequential reference (~1-ulp-equal with bitwise first-round
        losses — the full contract in parallel/cohort.py)."""
        if self._cohort_sequential:
            return cohort.sequential_map(fn, *stacked)
        return cohort.cohort_map(self.mesh, fn, *stacked)

    # ---------- Byzantine value faults (faults/adversary.py, ISSUE 5) ----------

    def _byz_on(self) -> bool:
        """True iff the fault schedule can corrupt upload VALUES — the
        round programs then route client uploads through the adversary
        transform (an all-honest round rides an identity plan, which
        ``apply_attack`` passes through bitwise)."""
        return (self.fault_schedule is not None
                and self.fault_schedule.spec.any_value_faults)

    def _byz_round_plan(self, round_idx: int, sampled: np.ndarray):
        """One round's attack plan over the sampled cohort (engine
        client index c == cross-silo rank c + 1, the faults/ contract):
        ``(mult[C], std[C], nonfinite[C], keys[C])`` device arrays, or
        None when the schedule has no value faults at all."""
        if not self._byz_on():
            return None
        ranks = np.asarray(sampled) + 1
        mult, std, nan = adversary.plan_arrays(self.fault_schedule,
                                               round_idx, ranks)
        byzantine = np.flatnonzero((mult != 1.0) | (std != 0.0) | nan)
        if byzantine.size:
            self.log.info(
                "round %d: clients %s upload BYZANTINE values (%s)",
                round_idx, np.asarray(sampled)[byzantine].tolist(),
                [self.fault_schedule.byzantine_kind(round_idx,
                                                    int(r))
                 for r in ranks[byzantine]])
        keys = adversary.attack_keys(self.cfg.seed, round_idx, ranks)
        return (jnp.asarray(mult), jnp.asarray(std), jnp.asarray(nan),
                keys)

    # NOTE: the shared sanitize -> defend -> aggregate round tail lives
    # in engines/program.py (``sanitize_defend_aggregate``) — it is a
    # builder-owned stage, applied to every engine whose declared round
    # has no custom aggregate stage (ISSUE 11).

    # ---------- privacy accounting (privacy/, ISSUE 8) ----------

    def record_privacy(self, round_idx: int) -> None:
        """Charge the RDP ledger for every round completed through
        ``round_idx`` and publish the running (epsilon, delta) in
        ``stat_info`` — one entry PER ROUND (the weak_dp observability
        the defense never had: the clip bound and sigma it actually
        applied were invisible). Pure host numpy, called from
        ``_flush_nonfinite``'s host boundaries (and the dpsgd driver),
        never inside a trace.

        Two armed sources, mutually exclusive by construction (weak_dp
        is a server-side defense, dp_clip/dp_sigma a client-side
        transform dpsgd owns):

        - ``defense_type == "weak_dp"``: per round, a subsampled
          Gaussian at q = cohort/total with the effective multiplier
          over the round's ACTUAL sample-count weights
          (``weak_dp_noise_multiplier``) — cohorts re-derived from the
          deterministic sampling contract, so accounting replays
          exactly.
        - ``dp_sigma > 0`` (dpsgd): full participation (q = 1, every
          silo reveals its noised model to neighbors every round) at
          noise multiplier ``dp_sigma``.
        """
        from neuroimagedisttraining_tpu.privacy import accountant as acct

        f = self.cfg.fed
        weak = f.defense_type == "weak_dp"
        dp = f.dp_sigma > 0
        if not (weak or dp) or round_idx <= self._dp_recorded_through:
            return
        if weak and (f.stddev <= 0 or f.norm_bound <= 0):
            # degenerate-but-runnable ablation (no noise / no clip
            # sensitivity): warn once, never die at an eval boundary —
            # the same guard cross_silo._note_weak_dp keeps
            if not getattr(self, "_warned_dp_disabled", False):
                self._warned_dp_disabled = True
                self.log.warning(
                    "weak_dp with stddev=%s/norm_bound=%s adds no "
                    "accountable noise — epsilon is infinite; the "
                    "accountant records nothing", f.stddev, f.norm_bound)
            return
        key = "weak_dp" if weak else "dp"
        stats = self.stat_info.setdefault(key, {
            "norm_bound": f.norm_bound if weak else f.dp_clip,
            "stddev": f.stddev if weak else f.dp_sigma * f.dp_clip,
            "delta": f.dp_delta, "noise_multiplier_per_round": [],
            "epsilon_per_round": [], "epsilon": 0.0})
        if self._dp_rdp is None:
            self._dp_rdp = np.zeros(len(acct.DEFAULT_ORDERS), np.float64)
        for r in range(self._dp_recorded_through + 1, round_idx + 1):
            if weak:
                sampled = self.client_sampling(r)
                w = self._n_train_host[np.asarray(sampled)]
                q = len(sampled) / max(1, self.real_clients)
                z = acct.weak_dp_noise_multiplier(f.stddev, f.norm_bound,
                                                  w)
            else:
                q, z = 1.0, f.dp_sigma
            self._dp_rdp = self._dp_rdp + acct.rdp_gaussian(q, z)
            eps = acct.rdp_to_epsilon(self._dp_rdp,
                                      delta=f.dp_delta)[0]
            stats["noise_multiplier_per_round"].append(round(z, 6))
            stats["epsilon_per_round"].append(round(eps, 4))
        stats["epsilon"] = stats["epsilon_per_round"][-1]
        # per-silo report: under the sampling model every silo's loss is
        # identical (the subsampling is the amplifier), so the per-silo
        # map is uniform — the cross-silo server's ledger (which sees
        # deterministic survivor sets, no amplification) is the
        # per-silo-varying counterpart (cross_silo.dp_report)
        stats["epsilon_per_silo"] = {
            int(c): stats["epsilon"] for c in range(self.real_clients)}
        self._dp_recorded_through = round_idx

    # ---------- non-finite upload guard (ISSUE 5 satellite) ----------

    def _note_nonfinite(self, n_bad) -> None:
        """Queue a round's device-side count of rejected non-finite
        client uploads. Deliberately NOT synced here: a per-round
        ``device_get`` would serialize every dispatch; the queue drains
        in one batched transfer at the next host boundary."""
        self._nonfinite_pending.append(n_bad)

    # ---------- training-health plane (obs/health.py, ISSUE 15) ----------

    def _note_health(self, stats: dict, k: int = 1,
                     stacked: bool = False) -> None:
        """Queue one dispatch's health-stats pytree (device arrays —
        the builder's dispatch wrapper calls this, never a driver).
        ``k`` rounds per dispatch; ``stacked`` marks scan-fused values
        with a leading [K] round axis. Drained at ``_flush_nonfinite``
        in the same batched device_get as the non-finite counts."""
        self._health_pending.append((int(k), bool(stacked), stats))

    def _drain_health(self, entries: list, host_vals: list,
                      round_idx: int) -> None:
        """Publish the drained health stats round by round. Dispatches
        between two host boundaries cover CONTIGUOUS rounds ending at
        the flush round (the drivers' loop invariant), so the round
        index of every entry is reconstructed backward from
        ``round_idx`` — no per-dispatch round plumbing through the
        legacy adapters. Each published round also lands one metrics
        JSONL record and one rule-engine boundary evaluation."""
        total = sum(k for k, _, _ in entries)
        r = round_idx - total + 1
        for (k, stacked, _), host in zip(entries, host_vals):
            for i in range(k):
                if stacked:
                    row = {n: np.asarray(v)[i] for n, v in host.items()}
                else:
                    row = host
                obs_health.publish_round_stats(self.name, r, row)
                # stash the host row BEFORE the boundary evaluation:
                # a divergence alert fired at this round must be able
                # to attribute the offender from its h_cos vector
                # (the reflex quarantine handler, ISSUE 20)
                self._stash_bounded(self._last_health_rows, int(r),
                                    dict(row))
                if r < round_idx:
                    # the flush round itself dumps/evaluates in
                    # publish_stat_info, AFTER the stat/DP gauges of
                    # this boundary are set
                    self._dump_metrics_jsonl(r)
                    obs_rules.observe_boundary(r)
                r += 1

    def _dump_metrics_jsonl(self, round_idx: int) -> None:
        """One metrics JSONL record per round (``--metrics_out``), each
        carrying the monotonic ``round`` + ``seq`` join keys
        (run_report joins series on them, never on timestamps).
        Re-flushing an already-recorded round is a no-op — boundaries
        and end-of-run paths may land on the same round."""
        path = getattr(self.cfg, "metrics_out", "")
        if not path:
            return
        if self._metrics_last_round is not None \
                and round_idx <= self._metrics_last_round:
            return
        self._metrics_seq += 1
        self._metrics_last_round = int(round_idx)
        obs_metrics.REGISTRY.dump_jsonl(
            path, round=int(round_idx), seq=self._metrics_seq,
            engine=self.name)

    def _flush_nonfinite(self, round_idx: int) -> None:
        """Drain the queued counts (one batched device_get) and emit the
        counted warning when any upload was rejected. Call at host-sync
        boundaries — eval rounds and end of training — where the driver
        already blocks on device results.

        Doubles as the privacy-ledger boundary: every driver that can
        arm weak_dp already calls this at exactly the host-sync points
        where per-round accounting should publish, so the accountant
        records here instead of asking each engine for a second hook —
        and as the OBS boundary (ISSUE 9): the stat_info accumulators
        publish into the metrics registry here, where the driver already
        blocks on device results, never from inside a dispatch. The
        training-health stats the round programs queued (ISSUE 15) ride
        the SAME batched device_get — armed health adds zero sync
        points to a run."""
        self.record_privacy(round_idx)
        if self._nonfinite_pending or self._health_pending:
            health_entries = self._health_pending
            self._health_pending = []
            with obs_trace.span("flush_nonfinite", round=round_idx):
                counts, health_vals = jax.device_get(
                    (self._nonfinite_pending,
                     [e[2] for e in health_entries]))
            self._nonfinite_pending.clear()
            total = int(sum(np.sum(np.asarray(c)) for c in counts))
            if total:
                self.stat_info["nonfinite_uploads"] += total
                self.log.warning(
                    "rounds <= %d: rejected %d non-finite (NaN/Inf) "
                    "client upload(s) before aggregation — the "
                    "offending clients were zero-weighted for their "
                    "rounds (%d rejected so far this run)", round_idx,
                    total, int(self.stat_info["nonfinite_uploads"]))
            if health_entries:
                self._drain_health(health_entries, health_vals,
                                   round_idx)
        self.publish_stat_info(round_idx)

    def publish_stat_info(self, round_idx: int) -> None:
        """Publish the scalar ``stat_info`` accumulators (and the armed
        privacy ledger's running epsilon) into the obs metrics registry
        — gauge semantics, value == the legacy dict entry by
        construction (the no-double-counting pin in tests/test_obs.py).
        Host-boundary only: the callers are ``_flush_nonfinite`` and
        run-end paths, both already synced."""
        g = obs_metrics.gauge(
            obs_names.STAT, "engine stat_info accumulators "
            "(engines/base.py), one series per key",
            labelnames=("key",))
        for k, v in self.stat_info.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                g.labels(key=k).set(float(v))
        for src in ("weak_dp", "dp"):
            d = self.stat_info.get(src)
            if isinstance(d, dict) and d.get("epsilon_per_round"):
                obs_metrics.gauge(
                    obs_names.DP_EPSILON,
                    "running (epsilon, delta) privacy cost of the armed "
                    "noise path (privacy/accountant.py)",
                    labelnames=("source",)).labels(source=src).set(
                    float(d["epsilon"]))
                # epsilon burn RATE (ISSUE 15 satellite): what the last
                # accounted round cost — the built-in dp-burn-rate rule
                # and the run report's epsilon ledger read this next to
                # the running total
                per = d["epsilon_per_round"]
                burn = (per[-1] - per[-2]) if len(per) > 1 else per[-1]
                obs_metrics.gauge(
                    obs_names.DP_EPSILON_PER_ROUND,
                    "epsilon spent by the last accounted round (the "
                    "budget burn rate --dp_epsilon_budget is judged "
                    "against)",
                    labelnames=("source",)).labels(source=src).set(
                    float(burn))
        obs_metrics.gauge(
            obs_names.ENGINE_ROUND,
            "last round index flushed at an engine host boundary",
        ).set(int(round_idx))
        # compute-plane boundary (ISSUE 14): this is a host point where
        # the driver ALREADY blocked on device results, so the profiler
        # can close its MFU window (flops dispatched since the last
        # boundary / synced wall) without adding any sync
        obs_compute.PROFILER.boundary(self.name)
        # training-health boundary (ISSUE 15): one metrics JSONL record
        # + one rule-engine evaluation per boundary round — both no-ops
        # when the drained health stats already covered this round (or
        # when the sink / rule engine is unarmed)
        self._dump_metrics_jsonl(round_idx)
        obs_rules.observe_boundary(round_idx)

    # ---------- reflex plane (obs/actions.py, ISSUE 20) ----------

    #: bound on the per-round host stashes the reflex handlers read
    #: (sampled cohorts, drained health rows): old rounds evict oldest-
    #: first — a handler only ever looks a few boundaries back
    _REFLEX_STASH_CAP = 64

    #: rounds an engine-side reflex quarantine lasts. The cross-silo
    #: control plane has an operator knob (--quarantine_rounds); the
    #: in-process reflex uses one fixed conservative window — the alert
    #: that fired it re-fires if the divergence survives the window
    _REFLEX_QUARANTINE_ROUNDS = 5

    #: the escalation ladder (ISSUE 20): each rung strictly stronger.
    #: Deliberately short — weak_dp and the order statistics beyond
    #: trimmed_mean change the privacy/accuracy contract in ways a
    #: reflex must not decide on its own
    _DEFENSE_LADDER = ("none", "norm_diff_clipping", "trimmed_mean")

    @staticmethod
    def _stash_bounded(d: dict, key: int, value) -> None:
        d[key] = value
        while len(d) > FederatedEngine._REFLEX_STASH_CAP:
            d.pop(min(d))

    def _is_quarantined(self, client: int, round_idx: int) -> bool:
        return any(a <= round_idx < b
                   for a, b in self._quarantine_windows.get(client, ()))

    def active_defense(self) -> str:
        """The defense the round programs realize RIGHT NOW: the config
        literal unless the reflex plane escalated it. The builder's
        sanitize/defend/aggregate tail reads this at TRACE time
        (engines/program.py), so escalation invalidates the compiled
        programs and the next dispatch re-traces through here."""
        return self._defense_override or self.cfg.fed.defense_type

    def _invalidate_round_programs(self) -> None:
        """Drop every compiled round program and plan cache so the next
        dispatch re-traces/re-plans against the CURRENT engine state
        (escalated defense, shrunken mesh). The caches are lazy
        cached-properties / plan dicts in ``__dict__`` — popping them
        is the whole invalidation."""
        for name in ("program", "_round_jit", "_round_stream_jit",
                     "_round_prog_cache", "_fused_round_jit_cache"):
            self.__dict__.pop(name, None)

    def _register_reflexes(self) -> None:
        """Register this engine's realizations of the reflex actions on
        the armed action bus — a no-op when none is armed (tests and
        library callers run engines without the CLI). Called at
        ``train()`` start; registration is latest-wins, so repeated
        trains re-arm cleanly."""
        bus = obs_actions.active()
        if bus is None:
            return
        bus.register("quarantine_silo", self._act_quarantine)
        bus.register("escalate_defense", self._act_escalate_defense)
        bus.register("freeze_rollback", self._act_freeze_rollback)

    def _act_quarantine(self, *, rule: str, round_idx: int | None,
                        value=None) -> dict:
        """quarantine_silo: attribute the divergence alert to the
        sampled client with the most negative leave-one-out cosine
        (the stashed ``h_cos`` row of the firing round) and open a
        quarantine window starting NEXT round. Concurrency is capped at
        the configured Byzantine budget — the same breakdown-point
        honesty the cross-silo strike machinery keeps."""
        r = -1 if round_idx is None else int(round_idx)
        sampled = self._sampled_by_round.get(r)
        row = self._last_health_rows.get(r)
        cos = None if row is None else row.get("h_cos")
        if sampled is None or cos is None:
            return {"status": "skipped",
                    "reason": "no per-client cosine row for the round "
                              "(--health_stats off, or pre-health "
                              "boundary)"}
        cos = np.ravel(np.asarray(cos))
        n = min(len(sampled), cos.size)
        if n == 0:
            return {"status": "skipped", "reason": "empty cohort"}
        offender = int(np.asarray(sampled)[int(np.argmin(cos[:n]))])
        if self._is_quarantined(offender, r + 1):
            return {"status": "skipped", "client": offender,
                    "reason": "offender already quarantined"}
        cap = max(1, int(self.cfg.fed.byz_f))
        active_now = sum(1 for c in self._quarantine_windows
                        if self._is_quarantined(c, r + 1))
        if active_now >= cap:
            return {"status": "skipped",
                    "reason": f"quarantine cap {cap} (byz_f) reached"}
        until = r + 1 + self._REFLEX_QUARANTINE_ROUNDS
        self._quarantine_windows.setdefault(offender, []).append(
            (r + 1, until))
        self.log.warning(
            "reflex: client %d quarantined rounds [%d, %d) (rule %s, "
            "min leave-one-out cosine %.3f)", offender, r + 1, until,
            rule, float(cos[:n].min()))
        return {"client": offender, "from_round": r + 1, "until": until,
                "cos": float(cos[:n].min())}

    def _act_escalate_defense(self, *, rule: str,
                              round_idx: int | None,
                              value=None) -> dict:
        """escalate_defense: step the ladder one rung and re-plan the
        round programs. Anything infeasible — an operator-chosen
        defense outside the ladder, an engine without the rung, a
        cohort below the rung's breakdown point, secure_quant's
        no-plaintext tail — is a SKIPPED dispatch with the reason in
        the action log, never an exception."""
        cur = self.active_defense()
        ladder = self._DEFENSE_LADDER
        if self.cfg.fed.secure_quant:
            return {"status": "skipped",
                    "reason": "secure_quant rounds have no plaintext "
                              "defend tail to escalate"}
        if cur not in ladder:
            return {"status": "skipped",
                    "reason": f"operator defense {cur!r} is outside "
                              "the escalation ladder"}
        if cur == ladder[-1]:
            return {"status": "skipped",
                    "reason": f"already at the top rung {cur!r}"}
        nxt = ladder[ladder.index(cur) + 1]
        if nxt not in self.supported_defenses:
            return {"status": "skipped",
                    "reason": f"engine {self.name!r} does not support "
                              f"{nxt!r}"}
        if nxt in robust.ROBUST_AGGREGATORS:
            try:
                robust._check_f(self.cfg.fed.client_num_per_round,
                                self.cfg.fed.byz_f, nxt)
            except ValueError as e:
                return {"status": "skipped", "reason": str(e)}
        self._defense_override = nxt
        self._invalidate_round_programs()
        self.log.warning(
            "reflex: defense escalated %s -> %s (rule %s); round "
            "programs invalidated for re-trace", cur, nxt, rule)
        return {"from": cur, "to": nxt}

    def _act_freeze_rollback(self, *, rule: str,
                             round_idx: int | None,
                             value=None) -> dict:
        """freeze_rollback: schedule a restore of the last healthy
        pinned state; the driver consumes it at the NEXT host boundary
        (``_reflex_boundary``) — never mid-dispatch, so the donation
        contract is untouched."""
        if self._healthy_pin is None:
            return {"status": "skipped",
                    "reason": "no healthy pinned state yet"}
        self._pending_rollback = {
            "rule": rule,
            "round": -1 if round_idx is None else int(round_idx)}
        return {"pin_round": int(self._healthy_pin["round"])}

    def _reflex_boundary(self, round_idx: int, params, bstats):
        """The drivers' per-boundary reflex hook, called right after
        ``_flush_nonfinite`` (whose rule evaluation may have scheduled
        a rollback): consume a pending freeze-and-rollback, else pin
        the current state as 'last healthy' while the rule engine
        reads ok. Pin and restore both take fresh ``jnp.array`` copies
        — the round programs donate their state arguments, so the pin
        must own buffers no dispatch can consume, and the restored
        arrays must be consumable without killing the pin."""
        pend = self._pending_rollback
        if pend is not None:
            self._pending_rollback = None
            pin = self._healthy_pin
            if pin is not None:
                params = jax.tree.map(jnp.array, pin["params"])
                bstats = jax.tree.map(jnp.array, pin["batch_stats"])
                if getattr(self, "_wire_ef", None) is not None:
                    # codec-EF reset invariant (ARCHITECTURE.md "Reflex
                    # plane"): the accumulated error was measured
                    # against states the rollback just discarded —
                    # replaying it would re-inject the divergence the
                    # rollback removed
                    self._wire_ef = jax.tree.map(jnp.zeros_like,
                                                 self._wire_ef)
                obs_flight.record("rollback", rule=pend.get("rule"),
                                  round=int(round_idx),
                                  pin_round=int(pin["round"]))
                self.log.warning(
                    "reflex: rolled back to the healthy state of round "
                    "%d at boundary %d (rule %s); codec EF reset",
                    pin["round"], round_idx, pend.get("rule"))
            return params, bstats
        bus = obs_actions.active()
        if bus is not None and bus.mode == "on":
            rules_eng = obs_rules.active()
            if rules_eng is None or rules_eng.status() == "ok":
                self._healthy_pin = {
                    "round": int(round_idx),
                    "params": jax.tree.map(jnp.array, params),
                    "batch_stats": jax.tree.map(jnp.array, bstats)}
        return params, bstats

    @staticmethod
    def _regather_live(tree):
        """Host-gather a live pytree off the pre-preemption devices and
        re-place it as fresh uncommitted buffers. The no-checkpoint
        resume path keeps training on the live state — but that state
        is committed to the OLD mesh's devices, and the re-planned
        programs shard over the survivors only."""
        return jax.tree.map(lambda x: jnp.array(np.asarray(x)), tree)

    def _maybe_preempt(self, round_idx: int):
        """Elastic compute plane (ISSUE 20): consume any scheduled
        ``preempt:NDEV@ROUND`` whose round has arrived (``<=`` — fused
        windows skip indices), shrink the training mesh to the NDEV
        survivors, re-plan every compiled program, and return
        ``(resume_round, restored_state | None)`` from the last
        donation-safe checkpoint. Returns None when nothing fired.
        Deliberately NOT gated by ``--actions``: an explicitly injected
        device loss is an event, not a reflex policy — the armed bus
        records it with the device-loss event as provenance either
        way."""
        if self.fault_schedule is None:
            return None
        hits = [(at, nd) for (at, nd)
                in self.fault_schedule.spec.preempts
                if at <= round_idx and at not in self._preempts_done]
        if not hits:
            return None
        at, ndev = hits[0]
        self._preempts_done.add(at)
        old = self.mesh.devices.size if self.mesh is not None else 0
        if self.mesh is None or not 0 < ndev < old:
            obs_actions.record_action(
                "shrink_mesh", rule="device-loss",
                round_idx=round_idx, status="skipped",
                detail={"reason": ("no mesh to shrink"
                                   if self.mesh is None else
                                   f"{ndev} survivors do not shrink "
                                   f"the {old}-device mesh"),
                        "scheduled_round": int(at)})
            return None
        self.mesh = make_mesh(num_devices=ndev)
        if int(self.cfg.fed.client_mesh) > 0:
            # keep the client_mesh == mesh-size startup invariant so
            # the re-planned programs shard over exactly the survivors
            self.cfg = dataclasses.replace(
                self.cfg, fed=dataclasses.replace(self.cfg.fed,
                                                  client_mesh=ndev))
        self._invalidate_round_programs()
        if self.data is not None:
            # the federation was device_put with the OLD mesh's client
            # sharding at federate time (data/federate.py); arrays still
            # committed to evicted devices would poison every re-planned
            # dispatch ("incompatible devices"). Host-gather and re-place
            # over the survivors — client-sharded while the padded client
            # count still divides them, replicated otherwise (the round
            # programs re-shard internally either way).
            sh = (client_sharding(self.mesh)
                  if self.data.num_clients % ndev == 0
                  else replicated_sharding(self.mesh))
            self.data = jax.tree.map(
                lambda x: jax.device_put(np.asarray(x), sh), self.data)
        if self._cohort_on:
            # the shrunken mesh may or may not still shard (mode checks
            # re-run against the new plan)
            self._cohort_on = self.program.cohort_fallback_key() is None
        start, restored = self.restore_checkpoint()
        if restored is not None and getattr(self, "_wire_ef", None) is not None:
            # match a fresh-process resume exactly: EF accumulators are
            # not checkpointed, so a from-checkpoint replay starts them
            # at zero — the elastic resume must too, or the pinned
            # replay parity breaks
            self._wire_ef = jax.tree.map(jnp.zeros_like, self._wire_ef)
        self.log.warning(
            "preemption at round %d (scheduled @%d): mesh shrunk "
            "%d -> %d devices; resuming from %s", round_idx, at, old,
            ndev, (f"checkpoint round {start}" if restored is not None
                   else "live state (no checkpoint configured)"))
        obs_actions.record_action(
            "shrink_mesh", rule="device-loss", round_idx=round_idx,
            detail={"devices_before": int(old),
                    "devices_after": int(ndev),
                    "scheduled_round": int(at),
                    "resume_round": (int(start) if restored is not None
                                     else int(round_idx))})
        return start, restored

    # ---------- compute-plane profiler (obs/compute.py, ISSUE 14) ----------

    #: lazily armed on the first dispatch (engines/program.py wrapper):
    #: one abstract eval_shape derives the analytic FLOPs-per-round the
    #: MFU gauges divide by — no device work, no params materialized
    _compute_armed = False

    def _arm_compute_profiler(self) -> None:
        """Arm the dispatch-boundary profiler's MFU accounting for this
        engine: analytic training FLOPs of one NOMINAL round (per-sample
        FLOPs x expected sampled sample mass x local epochs). The cohort
        estimate is the sampling contract's expectation — exact under
        full participation / equal-sized synthetic clients (the bench
        and profile-session configs), an estimate under frac sampling
        or fault schedules (MFU is a utilization gauge, not a parity
        pin; obs/compute.py documents the contract). Models the
        analytic counter cannot walk (no captured conv intermediates)
        disarm with a logged reason instead of failing a dispatch."""
        if self._compute_armed:
            return
        self._compute_armed = True
        try:
            if self.data is not None:
                shape = tuple(self.data.X_train.shape[2:])
            else:
                shape = tuple(self.stream.sample_shape)
            per_sample = obs_compute.analytic_sample_flops(self.trainer,
                                                           shape)
            total_n = float(np.sum(self._n_train_host))
            cohort_frac = (min(self.cfg.fed.client_num_per_round,
                               self.real_clients)
                           / max(1, self.real_clients))
            flops_per_round = (per_sample * total_n * cohort_frac
                               * max(1, self.cfg.optim.epochs))
            obs_compute.arm_model(self.name, flops_per_round)
        except Exception as e:  # noqa: BLE001 — MFU is best-effort
            # telemetry; an uncountable model must never fail a dispatch
            self.log.info(
                "compute profiler: analytic FLOPs unavailable for this "
                "model (%s) — nidt_mfu/nidt_sustained_tflops stay "
                "unpublished; dispatch/compile accounting is unaffected",
                e)

    # ---------- helpers ----------

    #: cap on per-instance plan-keyed jit caches: a topology whose
    #: circulant weights vary per round must not accumulate one compiled
    #: executable per distinct plan for the engine's lifetime
    _JIT_CACHE_CAP = 4

    def _plan_cached(self, cache_name: str, key, build):
        """Per-instance plan-keyed cache with LRU eviction past
        ``_JIT_CACHE_CAP`` (a class-level lru_cache would store ``self``
        and pin discarded engines' device-resident data)."""
        cache = self.__dict__.setdefault(cache_name, {})
        if key in cache:
            cache[key] = cache.pop(key)  # refresh recency (true LRU)
            return cache[key]
        if len(cache) >= self._JIT_CACHE_CAP:
            cache.pop(next(iter(cache)))
        cache[key] = build()
        return cache[key]

    def _max_samples(self) -> int:
        """Static per-client sample-axis pad (same in streamed and
        resident layouts, so round programs compile once)."""
        return (self.stream.nmax_train if self.stream is not None
                else int(self.data.X_train.shape[1]))

    def _eval_g(self, params, bstats) -> dict[str, float]:
        """Global-model eval, dispatched on the data residency mode."""
        if self.stream is not None:
            return self.eval_global_stream(params, bstats)
        return self.eval_global(params, bstats)

    def _eval_p(self, per_params, per_bstats) -> dict[str, float]:
        """Personalized eval over stacked per-client state, dispatched on
        the data residency mode."""
        if self.stream is not None:
            return self.eval_personalized_stream(per_params, per_bstats)
        return self.eval_personalized(ClientState(
            params=per_params, batch_stats=per_bstats, opt_state=None,
            rng=None))

    def round_lr(self, round_idx: int):
        return round_lr(self.cfg.optim, round_idx)

    def weights_for(self, sampled: np.ndarray) -> jax.Array:
        """FedAvg weights = per-client sample counts of the sampled set
        (fedavg_api.py:102-117)."""
        n = jnp.asarray(self._n_train_host[np.asarray(sampled)])
        return n.astype(jnp.float32)

    # ---------- wire codec (codec/, ISSUE 3) ----------

    def wire_masks(self):
        """Mask handoff: the pruning/saliency mask this engine would hand
        the wire codec so uploads pack mask-sparse — a params-congruent
        pytree (or a client-stacked one for per-client masks), or None
        for dense engines (the codec's top-k stage applies instead).
        Base engines own no mask."""
        return None

    def account_wire_bytes(self, upload_host, reference_host,
                           masks_host=None, n_uploads: int = 1) -> int:
        """Accumulate the round's uplink byte accounting from ONE
        representative encoded upload (uploads share sizes up to zlib
        noise): ``sum_comm_bytes`` gets the encoded frame size x
        ``n_uploads``, ``sum_comm_bytes_dense`` the dense msgpack size
        the legacy wire would have shipped. Host-side numpy — call it
        OUTSIDE jit with device_get'd trees. Re-encoding every round
        (rather than caching one frame size) is deliberate: zlib output
        varies with the round's residual entropy, and the measured host
        cost (~150 ms for the 2.6 M-param flagship) is < 1 % of its
        round wall time. Returns the frame size."""
        frame, _ = codec_wire.encode_update(
            self.wire_spec, upload_host, reference=reference_host,
            masks=masks_host, mask_on_wire=False)
        nbytes = codec_wire.frame_nbytes(frame)
        if self._dense_upload_nbytes is None:
            self._dense_upload_nbytes = codec_wire.frame_nbytes(
                jax.tree.map(np.asarray, upload_host))
        self.stat_info["sum_comm_bytes"] += float(nbytes * n_uploads)
        self.stat_info["sum_comm_bytes_dense"] += float(
            self._dense_upload_nbytes * n_uploads)
        return nbytes

    @functools.cached_property
    def _mask_nnz_jit(self):
        def nnz(masks_stacked):
            return jax.vmap(lambda m: sum(
                jnp.sum(x > 0) for x in jax.tree.leaves(m)))(masks_stacked)

        return jax.jit(nnz)

    def warn_if_masks_collapsed(self, masks_stacked, round_idx: int
                                ) -> np.ndarray:
        """Post-round diagnosability for the jitted mask-evolution paths
        (ADVICE r5): an all-False evolved mask — the footprint of a NaN
        poisoning fire/regrow's magnitude ranks — must be VISIBLE, not a
        silent collapse of the comm metrics. Returns per-client nnz.

        Doubles as the mask-health boundary for engines whose masks
        evolve OUTSIDE a declared round body (dispfl's chunked host
        driver, ISSUE 15): the nnz fetch this call already makes IS the
        density measurement, so ``nidt_health_mask_density`` publishes
        here with no added sync."""
        nnz = np.asarray(jax.device_get(
            self._mask_nnz_jit(masks_stacked)))[: self.real_clients]
        per_client = sum(
            float(np.prod(x.shape[1:]))
            for x in jax.tree.leaves(masks_stacked))
        if per_client > 0 and nnz.size:
            obs_health.publish_mask_density(
                self.name, round_idx,
                float(np.mean(nnz) / per_client))
        if (nnz == 0).any():
            dead = np.flatnonzero(nnz == 0).tolist()
            self.log.warning(
                "round %d: clients %s evolved an EMPTY mask (0 surviving "
                "weights) — a NaN in params/gradients poisons the "
                "fire/regrow magnitude ranks into all-False; check the "
                "local losses of these clients for divergence",
                round_idx, dead)
        return nnz

    def aggregate(self, stacked, weights: jax.Array):
        """Weighted mean of a client-stacked pytree. On a two-level
        (silos, clients) mesh (``--mesh_shape S C``) the reduction is
        routed silo-first: ICI within each silo, ONE aggregate per silo
        across DCN (parallel/hierarchical.py) — same result as the flat
        mean, bandwidth-correct layout. Falls back to the flat mean when
        the stacked axis doesn't tile the mesh (e.g. frac-sampled subsets
        smaller than the device grid)."""
        from neuroimagedisttraining_tpu.parallel.hierarchical import (
            is_two_level, silo_then_global_mean,
        )

        leaves = jax.tree.leaves(stacked)
        if not leaves:  # e.g. batch_stats of a GroupNorm model
            return stacked
        if is_two_level(self.mesh):
            if leaves[0].shape[0] % self.mesh.devices.size == 0:
                return silo_then_global_mean(stacked, weights, self.mesh)
            if not getattr(self, "_warned_flat_fallback", False):
                self._warned_flat_fallback = True
                self.log.info(
                    "two-level mesh: sampled-client axis (%d) does not "
                    "tile the %d-device grid; falling back to the FLAT "
                    "weighted mean (same result, but aggregation will NOT "
                    "be routed silo-first over ICI/DCN). Choose frac so "
                    "client_num_per_round is a multiple of the device "
                    "count to keep the two-level routing.",
                    leaves[0].shape[0], self.mesh.devices.size)
        return pt.tree_weighted_mean(stacked, weights)

    # ---------- streamed evaluation (cohort > HBM) ----------

    def _eval_chunk_size(self) -> int:
        if self.cfg.stream_chunk_clients > 0:
            return self.cfg.stream_chunk_clients
        return self.mesh.devices.size if self.mesh is not None else 4

    def eval_global_stream(self, params, bstats, split: str = "test"
                           ) -> dict[str, float]:
        """Full-cohort eval of one model, streaming client chunks through
        the same jitted per-chunk program as the resident path — metric
        parity by construction."""
        parts: list[tuple] = []
        ns: list[np.ndarray] = []
        for ch in self.stream.eval_chunks(self._eval_chunk_size(), split):
            out = self._eval_global_jit(params, bstats, ch.X, ch.y, ch.n)
            parts.append(tuple(np.asarray(o)[: len(ch.ids)] for o in out))
            ns.append(np.asarray(jax.device_get(ch.n))[: len(ch.ids)])
            if self.cfg.fed.ci:
                break
        cat = [np.concatenate([p[i] for p in parts]) for i in range(4)]
        n_all = np.concatenate(ns)
        if self.cfg.fed.ci:
            cat = [c[:1] for c in cat]
            n_all = n_all[:1]
        return self._summarize(*cat, n=n_all)

    def stream_map_train_chunks(self, block_fn, state_trees: tuple, rngs,
                                *args):
        """Run a vmapped per-client block over host-streamed TRAIN chunks
        and concatenate the per-client outputs back into [C, ...] stacks
        (the shared chunk loop of DisPFL/D-PSGD/Local streamed rounds).

        ``block_fn(*state_chunks, rng_chunk, X, y, n, *args)`` must return
        ``(*out_trees, per_client_aux_vector)``; outputs beyond the real
        clients in the final padded chunk are dropped."""
        chunk = self._eval_chunk_size()
        parts: list[list] | None = None
        aux_parts: list = []
        for ch in self.stream.eval_chunks(chunk, "train"):
            take = lambda t: pt.tree_stack_index(t, ch.padded_ids)
            *trees, aux = block_fn(*(take(t) for t in state_trees),
                                   rngs[ch.padded_ids], ch.X, ch.y, ch.n,
                                   *args)
            keep = len(ch.ids)
            if parts is None:
                parts = [[] for _ in trees]
            for lst, t in zip(parts, trees):
                lst.append(jax.tree.map(lambda x: x[:keep], t))
            aux_parts.append(aux[:keep])
        cat = lambda ps: jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *ps)
        return tuple(cat(ps) for ps in parts), jnp.concatenate(aux_parts)

    def eval_personalized_stream(self, per_params, per_bstats,
                                 split: str = "test") -> dict[str, float]:
        """Personalized eval when only the STATE is device-resident: stream
        the cohort's eval shards in client chunks and gather each chunk's
        rows out of the stacked per-client state. Per-client metrics are
        independent, so chunked results match the resident vmap bitwise."""
        chunk = self._eval_chunk_size()
        parts: list[tuple] = []
        ns: list[np.ndarray] = []
        for ch in self.stream.eval_chunks(chunk, split):
            p = pt.tree_stack_index(per_params, ch.padded_ids)
            b = pt.tree_stack_index(per_bstats, ch.padded_ids)
            out = self._eval_personal_jit(p, b, ch.X, ch.y, ch.n)
            parts.append(tuple(np.asarray(o)[: len(ch.ids)] for o in out))
            ns.append(np.asarray(jax.device_get(ch.n))[: len(ch.ids)])
            if self.cfg.fed.ci:
                break
        cat = [np.concatenate([p[i] for p in parts]) for i in range(4)]
        n_all = np.concatenate(ns)
        if self.cfg.fed.ci:  # client 0 only, matching the resident CI path
            cat = [c[:1] for c in cat]
            n_all = n_all[:1]
        return self._summarize(*cat, n=n_all)

    def train(self) -> dict[str, Any]:
        raise NotImplementedError
