"""SalientGrads: one-shot federated SNIP mask + masked-sparse FedAvg.

The flagship algorithm (fedml_api/standalone/sailentgrads/sailentgrads_api.py).
Behavior parity:

- PHASE 1 (once, before training): every client computes SNIP saliency
  scores on its own data (IterSNIP over ``itersnip_iteration`` batches,
  client.py:30-53); the server averages score dicts (snip.py:120-140) and
  builds ONE global cross-layer top-(dense_ratio) binary mask
  (snip.py:80-116). Dense escape hatch: ``snip_mask=False`` -> all-ones
  masks (sailentgrads_api.py:94-100).
- PHASE 2 (rounds): sampled clients train from the global model with
  post-step re-masking ``param *= mask`` (my_model_trainer.py:228-231);
  sample-weighted FedAvg over the sampled set (sailentgrads_api.py:212-227);
  each client's personal model is its most recent local-train result
  (sailentgrads_api.py:128-136); global + personal eval every round.

TPU-native: phase 1 is one jitted program — per-client scores vmapped over
the client-sharded mesh, the score mean is an ICI all-reduce, and the global
top-k threshold runs the Pallas histogram-select kernel. Phase 2 rounds are
the same single-program SPMD shape as FedAvg.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.obs import health as obs_health
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.ops import flops as flops_ops
from neuroimagedisttraining_tpu.ops import snip as snip_ops
from neuroimagedisttraining_tpu.ops.masks import mask_density, ones_mask
from neuroimagedisttraining_tpu.utils import pytree as pt


class SalientGradsEngine(FederatedEngine):
    name = "salientgrads"
    # Streaming mode (cohort > HBM): per-client DATA streams per round /
    # per phase-1 chunk; the per-client personal STATE (params + batch
    # stats) and the global mask stay device-resident — the reference's
    # per-batch lazy HDF5 fetch (my_model_trainer.py:185-199) done at
    # round granularity, same as FedAvg's streaming path.
    supports_streaming = True
    supports_wire_codec = True  # masked roundtrip inside _round_body
    supports_secure_quant = True  # masked uploads still aggregate
    # through the builder's default tail — the field fold replaces it
    supports_byz_faults = True  # uploads route through faults/adversary
    supports_cohort_sharding = True  # phase-1 scores and the phase-2
    # round's local-train stage shard over the --client_mesh (ISSUE 6)
    supported_defenses = robust.DEFENSES
    #: the phase-1 global mask once generated (wire_masks handoff)
    _wire_masks = None

    def wire_masks(self):
        """Mask handoff (codec/): the phase-1 global SNIP mask — static
        across rounds and owned by BOTH endpoints (the server computed
        and broadcast it), so the wire codec packs uploads against it
        with no bitmap frame."""
        return self._wire_masks

    # ---------- phase 1: the global mask ----------

    def _scores_body(self, params, bstats, Xs, ys, ns, rngs):
        """Weighted SNIP-score SUM over a block of clients + the block's
        client-weight sum — shared by the resident one-shot program and
        the streamed per-chunk program."""
        trainer = self.trainer
        s = self.cfg.sparsity
        o = self.cfg.optim
        K = Xs.shape[0]
        cs = ClientState(
            params=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (K,) + x.shape), params),
            batch_stats=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (K,) + x.shape), bstats),
            opt_state=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (K,) + x.shape),
                trainer.opt.init(params)),
            rng=rngs,
        )

        def per_client(cs_c, Xc, yc, nc, idx_c=None):
            sc = snip_ops.iter_snip_scores(
                trainer, cs_c, Xc, yc, nc,
                iterations=s.itersnip_iterations, batch_size=o.batch_size,
                stratified=s.stratified_sampling, idx_stack=idx_c)
            # zero-weight padding clients contribute nothing
            w = (nc > 0).astype(jnp.float32)
            return jax.tree.map(lambda t: t * w, sc), w

        # phase-1 scoring shards per-client over the cohort mesh when
        # armed (the resident cohort tiles the mesh by construction —
        # the data layer pads num_clients); the weighted SUM runs on the
        # all-gathered replicated stacks, so scores — and the global
        # mask/threshold — match the sequential pipeline's to ~1 ulp
        # (tests/test_cohort.py pins the emitted masks identical on its
        # seed). Like the round's epoch permutations, IterSNIP's batch
        # draws are HOISTED out of the partition (in-partition RNG draws
        # consumed by a scan are the measured miscompile class —
        # parallel/cohort.py); the STRATIFIED sampler's choice-based
        # draw has no hoisted form yet, so it keeps the unsharded path
        if self._cohort_on and K % self.mesh.devices.size == 0 \
                and not s.stratified_sampling:
            idxs = jax.vmap(
                lambda r, n: snip_ops.iter_snip_batch_indices(
                    r, s.itersnip_iterations, o.batch_size, n))(cs.rng, ns)
            per, w = self._cohort_map(per_client, cs, Xs, ys, ns, idxs)
        else:
            per, w = jax.vmap(per_client)(cs, Xs, ys, ns)
        return (jax.tree.map(lambda t: jnp.sum(t, axis=0), per),
                jnp.sum(w))

    @functools.cached_property
    def _scores_jit(self):
        def scores_fn(params, bstats, data, rngs):
            ssum, wsum = self._scores_body(params, bstats, data.X_train,
                                           data.y_train, data.n_train, rngs)
            # mean over REAL clients (snip.py get_mean_snip_scores)
            denom = jnp.maximum(wsum, 1.0)
            return jax.tree.map(lambda t: t / denom, ssum)

        return jax.jit(scores_fn)

    @functools.cached_property
    def _chunk_scores_jit(self):
        return jax.jit(self._scores_body)

    def _scores_streaming(self, params, bstats):
        """Phase-1 SNIP scores over a >HBM cohort: stream train shards in
        client chunks; only the (param-sized) score accumulator stays on
        device. Matches my_model_trainer.py:185-199's lazy per-batch fetch
        at chunk granularity."""
        chunk = self._eval_chunk_size()
        acc, wtot = None, None
        for ch in self.stream.eval_chunks(chunk, "train"):
            rngs = self.per_client_rngs(-1, ch.padded_ids)
            ssum, wsum = self._chunk_scores_jit(params, bstats, ch.X, ch.y,
                                                ch.n, rngs)
            if acc is None:
                acc, wtot = ssum, wsum
            else:
                acc = pt.tree_add(acc, ssum)
                wtot = wtot + wsum
        denom = jnp.maximum(wtot, 1.0)
        return jax.tree.map(lambda t: t / denom, acc)

    def generate_global_mask(self, params, bstats):
        """Phase-1 pipeline (sailentgrads_api.py:47-66)."""
        if self.stream is not None:
            scores = self._scores_streaming(params, bstats)
        else:
            rngs = self.per_client_rngs(-1, np.arange(self.num_clients))
            scores = self._scores_jit(params, bstats, self.data, rngs)
        masks, thr = snip_ops.mask_from_scores(
            scores, keep_ratio=self.cfg.sparsity.dense_ratio)
        if not self.cfg.sparsity.snip_mask:
            masks = ones_mask(params)  # dense escape hatch
        return masks, thr

    # ---------- phase 2: masked rounds ----------

    # ---------- the declared round (engines/program.py) ----------

    def round_stages(self):
        """The masked round as a declaration: FedAvg's carry plus the
        persistent per-client personal stacks, the phase-1 mask as a
        loop constant, and an update stage scattering each sampled
        client's HONEST local result (pre-attack/codec — the attack is
        on the wire payload, not the silo's own state). The builder's
        codec stage packs uploads against the mask (``codec_masks``
        handoff: top-k sparse by construction, bitmap-free)."""
        return round_program.RoundStages(
            carry=("params", "batch_stats", "per_params", "per_bstats"),
            train=self._train_stage,
            update=self._update_stage,
            consts=("masks",),
            supports_attack=True,
            codec_masks=self._codec_masks,
            health=self._health_stage,
            health_outputs=obs_health.MASK_STAT_NAMES,
        )

    def _health_stage(self, ctx, tr, new_carry) -> dict:
        """Mask-health leg (ISSUE 15, armed under ``--health_stats``):
        the phase-1 mask is a loop CONSTANT, so density is the whole
        story (overlap pins at 1 — which is itself the signal: a
        salientgrads run whose overlap moved would mean the const mask
        was rebuilt mid-run)."""
        return round_program.mask_health_stats(ctx.consts["masks"],
                                               None)

    def _train_stage(self, ctx) -> round_program.TrainOut:
        """Masked local-train stage (post-step re-mask ``param *= mask``,
        my_model_trainer.py:228-231): vmapped, or unbatched per-client
        loops under the client mesh with the mask riding as a closed-over
        replicated constant (ctx.client_map; perms hoisted —
        parallel/cohort.py)."""
        trainer = self.trainer
        o = self.cfg.optim
        params = ctx.carry["params"]
        bstats = ctx.carry["batch_stats"]
        masks = ctx.consts["masks"]
        Xs, ys, ns = ctx.Xs, ctx.ys, ctx.ns
        lr = ctx.lr
        S = Xs.shape[0]
        max_samples = self._max_samples()
        cs = ClientState(
            params=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), params),
            batch_stats=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), bstats),
            opt_state=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape),
                trainer.opt.init(params)),
            rng=ctx.rngs,
        )

        def local(cs_c, Xc, yc, nc, perms_c=None):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                mask=masks, perms=perms_c)

        cs, losses = ctx.client_map(
            local, cs, Xs, ys, ns,
            hoisted=(lambda: ctx.local_perms(ctx.rngs, ns, o.epochs),))
        return round_program.TrainOut(
            losses=losses,
            upload={"params": cs.params, "batch_stats": cs.batch_stats},
            state=cs)

    def _codec_masks(self, ctx) -> dict:
        """Mask handoff to the builder's codec stage: the phase-1 global
        mask over params, all-ones over the (never-pruned) batch stats —
        the exact tree a cross-silo silo encodes (distributed/run.py)."""
        return {"params": ctx.consts["masks"],
                "batch_stats": jax.tree.map(jnp.ones_like,
                                            ctx.carry["batch_stats"])}

    def _update_stage(self, ctx, tr, new_carry) -> dict:
        """Personal models <- this round's local results; pad entries
        (mesh tiling / streamed feed) are dropped, never written
        (base.scatter_sampled_rows)."""
        real = ctx.ns > 0
        per_params = self.scatter_sampled_rows(
            ctx.carry["per_params"], tr.state.params, ctx.sampled_idx,
            real)
        per_bstats = self.scatter_sampled_rows(
            ctx.carry["per_bstats"], tr.state.batch_stats,
            ctx.sampled_idx, real)
        return {"per_params": per_params, "per_bstats": per_bstats}

    # ---------- legacy-signature program adapters ----------

    @functools.cached_property
    def _round_jit(self):
        prog = self.program.round_jit()

        def round_call(params, bstats, per_params, per_bstats, data,
                       masks, sampled_idx, rngs, lr, byz=None):
            return prog((params, bstats, per_params, per_bstats), data,
                        (masks,), sampled_idx, rngs, lr, None, byz)

        return round_call

    def _sharded_round_jit(self, n_real: int):
        """The cohort-sharded masked round (ISSUE 6): ``_round_jit``'s
        signature and donation contract, with ``sampled_idx``/``rngs``
        covering the MESH-PADDED sampled set and the builder sharding
        the local-train stage over the client mesh (``n_real`` static)."""
        prog = self.program.round_jit(n_real=n_real)

        def sharded_round_call(params, bstats, per_params, per_bstats,
                               data, masks, sampled_idx, rngs, lr,
                               byz=None):
            return prog((params, bstats, per_params, per_bstats), data,
                        (masks,), sampled_idx, rngs, lr, None, byz)

        return sharded_round_call

    @functools.cached_property
    def _round_stream_jit(self):
        prog = self.program.stream_jit()

        def stream_round_call(params, bstats, per_params, per_bstats,
                              Xs, ys, ns, masks, sampled_idx, rngs, lr,
                              byz=None):
            return prog((params, bstats, per_params, per_bstats),
                        (masks,), Xs, ys, ns, sampled_idx, rngs, lr,
                        None, byz)

        return stream_round_call

    # ---------- fused multi-round dispatch (ISSUE 4) ----------

    def _run_fused_window(self, params, bstats, per_params, per_bstats,
                          masks, round_idx: int, k: int):
        """Dispatch rounds ``[round_idx, round_idx + k)`` as one scan
        (program.run_window). Returns the new state, per-round sampled
        sets (for the host-side stat accounting), the boundary round's
        loss, and the actual window length."""
        carry, _, outs, wi = self.program.run_window(
            (params, bstats, per_params, per_bstats), round_idx, k,
            consts=(masks,))
        return (*carry, wi.sampled, outs["loss"][-1], wi.k)

    def _eval_ckpt_hooks(self, round_idx, params, bstats, per_params,
                         per_bstats, masks, loss, history):
        """The sequential loop's per-round hook tail (eval cadence +
        checkpoint), shared verbatim by the fused windows — which, by the
        window planner's construction, reach here exactly on the rounds
        the sequential loop would have evaluated/checkpointed."""
        cfg = self.cfg
        if round_idx % cfg.fed.frequency_of_the_test == 0 \
                or round_idx == cfg.fed.comm_round - 1:
            m = self._eval_g(params, bstats)
            mp = self._eval_p(per_params, per_bstats)
            self._flush_nonfinite(round_idx)
            self.stat_info["global_test_acc"].append(m["acc"])
            self.stat_info["person_test_acc"].append(mp["acc"])
            self.log.metrics(round_idx, train_loss=loss, **m,
                             personal_acc=mp["acc"])
            history.append({"round": round_idx,
                            "train_loss": float(loss), **m,
                            "personal_acc": mp["acc"]})
        self.maybe_checkpoint(round_idx, {
            "params": params, "batch_stats": bstats,
            "per_params": per_params, "per_bstats": per_bstats,
            "masks": masks, "history": history})

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        params, bstats = gs.params, gs.batch_stats

        start, restored = self.restore_checkpoint()
        if restored is not None:
            masks = restored["masks"]  # phase 1 not recomputed on resume
        else:
            masks, thr = self.generate_global_mask(params, bstats)
        density = float(mask_density(masks))
        # mask handoff: the wire codec (and any cross-silo deployment of
        # this engine) packs uploads against this mask — both endpoints
        # own it, phase 1 computed it server-side and broadcast it
        self._wire_masks = masks
        self.log.info("global SNIP mask density = %.4f (target %.4f)",
                      density, cfg.sparsity.dense_ratio)
        self.stat_info["mask_density"] = density
        if cfg.sparsity.save_masks:
            self.stat_info["final_masks"] = jax.tree.map(np.asarray, masks)

        # flops/comm accounting (reference stat_info parity)
        dens_map = flops_ops.densities_from_masks(masks)
        flops_per_sample = flops_ops.count_training_flops_per_sample(
            self.trainer.model, params, self.trainer._prep(self.sample_input()),
            mask_density=dens_map, batch_stats=bstats)
        # communicated parameters per client per round = nonzero mask entries
        # (masks are ones on non-maskable leaves), matching the reference's
        # nonzero-parameter comm metric (model_trainer.py:49-53)
        comm_params_per_client = float(sum(
            float(jnp.sum(m)) for m in jax.tree.leaves(masks)))

        per = self.broadcast_states(
            ClientState(params=params, batch_stats=bstats,
                        opt_state=self.trainer.opt.init(params),
                        rng=gs.rng), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats

        history = []
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            history = restored["history"]
        if self.stream is not None:
            self.stream.prefetch_train(*self.stream_sampling(start))
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                (params, bstats, per_params, per_bstats, window_sampled,
                 loss, k) = self._run_fused_window(
                    params, bstats, per_params, per_bstats, masks,
                    round_idx, k)
                # per-round host-side accounting, identical to the
                # sequential loop's (host data only — no device sync)
                for off, s in enumerate(window_sampled):
                    n_samples = float(np.sum(self._n_train_host[s]))
                    self.stat_info["sum_training_flops"] += (
                        flops_per_sample * cfg.optim.epochs * n_samples)
                    self.stat_info["sum_comm_params"] += (
                        comm_params_per_client * len(s))
                round_idx += k - 1  # boundary hooks below
                self._eval_ckpt_hooks(round_idx, params, bstats,
                                      per_params, per_bstats, masks, loss,
                                      history)
                round_idx += 1
                continue
            sampled = self.client_sampling(round_idx)
            self.log.info("################ round %d: clients %s",
                          round_idx, sampled.tolist())
            if self.stream is not None:
                fed_ids, n_real = self.stream_sampling(round_idx, sampled)
                rngs = self.per_client_rngs(round_idx, fed_ids)
                byz = self._byz_round_plan(round_idx, fed_ids)
                Xs, ys, ns = self.stream.get_train(fed_ids, n_real)
                if round_idx + 1 < cfg.fed.comm_round:
                    # overlap next round's host read with this round
                    self.stream.prefetch_train(
                        *self.stream_sampling(round_idx + 1))
                (params, bstats, per_params, per_bstats, loss,
                 n_bad) = self._round_stream_jit(
                    params, bstats, per_params, per_bstats, Xs, ys, ns,
                    masks, jnp.asarray(fed_ids), rngs,
                    self.round_lr(round_idx), byz)
            else:
                # cohort sharding (ISSUE 6): padded gather ids for the
                # sharded program; byz plan and byte accounting stay on
                # the REAL sampled set (the body slices pads off)
                ids, round_prog = self._cohort_round_prog(sampled)
                rngs = self.per_client_rngs(round_idx, ids)
                byz = self._byz_round_plan(round_idx, sampled)
                if self.wire_spec is not None:
                    ref_host = jax.tree.map(
                        np.asarray, {"params": params,
                                     "batch_stats": bstats})
                    with obs_trace.span("round", round=round_idx,
                                        codec=True):
                        (params, bstats, per_params, per_bstats, loss,
                         n_bad, u0) = round_prog(
                            params, bstats, per_params, per_bstats,
                            self.data, masks, jnp.asarray(ids), rngs,
                            self.round_lr(round_idx), byz)
                    masks_host = {
                        "params": jax.tree.map(np.asarray, masks),
                        "batch_stats": jax.tree.map(
                            np.ones_like, ref_host["batch_stats"])}
                    self.account_wire_bytes(
                        jax.tree.map(np.asarray, u0), ref_host,
                        masks_host=masks_host, n_uploads=len(sampled))
                else:
                    with obs_trace.span("round", round=round_idx):
                        (params, bstats, per_params, per_bstats, loss,
                         n_bad) = round_prog(
                            params, bstats, per_params, per_bstats,
                            self.data, masks, jnp.asarray(ids), rngs,
                            self.round_lr(round_idx), byz)
            self._note_nonfinite(n_bad)
            n_samples = float(np.sum(self._n_train_host[sampled]))
            self.stat_info["sum_training_flops"] += (
                flops_per_sample * cfg.optim.epochs * n_samples)
            self.stat_info["sum_comm_params"] += (comm_params_per_client
                                                  * len(sampled))
            self._eval_ckpt_hooks(round_idx, params, bstats, per_params,
                                  per_bstats, masks, loss, history)
            round_idx += 1
        self._flush_nonfinite(cfg.fed.comm_round - 1)
        m_global = self._eval_g(params, bstats)
        m_person = self._eval_p(per_params, per_bstats)
        self.log.metrics(-1, global_=m_global, personal=m_person)
        return {"params": params, "batch_stats": bstats, "masks": masks,
                "mask_density": density, "history": history,
                "final_global": m_global, "final_personal": m_person}
