"""DisPFL: decentralized personalized sparse training (RigL-style dynamic
masks), fedml_api/standalone/DisPFL/dispfl_api.py:46-240 + DisPFL/client.py.

Behavior parity (with two documented deviations):

- Init: ERK (or uniform) layer sparsities at ``dense_ratio``; all clients
  share one random mask unless ``different_initial``; ``diff_spa`` cycles
  per-client densities through {0.2,0.4,0.6,0.8,1.0} (dispfl_api.py:52-71).
- Per round: Bernoulli(``active``) activity draw (dispfl_api.py:96) — the
  reference's fault injection. **Inactive clients still run local training**
  (dispfl_api.py:104-116 trains every client); activity only gates whether a
  client receives neighbors' models (its neighbor set collapses to {self}).
- Neighbor choice: reference ``_benefit_choose`` (dispfl_api.py:196-220).
  NOTE the reference force-overrides ``cs = "random"`` at dispfl_api.py:200,
  making its ring/full branches dead; we honor the configured ``cs`` but
  default to "random", and keep the reference's resample-while-self quirk.
- Consensus: mask-overlap-weighted neighbor aggregation
  (``_aggregate_func``, dispfl_api.py:222-240): per weight, the average of
  neighbors' (masked) values weighted 1/overlap-count, zero where no
  neighbor keeps the weight; then re-masked by the client's personal mask.
  DEVIATION (documented): the reference's committed code *bypasses* this
  call (dispfl_api.py:142 overwrites with the client's own previous model);
  we run the published algorithm. Set ``cs="self"`` for bypass parity.
- Local train: masked SGD with post-step ``param *= mask``
  (DisPFL/my_model_trainer.py:245-248).
- Mask evolution (unless ``static``): one-batch DENSE gradient probe in
  eval mode (``screen_gradients``, my_model_trainer.py:165-188), cosine-
  annealed magnitude ``fire_mask`` + gradient-magnitude ``regrow_mask``
  (DisPFL/client.py:71-99); random regrow under ``dis_gradient_check``.
- ``mask_shared`` (what neighbors aggregate against next round) is the
  PRE-evolution mask (dispfl_api.py:148 runs before client.train evolves).
- End of training: all-pairs mask Hamming matrix (dispfl_api.py:170-175),
  optional ``save_masks``.

TPU-native: per-client masks/models are stacked pytrees sharded over the
client mesh axis; the neighbor consensus for the whole federation is two
einsums against the adjacency matrix (an all-to-all over ICI); fire/regrow
are vmapped rank-select ops — one jitted program per round.

The reference's ``w_per_globals`` accumulator (dispfl_api.py:85,160-162) is
write-only in its committed code (only the bypassed aggregate would read
it); we do not carry it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.parallel.gossip import (
    SparseSpec, gossip_apply, gossip_apply_sparse, make_plan,
)
from neuroimagedisttraining_tpu.ops import flops as flops_ops
from neuroimagedisttraining_tpu.ops import masks as M
from neuroimagedisttraining_tpu.utils import pytree as pt

DIFF_SPA_CYCLE = (0.2, 0.4, 0.6, 0.8, 1.0)  # dispfl_api.py:65-66


class DisPFLEngine(FederatedEngine):
    name = "dispfl"
    # Streaming (cohort > HBM): DisPFL trains EVERY client each round, so
    # the streamed round runs the state-only neighbor consensus first, then
    # local-train+mask-evolution over client CHUNKS whose data shards are
    # host-fetched per chunk — per-client results are independent, so the
    # chunked composition equals the fused resident program.
    supports_streaming = True
    #: current per-client masks (client-stacked), tracked for the wire
    #: codec mask handoff
    _masks_local = None

    def cohort_fallback_key(self) -> str | None:
        # --client_mesh (ISSUE 6) is redundant here, not unsupported:
        # the decentralized consensus ALREADY runs client-sharded on the
        # mesh (parallel/gossip.py lowers the per-round adjacency to
        # ppermute / routed all_to_all collectives over the client axis)
        return "gossip-mesh-collectives"

    def wire_masks(self):
        """Mask handoff (codec/): the CURRENT per-client masks, stacked
        [C, ...]. Unlike SalientGrads' static global mask these evolve
        every round (fire/regrow), so a cross-silo deployment ships the
        bitmap frame alongside the surviving values — the receiver
        cannot assume it holds the sender's latest mask."""
        return self._masks_local

    # ---------- init ----------

    def init_masks_all(self, params) -> tuple:
        """Stacked per-client masks [C, ...] + per-client target densities
        (dispfl_api.py:52-71)."""
        s = self.cfg.sparsity
        C = self.num_clients
        dist = "uniform" if s.uniform else "ERK"
        rng = jax.random.key(self.cfg.seed + 23)
        w_spa = [s.dense_ratio] * C

        if s.diff_spa:
            per_client = []
            for i in range(C):
                dr = DIFF_SPA_CYCLE[i % len(DIFF_SPA_CYCLE)]
                w_spa[i] = dr
                sp = M.calculate_sparsities(params, dist, dense_ratio=dr,
                                            erk_power_scale=s.erk_power_scale)
                per_client.append(M.init_masks(jax.random.fold_in(rng, i),
                                               params, sp))
            masks = jax.tree.map(lambda *xs: jnp.stack(xs), *per_client)
        else:
            sp = M.calculate_sparsities(params, dist,
                                        dense_ratio=s.dense_ratio,
                                        erk_power_scale=s.erk_power_scale)
            if s.different_initial:
                per_client = [M.init_masks(jax.random.fold_in(rng, i),
                                           params, sp) for i in range(C)]
                masks = jax.tree.map(lambda *xs: jnp.stack(xs), *per_client)
            else:
                one = M.init_masks(rng, params, sp)
                masks = jax.tree.map(
                    lambda m: jnp.broadcast_to(m, (C,) + m.shape).copy(), one)
        return masks, w_spa

    # ---------- host-side per-round graph ----------

    def active_draw(self, round_idx: int) -> np.ndarray:
        """Bernoulli(active) per client (dispfl_api.py:96). Deviation: we
        seed by round for reproducibility; the reference draws from global
        unseeded np.random state. The draw now lives in
        ``faults.schedule.activity_mask`` (bit-identical stream) so the
        engine and the cross-silo fault schedule share one seed; a
        ``--fault_spec`` additionally forces crashed clients inactive."""
        from neuroimagedisttraining_tpu.faults.schedule import activity_mask

        if self.fault_schedule is not None:
            a = self.fault_schedule.active_mask(round_idx,
                                                self.real_clients,
                                                self.cfg.fed.active)
        else:
            a = activity_mask(self.cfg.seed, round_idx,
                              self.real_clients, self.cfg.fed.active)
        out = np.zeros(self.num_clients, bool)
        out[: self.real_clients] = a
        return out

    def adjacency(self, round_idx: int, active: np.ndarray) -> np.ndarray:
        """Row c = {neighbors(c)} ∪ {c}; inactive clients get {c} only
        (dispfl_api.py:104-127 + _benefit_choose:196-220)."""
        C = self.num_clients
        total = self.real_clients
        per_round = min(self.cfg.fed.client_num_per_round, total)
        cs = self.cfg.fed.cs
        A = np.zeros((C, C), np.float32)
        for c in range(total):
            A[c, c] = 1.0
            if not active[c] or cs == "self":
                continue
            if total == per_round:
                # reference _benefit_choose early-returns ALL clients for
                # any cs at full participation (dispfl_api.py:197-200)
                A[c, :total] = 1.0
                continue
            if cs == "random":
                # the reference draws from unseeded global np.random state;
                # we use a collision-free per-(seed, round, client) stream
                rs = np.random.RandomState(
                    (self.cfg.seed * 100003 + round_idx * 1009 + c)
                    % (2**31 - 1))
                nei = rs.choice(range(total), per_round, replace=False)
                while c in nei:  # reference resample-while-self quirk
                    nei = rs.choice(range(total), per_round, replace=False)
            elif cs == "ring":
                nei = np.asarray([(c - 1) % total, (c + 1) % total])
            elif cs == "full":
                nei = np.flatnonzero(active[:total])
                nei = nei[nei != c]
            else:
                raise ValueError(f"unknown cs {cs!r}")
            A[c, nei] = 1.0
        for c in range(total, C):
            A[c, c] = 1.0
        return A

    # ---------- the round program ----------

    def _consensus(self, per_params, per_bstats, masks_local, masks_shared,
                   A, plan_arrays=None, *, plan=None):
        """Mask-overlap-weighted neighbor aggregation (state-only).

        counts[c] = sum_j A[c,j] * masks_shared[j]  (overlap count)
        w_tmp[c]  = (1/counts[c]) * sum_j A[c,j] * w[j], 0 where count=0

        With a circulant ring/k-lattice adjacency tiling the mesh (Plan
        tuple), each neighbor sum lowers to ppermute shifts; with the
        reference's per-round random k-regular adjacency (SparseSpec +
        traced ``plan_arrays``) it lowers to a routed capped all_to_all
        (parallel/gossip.py). Only dense patterns fall back to the
        all-gather einsum. All three mixed trees (overlap counts, masked
        sums, batch stats) share one lowering.
        """
        if isinstance(plan, SparseSpec):
            mix = lambda t: gossip_apply_sparse(t, plan, plan_arrays,
                                                self.mesh)
        elif plan is not None:
            mix = lambda t: gossip_apply(t, plan, self.mesh)
        else:
            mix = lambda t: jax.tree.map(
                lambda x: jnp.einsum("cj,j...->c...", A,
                                     x.astype(jnp.float32)).astype(x.dtype),
                t)
        counts = mix(masks_shared)
        sums = mix(per_params)
        w_tmp = jax.tree.map(
            lambda sm, ct: jnp.where(ct > 0, sm / jnp.maximum(ct, 1.0),
                                     0.0),
            sums, counts)
        # personal re-mask (dispfl_api.py:238-239)
        w_local = jax.tree.map(jnp.multiply, w_tmp, masks_local)
        # batch_stats are not masked; plain neighbor mean (same sparse
        # lowering as the other mixes)
        deg = jnp.sum(A, axis=1)
        b_mixed = jax.tree.map(
            lambda x: x / deg.reshape((-1,) + (1,) * (x.ndim - 1)),
            mix(per_bstats))
        return w_local, b_mixed

    def _local_and_evolve(self, w_local, b_mixed, masks_local, rngs, X, y,
                          n, lr, round_idx):
        """Vmapped local training (post-step re-mask) + fire/regrow mask
        evolution over a block of clients — per-client independent, so the
        streamed chunked composition matches the fused resident program."""
        trainer = self.trainer
        o = self.cfg.optim
        s = self.cfg.sparsity
        comm_round = self.cfg.fed.comm_round
        max_samples = self._max_samples()

        def local(p, b, m, rng, Xc, yc, nc):
            cs_c = ClientState(params=p, batch_stats=b,
                               opt_state=trainer.opt.init(p), rng=rng)
            cs_c, loss = trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples, mask=m)
            return cs_c.params, cs_c.batch_stats, loss, cs_c.rng

        new_p, new_b, losses, rngs2 = jax.vmap(local)(
            w_local, b_mixed, masks_local, rngs, X, y, n)

        # --- mask evolution: screen -> fire -> regrow ---
        if s.static:
            new_masks = masks_local
        else:
            def evolve(p, b, m, rng, Xc, yc, nc):
                brng, grng = jax.random.split(rng)
                idx = jax.random.randint(brng, (o.batch_size,), 0,
                                         jnp.maximum(nc, 1))
                grad = trainer.eval_grad(p, b, jnp.take(Xc, idx, axis=0),
                                         jnp.take(yc, idx, axis=0))
                fired, num_remove = M.fire_mask(
                    m, p, round_idx, comm_round,
                    anneal_factor=s.anneal_factor)
                return M.regrow_mask(
                    fired, num_remove,
                    None if s.dis_gradient_check else grad,
                    rng=grng, dis_gradient_check=s.dis_gradient_check)

            new_masks = jax.vmap(evolve)(new_p, new_b, masks_local, rngs2,
                                         X, y, n)
        return new_p, new_b, new_masks, losses

    def _round_jit_for(self, plan):
        def build():
            def round_fn(per_params, per_bstats, masks_local, masks_shared,
                         data, A, rngs, lr, round_idx, plan_arrays):
                w_local, b_mixed = self._consensus(
                    per_params, per_bstats, masks_local, masks_shared, A,
                    plan_arrays, plan=plan)
                new_p, new_b, new_masks, losses = self._local_and_evolve(
                    w_local, b_mixed, masks_local, rngs,
                    data.X_train, data.y_train, data.n_train, lr, round_idx)
                # mask change tracking: hamming(shared_lstrd, local) per
                # client (dispfl_api.py:110)
                dist_self = jax.vmap(M.mask_hamming_distance)(masks_shared,
                                                              masks_local)
                real = (data.n_train > 0).astype(jnp.float32)
                mean_loss = jnp.sum(losses * real) / jnp.maximum(
                    jnp.sum(real), 1.0)
                # next round's shared masks = this round's PRE-evolution
                # masks
                return (new_p, new_b, new_masks, masks_local, dist_self,
                        mean_loss)

            # donation: personal stacks + both mask generations are
            # consumed (masks_local is returned as the next round's
            # shared masks — its buffer aliases that output directly)
            return jax.jit(round_fn,
                           donate_argnums=self._donate_argnums(0, 1, 2, 3))

        return self._plan_cached("_round_jit_cache", plan, build)

    @property
    def _round_jit(self):
        return self._round_jit_for(None)

    def gossip_plan(self, A: np.ndarray):
        """``(plan, plan_arrays)`` for this round's adjacency (unit
        weights: the consensus normalizes by mask-overlap counts
        afterwards): circulant Plan tuple, SparseSpec + routing arrays
        (the reference's forced ``cs=random`` draw, dispfl_api.py:200),
        or (None, {}) for the dense einsum."""
        return make_plan(A, self.mesh, self.num_clients)

    # ---------- streamed round (data per chunk, state resident) ----------

    def _consensus_jit_for(self, plan):
        # donation: per_params/per_bstats only — the streamed round
        # rereads masks_local and masks_shared AFTER the consensus (chunk
        # training + the round tail), so the mask stacks must survive
        return self._plan_cached(
            "_consensus_jit_cache", plan,
            lambda: jax.jit(functools.partial(self._consensus, plan=plan),
                            donate_argnums=self._donate_argnums(0, 1)))

    @property
    def _consensus_jit(self):
        return self._consensus_jit_for(None)

    @functools.cached_property
    def _local_chunk_jit(self):
        # consumes gathered per-chunk copies (fresh each chunk)
        return jax.jit(self._local_and_evolve,
                       donate_argnums=self._donate_argnums(0, 1, 2))

    @functools.cached_property
    def _round_tail_jit(self):
        def tail(masks_shared, masks_local, losses, n_train):
            dist_self = jax.vmap(M.mask_hamming_distance)(masks_shared,
                                                          masks_local)
            real = (n_train > 0).astype(jnp.float32)
            mean_loss = jnp.sum(losses * real) / jnp.maximum(jnp.sum(real),
                                                             1.0)
            return dist_self, mean_loss

        return jax.jit(tail)

    def _round_streaming(self, per_params, per_bstats, masks_local,
                         masks_shared, A, rngs, lr, round_idx, plan=None,
                         plan_arrays=None):
        """Chunked streamed round: consensus on resident state, then each
        client chunk's data is host-fetched, trained, and evolved; chunk
        outputs concatenate back into the stacked [C, ...] state."""
        w_local, b_mixed = self._consensus_jit_for(plan)(
            per_params, per_bstats, masks_local, masks_shared, A,
            plan_arrays or {})
        (new_p, new_b, new_masks), losses = self.stream_map_train_chunks(
            self._local_chunk_jit, (w_local, b_mixed, masks_local), rngs,
            lr, round_idx)
        dist_self, mean_loss = self._round_tail_jit(
            masks_shared, masks_local, losses,
            jnp.asarray(self._n_train_host))
        return new_p, new_b, new_masks, masks_local, dist_self, mean_loss

    @functools.cached_property
    def _pairwise_hamming_jit(self):
        def pairwise(masks):
            def row(mc):
                return jax.vmap(lambda mo: M.mask_hamming_distance(mc, mo))(
                    masks)
            return jax.vmap(row)(masks)

        return jax.jit(pairwise)

    # ---------- training loop ----------

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        masks_local, w_spa = self.init_masks_all(gs.params)
        per = self.broadcast_states(
            ClientState(params=gs.params, batch_stats=gs.batch_stats,
                        opt_state=None, rng=None), self.num_clients)
        # initial personal models are the masked global init
        # (dispfl_api.py:78-82)
        per_params = jax.tree.map(jnp.multiply, per.params, masks_local)
        per_bstats = per.batch_stats
        # independent buffers, NOT an alias: both mask generations ride
        # DONATED argument positions of the round program (ISSUE 4), and
        # donating one buffer twice is a runtime error ("attempt to
        # donate the same buffer twice"); every later round returns
        # distinct stacks, so only this init needs the copy
        masks_shared = jax.tree.map(jnp.copy, masks_local)

        # accounting: per-layer nnz is invariant under fire+regrow, so
        # per-client comm/flops factors are fixed at init
        n_dense_extra = pt.tree_size(gs.params) - sum(
            int(p.size) for p in self._maskable_leaves(gs.params))
        nnz_per_client = np.asarray(jax.device_get(jax.vmap(
            lambda m: sum(jnp.sum(x) for x in self._maskable_leaves(m)))(
                masks_local)))
        comm_per_client = nnz_per_client + n_dense_extra  # downlink; x2 for up
        # analytic training flops (the reference zeroes these counters,
        # client.py:103-105; we count honestly): sparse local epochs + the
        # dense one-batch screen probe per round
        sample = self.trainer._prep(self.sample_input())
        full_flops = flops_ops.count_training_flops_per_sample(
            self.trainer.model, gs.params, sample,
            batch_stats=gs.batch_stats)
        dist = "uniform" if cfg.sparsity.uniform else "ERK"
        flops_by_dr = {}
        for dr in sorted(set(w_spa)):
            sp = M.calculate_sparsities(
                gs.params, dist, dense_ratio=dr,
                erk_power_scale=cfg.sparsity.erk_power_scale)
            flops_by_dr[dr] = flops_ops.count_training_flops_per_sample(
                self.trainer.model, gs.params, sample,
                mask_density={k: 1.0 - v for k, v in sp.items()},
                batch_stats=gs.batch_stats)
        n_train = self._n_train_host
        flops_per_round = sum(
            cfg.optim.epochs * float(n_train[c]) * flops_by_dr[w_spa[c]]
            + cfg.optim.batch_size * full_flops
            for c in range(self.real_clients))

        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            masks_local, masks_shared = (restored["masks_local"],
                                         restored["masks_shared"])
            history = restored["history"]
        for round_idx in range(start, cfg.fed.comm_round):
            active = self.active_draw(round_idx)
            A_np = self.adjacency(round_idx, active)
            plan, plan_arrays = self.gossip_plan(A_np)
            A = jnp.asarray(A_np)
            rngs = self.per_client_rngs(round_idx,
                                        np.arange(self.num_clients))
            self.log.info(
                "################ round %d: active %s", round_idx,
                np.flatnonzero(active[: self.real_clients]).tolist())
            if self.stream is not None:
                (per_params, per_bstats, masks_local, masks_shared,
                 dist_self, loss) = self._round_streaming(
                    per_params, per_bstats, masks_local, masks_shared,
                    A, rngs, self.round_lr(round_idx),
                    jnp.float32(round_idx), plan=plan,
                    plan_arrays=plan_arrays)
            else:
                (per_params, per_bstats, masks_local, masks_shared,
                 dist_self, loss) = self._round_jit_for(plan)(
                    per_params, per_bstats, masks_local, masks_shared,
                    self.data, A, rngs, self.round_lr(round_idx),
                    jnp.float32(round_idx), plan_arrays)
            self._masks_local = masks_local
            if not cfg.sparsity.static:
                # NaN-poisoned-mask diagnosability (ADVICE r5): surface
                # an all-False evolved mask immediately instead of
                # letting it silently zero this client's comm volume and
                # consensus contribution from here on
                self.warn_if_masks_collapsed(masks_local, round_idx)
            real = self.real_clients
            # comm = actual gossip edges: client c receives each neighbor
            # j != c's sparse model (nnz of j's mask + dense leaves)
            A_off = np.asarray(jax.device_get(A))[:real, :real].copy()
            np.fill_diagonal(A_off, 0.0)
            self.stat_info["sum_comm_params"] += float(
                (A_off @ comm_per_client[:real]).sum())
            self.stat_info["sum_training_flops"] += flops_per_round
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                mp = self._eval_p(per_params, per_bstats)
                self.stat_info["person_test_acc"].append(mp["acc"])
                self.log.metrics(
                    round_idx, train_loss=loss, personal=mp,
                    mask_change=float(np.sum(np.asarray(dist_self)[:real])))
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "personal_acc": mp["acc"],
                                "mask_change": float(
                                    np.sum(np.asarray(dist_self)[:real]))})
            self.maybe_checkpoint(round_idx, {
                "per_params": per_params, "per_bstats": per_bstats,
                "masks_local": masks_local, "masks_shared": masks_shared,
                "history": history})

        dist_matrix = np.asarray(jax.device_get(
            self._pairwise_hamming_jit(masks_local)))[: self.real_clients,
                                                      : self.real_clients]
        self.stat_info["mask_dis_matrix"] = dist_matrix.tolist()
        if cfg.sparsity.save_masks:
            self.stat_info["final_masks"] = jax.tree.map(
                lambda m: np.asarray(m, bool), masks_local)
        m_person = self._eval_p(per_params, per_bstats)
        self.log.metrics(-1, personal=m_person)
        return {"personal_params": per_params, "masks": masks_local,
                "w_spa": w_spa, "history": history,
                "mask_dis_matrix": dist_matrix,
                "final_personal": m_person}

    # ---------- helpers ----------

    @staticmethod
    def _maskable_leaves(tree):
        out = []

        def collect(name, m):
            if M.is_weight_kernel(name, m):
                out.append(m)
            return m

        pt.tree_map_with_path_names(collect, tree)
        return out
