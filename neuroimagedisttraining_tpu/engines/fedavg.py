"""FedAvg: classical federated averaging, ABCD-adapted.

Behavior parity with fedml_api/standalone/fedavg/fedavg_api.py:40-117:
per round {seeded client sampling -> per-client local SGD from the global
model -> sample-count-weighted average}, evaluation on all clients each
``frequency_of_the_test`` rounds, and a final extra fine-tune pass over all
clients after the last aggregation (fedavg_api.py:79-88).

TPU-native design: one round = ONE jitted SPMD program. Sampled clients'
data shards are gathered along the client-sharded mesh axis, local training
runs vmapped (one client per core via the mesh), and the weighted average is
a cross-shard reduction lowered to an ICI all-reduce — there is no per-client
host round-trip of state dicts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.faults import adversary
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.parallel import cohort
from neuroimagedisttraining_tpu.utils import pytree as pt


class FedAvgEngine(FederatedEngine):
    name = "fedavg"
    supports_streaming = True
    supports_wire_codec = True  # _round_body runs the codec roundtrip
    supports_byz_faults = True  # _round_body routes uploads through the
    # adversary transform when the schedule carries byz: value faults
    supports_cohort_sharding = True  # _round_body's local-train stage
    # runs under the --client_mesh shard_map (ISSUE 6)
    supports_fused_streaming = True  # the streamed driver fuses K-round
    # windows over one prefetched [K, S, ...] shard stack (ISSUE 10)
    supported_defenses = robust.DEFENSES

    def _prox_kwargs(self, global_params) -> dict:
        """Extra ``local_train`` kwargs tying the local objective to the
        round's incoming global model; FedProx overrides."""
        return {}

    def _round_body(self, params, bstats, Xs, ys, ns, rngs, lr, efs=None,
                    byz=None, n_real=None):
        """One FedAvg round over pre-gathered sampled-client shards; shared
        by the device-resident, streaming, and cohort-sharded paths.

        ``n_real`` (static) marks the cohort-sharded program (ISSUE 6):
        the incoming shards cover the MESH-PADDED sampled set (pad rows
        zero-weighted by position — cohort.pad_row_weights, since a pad
        may duplicate a real client id), the local-training stage runs
        as unbatched per-client loops under the client-mesh shard_map,
        and the trained stacks are statically sliced back to the real
        ``n_real`` rows — the attack/codec/sanitize/defense/aggregation
        tail below then executes the identical operations the sequential
        C-loop program executes (losses bitwise from identical state,
        state to ~1 ulp — the full contract in parallel/cohort.py,
        pinned in tests/test_cohort.py). ``efs``/``byz`` are always
        sized for the REAL sampled set.

        ``byz`` (faults/adversary.py plan ``(mult, std, nonfinite,
        keys)``, [C] each) transforms the scheduled clients' uploads
        into Byzantine values BEFORE the wire codec — the attacker
        controls what its silo encodes, the server defends on what it
        decodes. Every round then sanitizes: non-finite uploads are
        swapped for the broadcast reference and zero-weighted (counted
        in the ``n_bad`` output — the non-finite guard runs with or
        without a defense), and ``--defense`` dispatches through
        core/robust.py (clip family per client before the weighted mean;
        trimmed_mean/median/krum/geometric_median replace the mean over
        the whole upload payload, batch_stats included).

        With ``--wire_codec`` set, every client's trained params pass
        through the codec's jitted lossy roundtrip (delta vs the round's
        broadcast ``params``, optional top-k with the ``efs``
        error-feedback rows threaded per sampled client, int8/bf16
        quantization) BEFORE defense + aggregation — the in-sim round
        aggregates exactly what a cross-silo server would decode. The
        extra outputs are (new_efs|None, u0 = client 0's decoded upload
        for the host-side byte accounting)."""
        trainer = self.trainer
        o = self.cfg.optim
        S = Xs.shape[0]
        max_samples = self._max_samples()
        prox = self._prox_kwargs(params)
        if n_real is not None:
            ns = cohort.pad_row_weights(ns, n_real)
        cs = ClientState(
            params=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), params),
            batch_stats=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), bstats),
            opt_state=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape),
                trainer.opt.init(params)),
            rng=rngs,
        )

        def local(cs_c, Xc, yc, nc, perms_c=None):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                perms=perms_c, **prox)

        if n_real is None:
            cs, losses = jax.vmap(local)(cs, Xs, ys, ns)
        else:
            # hoisted-perms sharded loop (base._cohort_local_stage)
            cs, losses = self._cohort_local_stage(local, cs, Xs, ys, ns)
            if n_real < S:  # static slice: drop the mesh-pad rows
                cs = jax.tree.map(lambda x: x[:n_real], cs)
                losses = losses[:n_real]
                ns = ns[:n_real]
        w = ns.astype(jnp.float32)
        client_params = cs.params
        client_bstats = cs.batch_stats
        if byz is not None:
            # the attack hits the WHOLE upload payload (params + batch
            # stats — what the wire ships) before any encoding; honest
            # clients ride the plan's identity rows bitwise-untouched
            mult, std, nonfinite, keys = byz
            atk = adversary.apply_attack_stacked(
                {"params": client_params, "batch_stats": client_bstats},
                {"params": params, "batch_stats": bstats},
                mult, std, nonfinite, keys)
            client_params = atk["params"]
            client_bstats = atk["batch_stats"]
        new_efs = u0 = None
        if self.wire_spec is not None:
            from neuroimagedisttraining_tpu.codec import device as codec_dev

            spec = self.wire_spec
            # the WHOLE upload payload rides the codec — {params,
            # batch_stats}, the exact tree FedAvgClientProc encodes
            # (distributed/run.py), so with delta+sparse+quant the global
            # top-k threshold sees BN running-stat residuals competing
            # for the k slots just like the real wire, and the simulated
            # aggregate matches the socket federation's decode
            upload = {"params": client_params,
                      "batch_stats": client_bstats}
            ref = {"params": params, "batch_stats": bstats}
            if spec.needs_ef:
                dec, new_efs = jax.vmap(
                    lambda u, e: codec_dev.lossy_roundtrip(
                        spec, u, reference=ref, ef=e))(upload, efs)
                # a non-finite upload row (byz nonfinite attack, diverged
                # optimizer) would park NaN in the EF stack FOREVER —
                # EF = u - decode(u) is NaN, and every later encode
                # consumes it, so the guard would zero-weight the client
                # for the rest of the run. Zero those rows so the value
                # fault stays transient (the engine-side mirror of the
                # server's post-quarantine ARG_EF_RESET invariant).
                fin = robust.finite_per_client(upload)
                new_efs = jax.tree.map(
                    lambda e: jnp.where(
                        fin.reshape((-1,) + (1,) * (e.ndim - 1)),
                        e, jnp.zeros_like(e)), new_efs)
            else:
                dec, _ = jax.vmap(
                    lambda u: codec_dev.lossy_roundtrip(
                        spec, u, reference=ref))(upload)
            client_params = dec["params"]
            client_bstats = dec["batch_stats"]
            u0 = jax.tree.map(lambda x: x[0], dec)
        # non-finite guard + defense dispatch (base._sanitize_and_defend)
        new_params, new_bstats, mean_loss, n_bad = self._sanitize_and_defend(
            {"params": client_params, "batch_stats": client_bstats},
            {"params": params, "batch_stats": bstats}, w, losses,
            rngs=cs.rng)
        if self.wire_spec is not None:
            return new_params, new_bstats, mean_loss, n_bad, new_efs, u0
        return new_params, new_bstats, mean_loss, n_bad

    @functools.cached_property
    def _round_jit(self):
        def round_fn(params, bstats, data, sampled_idx, rngs, lr,
                     efs=None, byz=None):
            Xs = jnp.take(data.X_train, sampled_idx, axis=0)
            ys = jnp.take(data.y_train, sampled_idx, axis=0)
            ns = jnp.take(data.n_train, sampled_idx, axis=0)
            return self._round_body(params, bstats, Xs, ys, ns, rngs, lr,
                                    efs, byz)

        # donation: the incoming global {params, bstats} and the sampled
        # EF rows are consumed by the round — their buffers back the
        # round's outputs; the driver snapshots (account_wire_bytes
        # reference) BEFORE dispatch and never rereads donated args.
        # The byz plan (arg 7) is tiny and never donated.
        return jax.jit(round_fn,
                       donate_argnums=self._donate_argnums(0, 1, 6))

    def _sharded_round_jit(self, n_real: int):
        """The cohort-sharded round program (ISSUE 6): same signature and
        donation contract as ``_round_jit``, but ``sampled_idx``/``rngs``
        cover the MESH-PADDED sampled set and the body shards the local-
        training stage over the client mesh (``n_real`` static — fault-
        schedule cohort shrinkage re-specializes via the plan cache)."""
        def build():
            def sharded_round_fn(params, bstats, data, sampled_idx, rngs,
                                 lr, efs=None, byz=None):
                Xs = jnp.take(data.X_train, sampled_idx, axis=0)
                ys = jnp.take(data.y_train, sampled_idx, axis=0)
                ns = jnp.take(data.n_train, sampled_idx, axis=0)
                return self._round_body(params, bstats, Xs, ys, ns, rngs,
                                        lr, efs, byz, n_real=n_real)

            return jax.jit(sharded_round_fn,
                           donate_argnums=self._donate_argnums(0, 1, 6))

        return self._plan_cached("_sharded_round_jit_cache", n_real, build)

    @functools.cached_property
    def _round_stream_jit(self):
        return jax.jit(self._round_body,
                       donate_argnums=self._donate_argnums(0, 1))

    # ---------- fused multi-round dispatch (ISSUE 4) ----------

    def fused_fallback_reason(self) -> str | None:
        return self._resident_fallback_reason()

    def _fused_round_jit(self, k: int, n_real: int | None = None):
        """K rounds as ONE dispatched program: a ``lax.scan`` over the
        exact per-round body, consuming host-precomputed stacks of
        sampling indices / per-client rngs / round lrs. Amortizes the
        per-dispatch latency the sequential loop pays K times
        (PROFILE.md round 2: a 16-step scan sustains 2.4x the
        per-dispatch loop through the tunnel). ``n_real`` marks the
        cohort-sharded variant (ISSUE 6): the scanned per-round body
        shards its local-training stage over the client mesh, consuming
        [K, P] mesh-padded index/rng stacks."""
        def build():
            def fused_round_fn(params, bstats, data, sampled_idx, rngs,
                               lrs, byz=None):
                def one_round(carry, xs):
                    p, b = carry
                    if byz is None:
                        (si, rg, lr), bz = xs, None
                    else:
                        si, rg, lr, bz = xs
                    Xs = jnp.take(data.X_train, si, axis=0)
                    ys = jnp.take(data.y_train, si, axis=0)
                    ns = jnp.take(data.n_train, si, axis=0)
                    p, b, loss, bad = self._round_body(p, b, Xs, ys, ns,
                                                       rg, lr, byz=bz,
                                                       n_real=n_real)
                    return (p, b), (loss, bad)

                xs = ((sampled_idx, rngs, lrs) if byz is None
                      else (sampled_idx, rngs, lrs, byz))
                (params, bstats), (losses, bads) = jax.lax.scan(
                    one_round, (params, bstats), xs)
                return params, bstats, losses, bads

            return jax.jit(fused_round_fn,
                           donate_argnums=self._donate_argnums(0, 1))

        return self._plan_cached("_fused_round_jit_cache", (k, n_real),
                                 build)

    def _run_fused_window(self, params, bstats, round_idx: int, k: int):
        """Dispatch rounds ``[round_idx, round_idx + k)`` as one scan.
        Sampling/rng/lr — and the Byzantine attack plan when the fault
        schedule carries value faults — are precomputed on the host
        round by round (the ``np.random.seed(round_idx)`` contract is
        untouched). Returns ``(params, bstats, last_round_loss,
        k_actual)`` — ``k_actual`` may shrink when the fault schedule
        varies the cohort size."""
        # the window IS a host boundary pair (ISSUE 9): the prologue and
        # the dispatch are separate host spans — "dispatch" measures the
        # enqueue only (async dispatch races ahead; the sync lands at
        # the next eval/flush boundary, never here)
        with obs_trace.span("window", round=round_idx, k=k):
            with obs_trace.span("window_host_prologue", round=round_idx):
                (_, idx, rngs, lrs, byz, k,
                 n_real) = self._window_host_inputs(round_idx, k)
            with obs_trace.span("dispatch", round=round_idx, k=k):
                params, bstats, losses, bads = self._fused_round_jit(
                    k, n_real)(params, bstats, self.data, idx, rngs,
                               lrs, byz)
        self._note_nonfinite(bads)
        return params, bstats, losses[-1], k

    def _fused_round_stream_jit(self, k: int):
        """K STREAMED rounds as one dispatched program (ISSUE 10): a
        ``lax.scan`` over the exact streamed per-round body, consuming
        the window's prefetched ``[K, S, nmax, ...]`` shard stacks one
        round per step — the window-granular analog of
        ``_fused_round_jit`` for cohorts that live on the host. The
        carried {params, bstats} are donated like every round program's;
        the uint8/int32 shard stacks are NOT — no output shares their
        dtype/shape, so the donation would be unusable (XLA warns and
        ignores it) and the buffers die at end of dispatch anyway."""
        def build():
            def fused_stream_fn(params, bstats, Xs, ys, ns, rngs, lrs,
                                byz=None):
                def one_round(carry, xs):
                    p, b = carry
                    if byz is None:
                        (X, y, n, rg, lr), bz = xs, None
                    else:
                        X, y, n, rg, lr, bz = xs
                    p, b, loss, bad = self._round_body(p, b, X, y, n, rg,
                                                       lr, byz=bz)
                    return (p, b), (loss, bad)

                xs = ((Xs, ys, ns, rngs, lrs) if byz is None
                      else (Xs, ys, ns, rngs, lrs, byz))
                (params, bstats), (losses, bads) = jax.lax.scan(
                    one_round, (params, bstats), xs)
                return params, bstats, losses, bads

            return jax.jit(fused_stream_fn,
                           donate_argnums=self._donate_argnums(0, 1))

        return self._plan_cached("_fused_round_stream_jit_cache", k, build)

    def _stream_prefetch_for(self, round_idx: int) -> None:
        """Kick off the streamed feed for whatever the driver will
        dispatch AT ``round_idx``: the whole fused window's shard stack
        when the fused streamed driver is armed and the window planner
        gives more than one round, the single round's shards otherwise.
        The key-matching get (``get_window``/``get_train``) re-derives
        the identical ids — sampling is deterministic in the round
        index — so a planner disagreement degrades to a fresh fetch,
        never a stale serve."""
        if round_idx >= self.cfg.fed.comm_round:
            return
        fuse = (self.cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        if fuse:
            k = self._dispatch_window(round_idx)
            if k > 1:
                sampled, k = self._window_sampling(round_idx, k)
                pads = [self.stream_sampling(round_idx + off, sampled=s)
                        for off, s in enumerate(sampled)]
                self.stream.prefetch_window([p[0] for p in pads],
                                            pads[0][1])
                return
        self.stream.prefetch_train(*self.stream_sampling(round_idx))

    def _run_fused_stream_window(self, params, bstats, round_idx: int,
                                 k: int):
        """Dispatch streamed rounds ``[round_idx, round_idx + k)`` as one
        scan over the prefetched window stack, then immediately queue the
        NEXT window's host read + device transfer behind this window's
        compute (the dispatch returns asynchronously; the boundary hooks
        block later). Returns ``(params, bstats, last_round_loss,
        k_actual)``."""
        with obs_trace.span("window", round=round_idx, k=k, stream=True):
            with obs_trace.span("window_host_prologue", round=round_idx):
                (ids_per_round, rngs, lrs, byz, k,
                 n_real) = self._window_stream_inputs(round_idx, k)
                Xs, ys, ns = self.stream.get_window(ids_per_round, n_real)
                self._stream_prefetch_for(round_idx + k)
            with obs_trace.span("dispatch", round=round_idx, k=k):
                params, bstats, losses, bads = self._fused_round_stream_jit(
                    k)(params, bstats, Xs, ys, ns, rngs, lrs, byz)
        self._note_nonfinite(bads)
        return params, bstats, losses[-1], k

    def _finetune_body(self, params, bstats, X, y, n, rngs, lr):
        """Per-client fine-tune from the aggregated model over a block of
        clients (fedavg_api.py:79-88) — produces personalized models."""
        trainer = self.trainer
        o = self.cfg.optim
        C = X.shape[0]
        max_samples = self._max_samples()
        cs = ClientState(
            params=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (C,) + x.shape), params),
            batch_stats=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (C,) + x.shape), bstats),
            opt_state=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (C,) + x.shape),
                trainer.opt.init(params)),
            rng=rngs,
        )

        def local(cs_c, Xc, yc, nc, perms_c=None):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                perms=perms_c)

        # the final fine-tune trains EVERY client — the heaviest single
        # program of the run — so it rides the cohort-sharded mesh too
        # when armed (the full cohort already tiles the mesh: the data
        # layer pads num_clients to a device multiple; permutations
        # hoisted out of the shard_map like the round's —
        # base._cohort_local_stage)
        if self._cohort_on and C % self.mesh.devices.size == 0:
            cs, _ = self._cohort_local_stage(local, cs, X, y, n)
        else:
            cs, _ = jax.vmap(local)(cs, X, y, n)
        return cs

    @functools.cached_property
    def _finetune_jit(self):
        def ft(params, bstats, data, rngs, lr):
            return self._finetune_body(params, bstats, data.X_train,
                                       data.y_train, data.n_train, rngs, lr)

        return jax.jit(ft)

    @functools.cached_property
    def _finetune_stream_jit(self):
        return jax.jit(self._finetune_body)

    def train(self):
        if self.stream is not None:
            return self._train_streaming()
        cfg = self.cfg
        start, restored = self.restore_checkpoint()
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            history = restored["history"]
        else:
            gs = self.init_global_state()
            params, bstats = gs.params, gs.batch_stats
            history = []
        codec_on = self.wire_spec is not None
        if codec_on and self.wire_spec.needs_ef:
            # per-client error-feedback accumulators over the FULL upload
            # payload (params + batch_stats — what the wire encodes),
            # threaded across rounds: rows for the sampled set ride into
            # the jitted round and the updated rows scatter back (pads
            # dropped)
            self._wire_ef = jax.tree.map(
                lambda x: jnp.zeros((self.num_clients,) + x.shape,
                                    jnp.float32),
                {"params": params, "batch_stats": bstats})
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                params, bstats, loss, k = self._run_fused_window(
                    params, bstats, round_idx, k)
                round_idx += k - 1  # hooks below fire for the boundary
            else:
                sampled = self.client_sampling(round_idx)
                self.log.info("################ round %d: clients %s",
                              round_idx, sampled.tolist())
                # cohort sharding (ISSUE 6): the sharded program gathers
                # the mesh-padded set (and takes rngs for it); the EF
                # rows, byz plan, and byte accounting stay on the REAL
                # sampled set — the body slices pads off before that tail
                ids, round_prog = self._cohort_round_prog(sampled)
                rngs = self.per_client_rngs(round_idx, ids)
                byz = self._byz_round_plan(round_idx, sampled)
                if codec_on:
                    # downlink reference snapshot BEFORE dispatch: the
                    # round donates {params, bstats} and the sampled EF
                    # rows, so nothing may read them after the call
                    ref_host = jax.tree.map(np.asarray,
                                            {"params": params,
                                             "batch_stats": bstats})
                    efs = (pt.tree_stack_index(self._wire_ef,
                                               np.asarray(sampled))
                           if self.wire_spec.needs_ef else None)
                    with obs_trace.span("round", round=round_idx,
                                        codec=True):
                        (params, bstats, loss, n_bad, new_efs,
                         u0) = round_prog(
                            params, bstats, self.data, jnp.asarray(ids),
                            rngs, self.round_lr(round_idx), efs, byz)
                    if new_efs is not None:
                        real = jnp.asarray(self._n_train_host[sampled] > 0)
                        self._wire_ef = self.scatter_sampled_rows(
                            self._wire_ef, new_efs, jnp.asarray(sampled),
                            real)
                    self.account_wire_bytes(jax.tree.map(np.asarray, u0),
                                            ref_host, None, len(sampled))
                elif byz is not None:
                    # byz plans only reach engines whose round accepts
                    # them (supports_byz_faults gates at startup); efs
                    # rides its default None
                    with obs_trace.span("round", round=round_idx):
                        params, bstats, loss, n_bad = round_prog(
                            params, bstats, self.data, jnp.asarray(ids),
                            rngs, self.round_lr(round_idx), None, byz)
                else:
                    # efs/byz stay default-bound (None): subclasses
                    # override _round_jit with efs-free signatures
                    # (turboaggregate), and an argument filled from its
                    # default is never donated, so no explicit None is
                    # needed here
                    with obs_trace.span("round", round=round_idx):
                        params, bstats, loss, n_bad = round_prog(
                            params, bstats, self.data, jnp.asarray(ids),
                            rngs, self.round_lr(round_idx))
                self._note_nonfinite(n_bad)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self.eval_global(params, bstats)
                self._flush_nonfinite(round_idx)
                self.stat_info["global_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss, **m)
                history.append({"round": round_idx, "train_loss": float(loss),
                                **m})
            self.maybe_checkpoint(round_idx, {
                "params": params, "batch_stats": bstats, "history": history})
            round_idx += 1
        self._flush_nonfinite(cfg.fed.comm_round - 1)
        # final fine-tune pass -> personalized models + final eval at "-1"
        rngs = self.per_client_rngs(cfg.fed.comm_round,
                                    np.arange(self.num_clients))
        # reference passes round=-1 for this pass (fedavg_api.py:85), so the
        # fine-tune lr is lr * decay^-1, not the decayed end-of-training lr
        per_states = self._finetune_jit(params, bstats, self.data, rngs,
                                        self.round_lr(-1))
        m_global = self.eval_global(params, bstats)
        m_person = self.eval_personalized(per_states)
        self.stat_info["person_test_acc"].append(m_person["acc"])
        self.log.metrics(-1, global_=m_global, personal=m_person)
        return {"params": params, "batch_stats": bstats,
                "personal": per_states, "history": history,
                "final_global": m_global, "final_personal": m_person}

    # ---------- streaming mode (cohort > HBM) ----------

    def _train_streaming(self):
        """Same round loop, but only the sampled clients' shards live on
        device each round (double-buffered host reads), and evaluation +
        the final fine-tune pass stream the cohort in client chunks."""
        cfg = self.cfg
        start, restored = self.restore_checkpoint()
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            history = restored["history"]
        else:
            gs = self.init_global_state()
            params, bstats = gs.params, gs.batch_stats
            history = []
        # fused streamed windows (ISSUE 10): when the window planner can
        # fuse, whole K-round shard stacks are prefetched behind the
        # previous window's scan; hook rounds land on window boundaries
        # exactly as in the resident fused driver, so observable
        # behavior matches the round-granular loop
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        self._stream_prefetch_for(start)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                params, bstats, loss, k = self._run_fused_stream_window(
                    params, bstats, round_idx, k)
                round_idx += k - 1  # hooks below fire for the boundary
            else:
                fed_ids, n_real = self.stream_sampling(round_idx)
                self.log.info("################ round %d (stream): "
                              "clients %s", round_idx,
                              fed_ids[:n_real].tolist())
                Xs, ys, ns = self.stream.get_train(fed_ids, n_real)
                # overlap the next dispatch's host read (single round or
                # whole window) with this round's compute
                self._stream_prefetch_for(round_idx + 1)
                rngs = self.per_client_rngs(round_idx, fed_ids)
                byz = self._byz_round_plan(round_idx, fed_ids)
                if byz is not None:
                    params, bstats, loss, n_bad = self._round_stream_jit(
                        params, bstats, Xs, ys, ns, rngs,
                        self.round_lr(round_idx), None, byz)
                else:
                    params, bstats, loss, n_bad = self._round_stream_jit(
                        params, bstats, Xs, ys, ns, rngs,
                        self.round_lr(round_idx))
                self._note_nonfinite(n_bad)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self.eval_global_stream(params, bstats)
                self._flush_nonfinite(round_idx)
                self.stat_info["global_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss, **m)
                history.append({"round": round_idx,
                                "train_loss": float(loss), **m})
            self.maybe_checkpoint(round_idx, {
                "params": params, "batch_stats": bstats, "history": history})
            round_idx += 1
        self._flush_nonfinite(cfg.fed.comm_round - 1)
        # final fine-tune: chunked over client blocks; personalized models
        # are evaluated per block then discarded (they'd exceed HBM)
        chunk = self._eval_chunk_size()
        ft_lr = self.round_lr(-1)
        per_parts, per_ns = [], []
        test_iter = self.stream.eval_chunks(chunk, "test")
        for ch in self.stream.eval_chunks(chunk, "train"):
            if self.cfg.fed.ci and per_parts:
                break  # CI escape hatch: first chunk only
            rngs = self.per_client_rngs(cfg.fed.comm_round, ch.padded_ids)
            states = self._finetune_stream_jit(params, bstats, ch.X, ch.y,
                                               ch.n, rngs, ft_lr)
            che = next(test_iter)
            assert np.array_equal(ch.ids, che.ids)
            out = self._eval_personal_jit(states.params, states.batch_stats,
                                          che.X, che.y, che.n)
            per_parts.append(tuple(np.asarray(o)[: len(ch.ids)]
                                   for o in out))
            per_ns.append(np.asarray(jax.device_get(che.n))[: len(ch.ids)])
        cat = [np.concatenate([p[i] for p in per_parts]) for i in range(4)]
        n_cat = np.concatenate(per_ns)
        if self.cfg.fed.ci:  # client 0 only, matching the resident CI path
            cat, n_cat = [c[:1] for c in cat], n_cat[:1]
        m_person = self._summarize(*cat, n=n_cat)
        m_global = self.eval_global_stream(params, bstats)
        self.stat_info["person_test_acc"].append(m_person["acc"])
        self.log.metrics(-1, global_=m_global, personal=m_person)
        return {"params": params, "batch_stats": bstats,
                "personal": None, "history": history,
                "final_global": m_global, "final_personal": m_person}
