"""FedAvg: classical federated averaging, ABCD-adapted.

Behavior parity with fedml_api/standalone/fedavg/fedavg_api.py:40-117:
per round {seeded client sampling -> per-client local SGD from the global
model -> sample-count-weighted average}, evaluation on all clients each
``frequency_of_the_test`` rounds, and a final extra fine-tune pass over all
clients after the last aggregation (fedavg_api.py:79-88).

TPU-native design: one round = ONE jitted SPMD program. Sampled clients'
data shards are gathered along the client-sharded mesh axis, local training
runs vmapped (one client per core via the mesh), and the weighted average is
a cross-shard reduction lowered to an ICI all-reduce — there is no per-client
host round-trip of state dicts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.utils import pytree as pt


class FedAvgEngine(FederatedEngine):
    name = "fedavg"

    @functools.cached_property
    def _round_jit(self):
        trainer = self.trainer
        o = self.cfg.optim
        S = min(self.cfg.fed.client_num_per_round, self.real_clients)
        max_samples = int(self.data.X_train.shape[1])

        def round_fn(params, bstats, data, sampled_idx, rngs, lr):
            Xs = jnp.take(data.X_train, sampled_idx, axis=0)
            ys = jnp.take(data.y_train, sampled_idx, axis=0)
            ns = jnp.take(data.n_train, sampled_idx, axis=0)
            cs = ClientState(
                params=jax.tree.map(
                    lambda x: jnp.broadcast_to(x, (S,) + x.shape), params),
                batch_stats=jax.tree.map(
                    lambda x: jnp.broadcast_to(x, (S,) + x.shape), bstats),
                opt_state=jax.tree.map(
                    lambda x: jnp.broadcast_to(x, (S,) + x.shape),
                    trainer.opt.init(params)),
                rng=rngs,
            )

            def local(cs_c, Xc, yc, nc):
                return trainer.local_train(
                    cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                    batch_size=o.batch_size, max_samples=max_samples)

            cs, losses = jax.vmap(local)(cs, Xs, ys, ns)
            w = ns.astype(jnp.float32)
            new_params = pt.tree_weighted_mean(cs.params, w)
            new_bstats = pt.tree_weighted_mean(cs.batch_stats, w)
            mean_loss = jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1e-9)
            return new_params, new_bstats, mean_loss

        return jax.jit(round_fn)

    @functools.cached_property
    def _finetune_jit(self):
        """Final per-client fine-tune from the aggregated model
        (fedavg_api.py:79-88) — produces the personalized models."""
        trainer = self.trainer
        o = self.cfg.optim
        C = self.num_clients
        max_samples = int(self.data.X_train.shape[1])

        def ft(params, bstats, data, rngs, lr):
            cs = ClientState(
                params=jax.tree.map(
                    lambda x: jnp.broadcast_to(x, (C,) + x.shape), params),
                batch_stats=jax.tree.map(
                    lambda x: jnp.broadcast_to(x, (C,) + x.shape), bstats),
                opt_state=jax.tree.map(
                    lambda x: jnp.broadcast_to(x, (C,) + x.shape),
                    trainer.opt.init(params)),
                rng=rngs,
            )

            def local(cs_c, Xc, yc, nc):
                return trainer.local_train(
                    cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                    batch_size=o.batch_size, max_samples=max_samples)

            cs, _ = jax.vmap(local)(cs, data.X_train, data.y_train,
                                    data.n_train)
            return cs

        return jax.jit(ft)

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        params, bstats = gs.params, gs.batch_stats
        history = []
        for round_idx in range(cfg.fed.comm_round):
            sampled = self.client_sampling(round_idx)
            self.log.info("################ round %d: clients %s",
                          round_idx, sampled.tolist())
            rngs = self.per_client_rngs(round_idx, sampled)
            params, bstats, loss = self._round_jit(
                params, bstats, self.data, jnp.asarray(sampled), rngs,
                self.round_lr(round_idx))
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self.eval_global(params, bstats)
                self.stat_info["global_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss, **m)
                history.append({"round": round_idx, "train_loss": float(loss),
                                **m})
        # final fine-tune pass -> personalized models + final eval at "-1"
        rngs = self.per_client_rngs(cfg.fed.comm_round,
                                    np.arange(self.num_clients))
        # reference passes round=-1 for this pass (fedavg_api.py:85), so the
        # fine-tune lr is lr * decay^-1, not the decayed end-of-training lr
        per_states = self._finetune_jit(params, bstats, self.data, rngs,
                                        self.round_lr(-1))
        m_global = self.eval_global(params, bstats)
        m_person = self.eval_personalized(per_states)
        self.stat_info["person_test_acc"].append(m_person["acc"])
        self.log.metrics(-1, global_=m_global, personal=m_person)
        return {"params": params, "batch_stats": bstats,
                "personal": per_states, "history": history,
                "final_global": m_global, "final_personal": m_person}
