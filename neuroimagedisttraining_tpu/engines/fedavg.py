"""FedAvg: classical federated averaging, ABCD-adapted.

Behavior parity with fedml_api/standalone/fedavg/fedavg_api.py:40-117:
per round {seeded client sampling -> per-client local SGD from the global
model -> sample-count-weighted average}, evaluation on all clients each
``frequency_of_the_test`` rounds, and a final extra fine-tune pass over all
clients after the last aggregation (fedavg_api.py:79-88).

TPU-native design: one round = ONE jitted SPMD program. Sampled clients'
data shards are gathered along the client-sharded mesh axis, local training
runs vmapped (one client per core via the mesh), and the weighted average is
a cross-shard reduction lowered to an ICI all-reduce — there is no per-client
host round-trip of state dicts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.utils import pytree as pt


class FedAvgEngine(FederatedEngine):
    name = "fedavg"
    supports_streaming = True
    supports_wire_codec = True  # the declared round runs the codec
    # roundtrip (builder codec stage, engines/program.py)
    supports_secure_quant = True  # the declared round routes the
    # builder's default aggregate tail, which --secure_quant swaps for
    # the jitted GF(p) fold (program.secure_quant_aggregate)
    supports_byz_faults = True  # uploads route through the builder's
    # attack stage when the schedule carries byz: value faults
    supports_cohort_sharding = True  # the declared local-train stage
    # runs under the --client_mesh shard_map (ISSUE 6)
    supports_fused_streaming = True  # the streamed driver fuses K-round
    # windows over one prefetched [K, S, ...] shard stack (ISSUE 10)
    supported_defenses = robust.DEFENSES

    def _prox_kwargs(self, global_params) -> dict:
        """Extra ``local_train`` kwargs tying the local objective to the
        round's incoming global model; FedProx overrides."""
        return {}

    # ---------- the declared round (engines/program.py) ----------

    def round_stages(self):
        """FedAvg is the builder's simplest declaration: carry the
        global model, train the sampled cohort, and let the builder run
        the attack -> codec (with EF) -> sanitize -> defend -> aggregate
        tail. The compiled programs are bitwise-equal to the pre-builder
        hand-written paths (tests/test_dispatch.py, test_cohort.py)."""
        return round_program.RoundStages(
            carry=("params", "batch_stats"),
            train=self._train_stage,
            uses_ef=True,
            supports_attack=True,
        )

    def _train_stage(self, ctx) -> round_program.TrainOut:
        """Local-train stage: broadcast the round's incoming global model
        over the cohort and run each client's local SGD — vmapped, or as
        unbatched per-client loops under the client mesh when the program
        was built sharded (ctx.client_map; epoch permutations hoisted out
        of the partition — parallel/cohort.py)."""
        trainer = self.trainer
        o = self.cfg.optim
        params = ctx.carry["params"]
        bstats = ctx.carry["batch_stats"]
        Xs, ys, ns = ctx.Xs, ctx.ys, ctx.ns
        lr = ctx.lr
        S = Xs.shape[0]
        max_samples = self._max_samples()
        prox = self._prox_kwargs(params)
        cs = ClientState(
            params=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), params),
            batch_stats=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), bstats),
            opt_state=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape),
                trainer.opt.init(params)),
            rng=ctx.rngs,
        )

        def local(cs_c, Xc, yc, nc, perms_c=None):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                perms=perms_c, **prox)

        cs, losses = ctx.client_map(
            local, cs, Xs, ys, ns,
            hoisted=(lambda: ctx.local_perms(ctx.rngs, ns, o.epochs),))
        return round_program.TrainOut(
            losses=losses,
            upload={"params": cs.params, "batch_stats": cs.batch_stats},
            state=cs)

    # ---------- legacy-signature program adapters ----------
    # The builder's compiled programs take structured (carry, data,
    # consts, ...) arguments; these adapters keep the historic per-engine
    # call shapes the drivers and the bitwise-parity tests use.

    @functools.cached_property
    def _round_jit(self):
        prog = self.program.round_jit()

        def round_call(params, bstats, data, sampled_idx, rngs, lr,
                       efs=None, byz=None):
            return prog((params, bstats), data, (), sampled_idx, rngs,
                        lr, efs, byz)

        def lower(params, bstats, data, sampled_idx, rngs, lr,
                  efs=None, byz=None):
            # legacy-signature .lower passthrough (compile pins)
            return prog.jit.lower((params, bstats), data, (),
                                  sampled_idx, rngs, lr, efs, byz)

        round_call.jit = prog.jit
        round_call.lower = lower
        return round_call

    def _sharded_round_jit(self, n_real: int):
        """The cohort-sharded round program (ISSUE 6): same signature and
        donation contract as ``_round_jit``, but ``sampled_idx``/``rngs``
        cover the MESH-PADDED sampled set and the builder shards the
        local-training stage over the client mesh (``n_real`` static —
        fault-schedule cohort shrinkage re-specializes via the plan
        cache)."""
        prog = self.program.round_jit(n_real=n_real)

        def sharded_round_call(params, bstats, data, sampled_idx, rngs,
                               lr, efs=None, byz=None):
            return prog((params, bstats), data, (), sampled_idx, rngs,
                        lr, efs, byz)

        return sharded_round_call

    @functools.cached_property
    def _round_stream_jit(self):
        prog = self.program.stream_jit()

        def stream_round_call(params, bstats, Xs, ys, ns, rngs, lr,
                              efs=None, byz=None):
            return prog((params, bstats), (), Xs, ys, ns, None, rngs,
                        lr, efs, byz)

        return stream_round_call

    # ---------- fused multi-round dispatch (ISSUE 4) ----------

    def _run_fused_window(self, params, bstats, round_idx: int, k: int):
        """Dispatch rounds ``[round_idx, round_idx + k)`` as one scan
        (program.run_window: host prologue + ONE compiled program).
        Returns ``(params, bstats, last_round_loss, k_actual)`` —
        ``k_actual`` may shrink when the fault schedule varies the
        cohort size."""
        (params, bstats), _, outs, wi = self.program.run_window(
            (params, bstats), round_idx, k)
        return params, bstats, outs["loss"][-1], wi.k

    def _stream_prefetch_for(self, round_idx: int) -> None:
        """Kick off the streamed feed for whatever the driver will
        dispatch AT ``round_idx``: the whole fused window's shard stack
        when the fused streamed driver is armed and the window planner
        gives more than one round, the single round's shards otherwise.
        The key-matching get (``get_window``/``get_train``) re-derives
        the identical ids — sampling is deterministic in the round
        index — so a planner disagreement degrades to a fresh fetch,
        never a stale serve."""
        if round_idx >= self.cfg.fed.comm_round:
            return
        fuse = (self.cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        if fuse:
            k = self._dispatch_window(round_idx)
            if k > 1:
                sampled, k = self.program.window_sampling(round_idx, k)
                pads = [self.stream_sampling(round_idx + off, sampled=s)
                        for off, s in enumerate(sampled)]
                self.stream.prefetch_window([p[0] for p in pads],
                                            pads[0][1])
                return
        self.stream.prefetch_train(*self.stream_sampling(round_idx))

    def _run_fused_stream_window(self, params, bstats, round_idx: int,
                                 k: int):
        """Dispatch streamed rounds ``[round_idx, round_idx + k)`` as one
        scan over the prefetched window stack (ISSUE 10), then
        immediately queue the NEXT window's host read + device transfer
        behind this window's compute (the dispatch returns
        asynchronously; the boundary hooks block later). Returns
        ``(params, bstats, last_round_loss, k_actual)``."""
        with obs_trace.span("window", round=round_idx, k=k, stream=True):
            with obs_trace.span("window_host_prologue", round=round_idx):
                (ids_per_round, rngs, lrs, byz, k,
                 n_real) = self.program.stream_window_inputs(round_idx, k)
                Xs, ys, ns = self.stream.get_window(ids_per_round, n_real)
                self._stream_prefetch_for(round_idx + k)
            with obs_trace.span("dispatch", round=round_idx, k=k):
                params, bstats, losses, bads = self.program.fused_stream_jit(
                    k)((params, bstats), (), Xs, ys, ns, rngs, lrs, byz)
        self._note_nonfinite(bads)
        return params, bstats, losses[-1], k

    def _finetune_body(self, params, bstats, X, y, n, rngs, lr):
        """Per-client fine-tune from the aggregated model over a block of
        clients (fedavg_api.py:79-88) — produces personalized models."""
        trainer = self.trainer
        o = self.cfg.optim
        C = X.shape[0]
        max_samples = self._max_samples()
        cs = ClientState(
            params=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (C,) + x.shape), params),
            batch_stats=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (C,) + x.shape), bstats),
            opt_state=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (C,) + x.shape),
                trainer.opt.init(params)),
            rng=rngs,
        )

        def local(cs_c, Xc, yc, nc, perms_c=None):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                perms=perms_c)

        # the final fine-tune trains EVERY client — the heaviest single
        # program of the run — so it rides the cohort-sharded mesh too
        # when armed (the full cohort already tiles the mesh: the data
        # layer pads num_clients to a device multiple; permutations
        # hoisted out of the shard_map like the round's —
        # program.cohort_local_stage)
        if self._cohort_on and C % self.mesh.devices.size == 0:
            cs, _ = round_program.cohort_local_stage(self, local, cs,
                                                     X, y, n)
        else:
            cs, _ = jax.vmap(local)(cs, X, y, n)
        return cs

    @functools.cached_property
    def _finetune_jit(self):
        def ft(params, bstats, data, rngs, lr):
            return self._finetune_body(params, bstats, data.X_train,
                                       data.y_train, data.n_train, rngs, lr)

        return jax.jit(ft)

    @functools.cached_property
    def _finetune_stream_jit(self):
        return jax.jit(self._finetune_body)

    def train(self):
        if self.stream is not None:
            return self._train_streaming()
        cfg = self.cfg
        self._register_reflexes()
        start, restored = self.restore_checkpoint()
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            history = restored["history"]
        else:
            gs = self.init_global_state()
            params, bstats = gs.params, gs.batch_stats
            history = []
        codec_on = self.wire_spec is not None
        if codec_on and self.wire_spec.needs_ef:
            # per-client error-feedback accumulators over the FULL upload
            # payload (params + batch_stats — what the wire encodes),
            # threaded across rounds: rows for the sampled set ride into
            # the jitted round and the updated rows scatter back (pads
            # dropped)
            self._wire_ef = jax.tree.map(
                lambda x: jnp.zeros((self.num_clients,) + x.shape,
                                    jnp.float32),
                {"params": params, "batch_stats": bstats})
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            # elastic compute plane (ISSUE 20): a scheduled device loss
            # shrinks the mesh mid-run; resume from the donation-safe
            # checkpoint when one exists, else continue on the live
            # state over the survivors
            pre = self._maybe_preempt(round_idx)
            if pre is not None:
                if pre[1] is not None:
                    round_idx, restored = pre
                    params, bstats = (restored["params"],
                                      restored["batch_stats"])
                    history = restored["history"]
                    continue
                # no checkpoint: continue on the live state over the
                # survivors — off the evicted devices first
                params = self._regather_live(params)
                bstats = self._regather_live(bstats)
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                params, bstats, loss, k = self._run_fused_window(
                    params, bstats, round_idx, k)
                round_idx += k - 1  # hooks below fire for the boundary
            else:
                sampled = self.client_sampling(round_idx)
                self.log.info("################ round %d: clients %s",
                              round_idx, sampled.tolist())
                # cohort sharding (ISSUE 6): the sharded program gathers
                # the mesh-padded set (and takes rngs for it); the EF
                # rows, byz plan, and byte accounting stay on the REAL
                # sampled set — the body slices pads off before that tail
                ids, round_prog = self._cohort_round_prog(sampled)
                rngs = self.per_client_rngs(round_idx, ids)
                byz = self._byz_round_plan(round_idx, sampled)
                if codec_on:
                    # downlink reference snapshot BEFORE dispatch: the
                    # round donates {params, bstats} and the sampled EF
                    # rows, so nothing may read them after the call
                    ref_host = jax.tree.map(np.asarray,
                                            {"params": params,
                                             "batch_stats": bstats})
                    efs = (pt.tree_stack_index(self._wire_ef,
                                               np.asarray(sampled))
                           if self.wire_spec.needs_ef else None)
                    with obs_trace.span("round", round=round_idx,
                                        codec=True):
                        (params, bstats, loss, n_bad, new_efs,
                         u0) = round_prog(
                            params, bstats, self.data, jnp.asarray(ids),
                            rngs, self.round_lr(round_idx), efs, byz)
                    if new_efs is not None:
                        real = jnp.asarray(self._n_train_host[sampled] > 0)
                        self._wire_ef = self.scatter_sampled_rows(
                            self._wire_ef, new_efs, jnp.asarray(sampled),
                            real)
                    self.account_wire_bytes(jax.tree.map(np.asarray, u0),
                                            ref_host, None, len(sampled))
                elif byz is not None:
                    # byz plans only reach engines whose round accepts
                    # them (supports_byz_faults gates at startup); efs
                    # rides its default None
                    with obs_trace.span("round", round=round_idx):
                        params, bstats, loss, n_bad = round_prog(
                            params, bstats, self.data, jnp.asarray(ids),
                            rngs, self.round_lr(round_idx), None, byz)
                else:
                    # efs/byz stay default-bound (None): subclasses
                    # override _round_jit with efs-free signatures
                    # (turboaggregate), and an argument filled from its
                    # default is never donated, so no explicit None is
                    # needed here
                    with obs_trace.span("round", round=round_idx):
                        params, bstats, loss, n_bad = round_prog(
                            params, bstats, self.data, jnp.asarray(ids),
                            rngs, self.round_lr(round_idx))
                self._note_nonfinite(n_bad)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self.eval_global(params, bstats)
                self._flush_nonfinite(round_idx)
                # the rule evaluation inside the flush may have fired
                # freeze_rollback; consume it (or pin healthy state) at
                # this host boundary, never mid-dispatch
                params, bstats = self._reflex_boundary(round_idx, params,
                                                       bstats)
                self.stat_info["global_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss, **m)
                history.append({"round": round_idx, "train_loss": float(loss),
                                **m})
            self.maybe_checkpoint(round_idx, {
                "params": params, "batch_stats": bstats, "history": history})
            round_idx += 1
        self._flush_nonfinite(cfg.fed.comm_round - 1)
        # final fine-tune pass -> personalized models + final eval at "-1"
        rngs = self.per_client_rngs(cfg.fed.comm_round,
                                    np.arange(self.num_clients))
        # reference passes round=-1 for this pass (fedavg_api.py:85), so the
        # fine-tune lr is lr * decay^-1, not the decayed end-of-training lr
        per_states = self._finetune_jit(params, bstats, self.data, rngs,
                                        self.round_lr(-1))
        m_global = self.eval_global(params, bstats)
        m_person = self.eval_personalized(per_states)
        self.stat_info["person_test_acc"].append(m_person["acc"])
        self.log.metrics(-1, global_=m_global, personal=m_person)
        return {"params": params, "batch_stats": bstats,
                "personal": per_states, "history": history,
                "final_global": m_global, "final_personal": m_person}

    # ---------- streaming mode (cohort > HBM) ----------

    def _train_streaming(self):
        """Same round loop, but only the sampled clients' shards live on
        device each round (double-buffered host reads), and evaluation +
        the final fine-tune pass stream the cohort in client chunks."""
        cfg = self.cfg
        self._register_reflexes()
        start, restored = self.restore_checkpoint()
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            history = restored["history"]
        else:
            gs = self.init_global_state()
            params, bstats = gs.params, gs.batch_stats
            history = []
        # fused streamed windows (ISSUE 10): when the window planner can
        # fuse, whole K-round shard stacks are prefetched behind the
        # previous window's scan; hook rounds land on window boundaries
        # exactly as in the resident fused driver, so observable
        # behavior matches the round-granular loop
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        self._stream_prefetch_for(start)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            pre = self._maybe_preempt(round_idx)
            if pre is not None:
                if pre[1] is not None:
                    round_idx, restored = pre
                    params, bstats = (restored["params"],
                                      restored["batch_stats"])
                    history = restored["history"]
                    # the prefetched shards targeted the pre-preemption
                    # round; re-key the feed to the resume point (a key
                    # mismatch would degrade to a fresh fetch anyway)
                    self._stream_prefetch_for(round_idx)
                    continue
                params = self._regather_live(params)
                bstats = self._regather_live(bstats)
                self._stream_prefetch_for(round_idx)
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                params, bstats, loss, k = self._run_fused_stream_window(
                    params, bstats, round_idx, k)
                round_idx += k - 1  # hooks below fire for the boundary
            else:
                fed_ids, n_real = self.stream_sampling(round_idx)
                self.log.info("################ round %d (stream): "
                              "clients %s", round_idx,
                              fed_ids[:n_real].tolist())
                Xs, ys, ns = self.stream.get_train(fed_ids, n_real)
                # overlap the next dispatch's host read (single round or
                # whole window) with this round's compute
                self._stream_prefetch_for(round_idx + 1)
                rngs = self.per_client_rngs(round_idx, fed_ids)
                byz = self._byz_round_plan(round_idx, fed_ids)
                if byz is not None:
                    params, bstats, loss, n_bad = self._round_stream_jit(
                        params, bstats, Xs, ys, ns, rngs,
                        self.round_lr(round_idx), None, byz)
                else:
                    params, bstats, loss, n_bad = self._round_stream_jit(
                        params, bstats, Xs, ys, ns, rngs,
                        self.round_lr(round_idx))
                self._note_nonfinite(n_bad)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self.eval_global_stream(params, bstats)
                self._flush_nonfinite(round_idx)
                params, bstats = self._reflex_boundary(round_idx, params,
                                                       bstats)
                self.stat_info["global_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss, **m)
                history.append({"round": round_idx,
                                "train_loss": float(loss), **m})
            self.maybe_checkpoint(round_idx, {
                "params": params, "batch_stats": bstats, "history": history})
            round_idx += 1
        self._flush_nonfinite(cfg.fed.comm_round - 1)
        # final fine-tune: chunked over client blocks; personalized models
        # are evaluated per block then discarded (they'd exceed HBM)
        chunk = self._eval_chunk_size()
        ft_lr = self.round_lr(-1)
        per_parts, per_ns = [], []
        test_iter = self.stream.eval_chunks(chunk, "test")
        for ch in self.stream.eval_chunks(chunk, "train"):
            if self.cfg.fed.ci and per_parts:
                break  # CI escape hatch: first chunk only
            rngs = self.per_client_rngs(cfg.fed.comm_round, ch.padded_ids)
            states = self._finetune_stream_jit(params, bstats, ch.X, ch.y,
                                               ch.n, rngs, ft_lr)
            che = next(test_iter)
            assert np.array_equal(ch.ids, che.ids)
            out = self._eval_personal_jit(states.params, states.batch_stats,
                                          che.X, che.y, che.n)
            per_parts.append(tuple(np.asarray(o)[: len(ch.ids)]
                                   for o in out))
            per_ns.append(np.asarray(jax.device_get(che.n))[: len(ch.ids)])
        cat = [np.concatenate([p[i] for p in per_parts]) for i in range(4)]
        n_cat = np.concatenate(per_ns)
        if self.cfg.fed.ci:  # client 0 only, matching the resident CI path
            cat, n_cat = [c[:1] for c in cat], n_cat[:1]
        m_person = self._summarize(*cat, n=n_cat)
        m_global = self.eval_global_stream(params, bstats)
        self.stat_info["person_test_acc"].append(m_person["acc"])
        self.log.metrics(-1, global_=m_global, personal=m_person)
        return {"params": params, "batch_stats": bstats,
                "personal": None, "history": history,
                "final_global": m_global, "final_personal": m_person}
