"""D-PSGD: decentralized gossip SGD (fedml_api/standalone/dpsgd/dpsgd_api.py).

Behavior parity (dpsgd_api.py:41-139):
- Per round, every client picks neighbors by the ``cs`` selector: "random"
  (seeded np.random.seed(round_idx + client), resampled while it contains
  self, then self appended), "ring" (left/right), or "full" (everyone).
- Consensus: uniform average over {neighbors ∪ self} of LAST round's
  personal models (dpsgd_api.py:169-178), then local training from the
  consensus point.
- ``w_global`` = plain mean of all personal models, used for global eval
  (dpsgd_api.py:161-167).
- Every 100 rounds a fine-tune-from-global evaluation pass
  (dpsgd_api.py:89-101).

TPU-native: neighbor choices become one row-stochastic mixing matrix
``M[C,C]`` per round; the consensus step for the whole federation is a
single ``einsum('cj,j...->c...')`` over the client-sharded axis (an
all-to-all over ICI), followed by the usual vmapped local training — one
jitted program per round.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine


def benefit_choose(round_idx: int, cur_clnt: int, total: int,
                   per_round: int, cs: str) -> np.ndarray:
    """Neighbor selection, reference parity (dpsgd_api.py:116-139)."""
    if total == per_round:
        return np.arange(total)
    if cs == "random":
        num = min(per_round, total)
        np.random.seed(round_idx + cur_clnt)
        idx = np.random.choice(range(total), num, replace=False)
        while cur_clnt in idx:
            idx = np.random.choice(range(total), num, replace=False)
        return idx
    if cs == "ring":
        return np.asarray([(cur_clnt - 1) % total, (cur_clnt + 1) % total])
    if cs == "full":
        return np.delete(np.arange(total), cur_clnt)
    raise ValueError(f"unknown cs {cs!r}")


class DPSGDEngine(FederatedEngine):
    name = "dpsgd"

    def mixing_matrix(self, round_idx: int) -> np.ndarray:
        """Row c = uniform weights over {neighbors(c) ∪ c} among real
        clients; padding clients keep themselves."""
        C = self.num_clients
        total = self.real_clients
        per_round = min(self.cfg.fed.client_num_per_round, total)
        M = np.zeros((C, C), np.float32)
        for c in range(total):
            nei = benefit_choose(round_idx, c, total, per_round,
                                 self.cfg.fed.cs)
            if total != per_round:
                nei = np.append(nei, c)
            nei = np.unique(nei)
            M[c, nei] = 1.0 / len(nei)
        for c in range(total, C):
            M[c, c] = 1.0
        return M

    @functools.cached_property
    def _round_jit(self):
        trainer = self.trainer
        o = self.cfg.optim
        max_samples = int(self.data.X_train.shape[1])

        def round_fn(per_params, per_bstats, data, M, rngs, lr):
            # consensus over last round's models: one all-to-all matmul
            mix = lambda t: jnp.einsum("cj,j...->c...", M, t)
            mixed_p = jax.tree.map(mix, per_params)
            mixed_b = jax.tree.map(mix, per_bstats)

            def local(p, b, rng, Xc, yc, nc):
                cs = ClientState(params=p, batch_stats=b,
                                 opt_state=trainer.opt.init(p), rng=rng)
                cs, loss = trainer.local_train(
                    cs, Xc, yc, nc, lr, epochs=o.epochs,
                    batch_size=o.batch_size, max_samples=max_samples)
                return cs.params, cs.batch_stats, loss

            new_p, new_b, losses = jax.vmap(local)(
                mixed_p, mixed_b, rngs, data.X_train, data.y_train,
                data.n_train)
            real = (data.n_train > 0).astype(jnp.float32)
            denom = jnp.maximum(jnp.sum(real), 1.0)
            gmean = lambda t: jax.tree.map(
                lambda x: jnp.einsum(
                    "c,c...->...", real / denom, x.astype(jnp.float32)
                ).astype(x.dtype), t)
            w_global_p = gmean(new_p)
            w_global_b = gmean(new_b)
            mean_loss = jnp.sum(losses * real) / denom
            return new_p, new_b, w_global_p, w_global_b, mean_loss

        return jax.jit(round_fn)

    @functools.cached_property
    def _finetune_jit(self):
        """Every-100-rounds fine-tune-from-global evaluation pass
        (dpsgd_api.py:89-101): each client trains one round from w_global;
        the fine-tuned models are evaluated then DISCARDED (w_per_tmp)."""
        trainer = self.trainer
        o = self.cfg.optim
        C = self.num_clients
        max_samples = int(self.data.X_train.shape[1])

        def ft(params, bstats, data, rngs, lr):
            def local(rng, Xc, yc, nc):
                cs = ClientState(
                    params=params, batch_stats=bstats,
                    opt_state=trainer.opt.init(params), rng=rng)
                cs, _ = trainer.local_train(
                    cs, Xc, yc, nc, lr, epochs=o.epochs,
                    batch_size=o.batch_size, max_samples=max_samples)
                return cs.params, cs.batch_stats

            p, b = jax.vmap(local)(rngs, data.X_train, data.y_train,
                                   data.n_train)
            return p, b

        return jax.jit(ft)

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        per = self.broadcast_states(
            ClientState(params=gs.params, batch_stats=gs.batch_stats,
                        opt_state=None, rng=None), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats
        g_params, g_bstats = gs.params, gs.batch_stats
        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            g_params, g_bstats = (restored["g_params"],
                                  restored["g_bstats"])
            history = restored["history"]
        for round_idx in range(start, cfg.fed.comm_round):
            M = jnp.asarray(self.mixing_matrix(round_idx))
            rngs = self.per_client_rngs(round_idx,
                                        np.arange(self.num_clients))
            per_params, per_bstats, g_params, g_bstats, loss = \
                self._round_jit(per_params, per_bstats, self.data, M, rngs,
                                self.round_lr(round_idx))
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                mg = self.eval_global(g_params, g_bstats)
                mp = self.eval_personalized(ClientState(
                    params=per_params, batch_stats=per_bstats,
                    opt_state=None, rng=None))
                self.stat_info["global_test_acc"].append(mg["acc"])
                self.log.metrics(round_idx, train_loss=loss, global_=mg,
                                 personal=mp)
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "global_acc": mg["acc"],
                                "personal_acc": mp["acc"]})
            if round_idx % 100 == 99:
                # fine-tune pass: lr uses round=-1 (client.train(..., -1),
                # dpsgd_api.py:97 -> lr * decay^-1)
                ft_rngs = self.per_client_rngs(-1,
                                               np.arange(self.num_clients))
                ft_p, ft_b = self._finetune_jit(g_params, g_bstats, self.data,
                                                ft_rngs, self.round_lr(-1))
                mft = self.eval_personalized(ClientState(
                    params=ft_p, batch_stats=ft_b, opt_state=None, rng=None))
                self.log.metrics(-1, finetune_after_round=round_idx,
                                 finetune_personal=mft)
            self.maybe_checkpoint(round_idx, {
                "per_params": per_params, "per_bstats": per_bstats,
                "g_params": g_params, "g_bstats": g_bstats,
                "history": history})
        return {"personal_params": per_params, "global_params": g_params,
                "history": history,
                "final_global": self.eval_global(g_params, g_bstats)}
