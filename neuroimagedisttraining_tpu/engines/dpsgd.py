"""D-PSGD: decentralized gossip SGD (fedml_api/standalone/dpsgd/dpsgd_api.py).

Behavior parity (dpsgd_api.py:41-139):
- Per round, every client picks neighbors by the ``cs`` selector: "random"
  (seeded np.random.seed(round_idx + client), resampled while it contains
  self, then self appended), "ring" (left/right), or "full" (everyone).
- Consensus: uniform average over {neighbors ∪ self} of LAST round's
  personal models (dpsgd_api.py:169-178), then local training from the
  consensus point.
- ``w_global`` = plain mean of all personal models, used for global eval
  (dpsgd_api.py:161-167).
- Every 100 rounds a fine-tune-from-global evaluation pass
  (dpsgd_api.py:89-101).

TPU-native: neighbor choices become one row-stochastic mixing matrix
``M[C,C]`` per round. For ``cs="ring"`` at full activity the matrix is
CIRCULANT and the consensus lowers to ``lax.ppermute`` shifts of 1-row
slices between neighboring devices (parallel/gossip.py) — per-device
traffic O(model), independent of C. For ``cs="random"`` (a fresh
k-regular draw every round) the consensus lowers to a routed, capped
``lax.all_to_all`` whose routing tables are traced operands
(parallel/gossip.py::sparse_plan) — per-device traffic O(D * m * model),
m ~ B(k+1)/D rows, one compiled program per size bucket. Only when
neither structure applies (dense patterns) does it fall back to the
``einsum('cj,j...->c...')`` all-gather. Either way, consensus + vmapped
local training is one jitted program per round.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.parallel.gossip import (
    SparseSpec, gossip_apply, gossip_apply_sparse, make_plan,
)

#: fold_in tag separating the DP noise stream from the training stream
#: (both derive from the same config-seeded per-client round key)
_DP_STREAM = 0x0D9


def benefit_choose(round_idx: int, cur_clnt: int, total: int,
                   per_round: int, cs: str) -> np.ndarray:
    """Neighbor selection, reference parity (dpsgd_api.py:116-139)."""
    if total == per_round:
        return np.arange(total)
    if cs == "random":
        num = min(per_round, total)
        np.random.seed(round_idx + cur_clnt)  # nidt: allow[determinism-global-random] -- reference-parity shim (dpsgd_api.py:116-139)
        idx = np.random.choice(range(total), num, replace=False)  # nidt: allow[determinism-global-random] -- reference-parity shim (dpsgd_api.py:116-139)
        while cur_clnt in idx:
            idx = np.random.choice(range(total), num, replace=False)  # nidt: allow[determinism-global-random] -- reference-parity shim (dpsgd_api.py:116-139)
        return idx
    if cs == "ring":
        return np.asarray([(cur_clnt - 1) % total, (cur_clnt + 1) % total])
    if cs == "full":
        return np.delete(np.arange(total), cur_clnt)
    raise ValueError(f"unknown cs {cs!r}")


class DPSGDEngine(FederatedEngine):
    name = "dpsgd"
    #: round-level DP (--dp_clip/--dp_sigma, privacy/ ISSUE 8): in a
    #: decentralized federation every client REVEALS its personal model
    #: to its gossip neighbors each round — there is no trusted server
    #: to defend at, so the only privacy boundary is the client's own
    #: upload. When armed, each client's post-training delta vs its
    #: consensus point is clipped to dp_clip and noised with
    #: N(0, (dp_sigma * dp_clip)^2) INSIDE the jitted round, before
    #: anything leaves the vmapped client row (neighbors, w_global, and
    #: eval all consume the noised models); the RDP accountant reports
    #: the running per-silo (epsilon, dp_delta) in stat_info
    #: (record_privacy: q = 1 full participation, z = dp_sigma).
    supports_dp = True

    def mixing_matrix(self, round_idx: int) -> np.ndarray:
        """Row c = uniform weights over {neighbors(c) ∪ c} among real
        clients; padding clients keep themselves."""
        C = self.num_clients
        total = self.real_clients
        per_round = min(self.cfg.fed.client_num_per_round, total)
        M = np.zeros((C, C), np.float32)
        for c in range(total):
            nei = benefit_choose(round_idx, c, total, per_round,
                                 self.cfg.fed.cs)
            if total != per_round:
                nei = np.append(nei, c)
            nei = np.unique(nei)
            M[c, nei] = 1.0 / len(nei)
        for c in range(total, C):
            M[c, c] = 1.0
        return M

    # Streaming (cohort > HBM): like DisPFL, every client trains each
    # round, so the streamed round runs the state-only gossip consensus
    # first and then local-trains client CHUNKS against host-fetched
    # shards.
    supports_streaming = True

    def cohort_fallback_reason(self) -> str | None:
        # same story as DisPFL: the gossip consensus already lowers to
        # client-sharded mesh collectives (parallel/gossip.py)
        return ("dpsgd's decentralized round already runs client-sharded "
                "gossip collectives on the mesh (parallel/gossip.py); "
                "--client_mesh adds nothing")

    def _consensus(self, per_params, per_bstats, M, plan_arrays=None, *,
                   plan=None):
        """Gossip consensus over last round's models: ppermute ring shifts
        when the round's matrix is circulant and tiles the mesh (Plan
        tuple), a routed all_to_all for per-round sparse random topologies
        (SparseSpec + traced ``plan_arrays``), else one all-gather matmul
        against the mixing matrix."""
        if isinstance(plan, SparseSpec):
            mix = lambda t: gossip_apply_sparse(t, plan, plan_arrays,
                                                self.mesh)
        elif plan is not None:
            mix = lambda t: gossip_apply(t, plan, self.mesh)
        else:
            mix = lambda t: jax.tree.map(
                lambda x: jnp.einsum("cj,j...->c...", M, x), t)
        return mix(per_params), mix(per_bstats)

    def gossip_plan(self, M_np: np.ndarray):
        """``(plan, plan_arrays)`` for this round's matrix: a hashable
        circulant Plan tuple (ppermute shifts, round-invariant ring
        topologies), a SparseSpec + routing arrays (routed all_to_all,
        per-round random topologies — the spec keys the jit cache, the
        arrays are traced operands), or (None, {}) for the dense einsum.
        Detection cost: O(C^2) host compares / O(C*k) bucketing per
        round."""
        return make_plan(M_np, self.mesh, self.num_clients)

    def _local_block(self, mixed_p, mixed_b, rngs, X, y, n, lr):
        trainer = self.trainer
        o = self.cfg.optim
        f = self.cfg.fed
        max_samples = self._max_samples()
        dp_on = f.dp_sigma > 0 or f.dp_clip > 0

        def local(p, b, rng, Xc, yc, nc):
            cs = ClientState(params=p, batch_stats=b,
                             opt_state=trainer.opt.init(p), rng=rng)
            cs, loss = trainer.local_train(
                cs, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples)
            out_p = cs.params
            if dp_on:
                # DP boundary: clip the update delta vs THIS client's
                # consensus point (its round input p — the model its
                # neighbors already hold), then Gaussian noise at
                # sigma = dp_sigma * dp_clip from the config-folded key.
                # batch_stats are never clipped/noised (structural
                # parity with the weak_dp is_weight_param exclusion).
                out_p = robust.norm_diff_clip(out_p, p, f.dp_clip)
                if f.dp_sigma > 0:
                    out_p = robust.add_weak_dp_noise(
                        out_p, jax.random.fold_in(rng, _DP_STREAM),
                        f.dp_sigma * f.dp_clip)
            return out_p, cs.batch_stats, loss

        return jax.vmap(local)(mixed_p, mixed_b, rngs, X, y, n)

    @staticmethod
    def _global_mean(new_p, new_b, n_train):
        real = (n_train > 0).astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(real), 1.0)
        gmean = lambda t: jax.tree.map(
            lambda x: jnp.einsum(
                "c,c...->...", real / denom, x.astype(jnp.float32)
            ).astype(x.dtype), t)
        return gmean(new_p), gmean(new_b), real, denom

    def _round_jit_for(self, plan):
        def build():
            def round_fn(per_params, per_bstats, data, M, rngs, lr,
                         plan_arrays):
                mixed_p, mixed_b = self._consensus(per_params, per_bstats,
                                                   M, plan_arrays,
                                                   plan=plan)
                new_p, new_b, losses = self._local_block(
                    mixed_p, mixed_b, rngs, data.X_train, data.y_train,
                    data.n_train, lr)
                w_global_p, w_global_b, real, denom = self._global_mean(
                    new_p, new_b, data.n_train)
                mean_loss = jnp.sum(losses * real) / denom
                return new_p, new_b, w_global_p, w_global_b, mean_loss

            # donation: last round's personal stacks are consumed by the
            # consensus; their buffers back this round's stacks
            return jax.jit(round_fn,
                           donate_argnums=self._donate_argnums(0, 1))

        return self._plan_cached("_round_jit_cache", plan, build)

    @property
    def _round_jit(self):
        return self._round_jit_for(None)

    def _consensus_jit_for(self, plan):
        # donation: the streamed round never rereads the pre-consensus
        # stacks once mixed
        return self._plan_cached(
            "_consensus_jit_cache", plan,
            lambda: jax.jit(functools.partial(self._consensus, plan=plan),
                            donate_argnums=self._donate_argnums(0, 1)))

    @property
    def _consensus_jit(self):
        return self._consensus_jit_for(None)

    @functools.cached_property
    def _block_jit(self):
        # consumes the consensus output chunks (gathered fresh per chunk)
        return jax.jit(self._local_block,
                       donate_argnums=self._donate_argnums(0, 1))

    @functools.cached_property
    def _tail_jit(self):
        def tail(new_p, new_b, losses, n_train):
            w_global_p, w_global_b, real, denom = self._global_mean(
                new_p, new_b, n_train)
            mean_loss = jnp.sum(losses * real) / denom
            return w_global_p, w_global_b, mean_loss

        return jax.jit(tail)

    def _round_streaming(self, per_params, per_bstats, M, rngs, lr,
                         plan=None, plan_arrays=None):
        mixed_p, mixed_b = self._consensus_jit_for(plan)(
            per_params, per_bstats, M, plan_arrays or {})
        (new_p, new_b), losses = self.stream_map_train_chunks(
            self._block_jit, (mixed_p, mixed_b), rngs, lr)
        w_global_p, w_global_b, mean_loss = self._tail_jit(
            new_p, new_b, losses, jnp.asarray(self._n_train_host))
        return new_p, new_b, w_global_p, w_global_b, mean_loss

    @functools.cached_property
    def _finetune_jit(self):
        """Every-100-rounds fine-tune-from-global evaluation pass
        (dpsgd_api.py:89-101): each client trains one round from w_global;
        the fine-tuned models are evaluated then DISCARDED (w_per_tmp)."""
        trainer = self.trainer
        o = self.cfg.optim
        C = self.num_clients
        max_samples = int(self.data.X_train.shape[1])

        def ft(params, bstats, data, rngs, lr):
            def local(rng, Xc, yc, nc):
                cs = ClientState(
                    params=params, batch_stats=bstats,
                    opt_state=trainer.opt.init(params), rng=rng)
                cs, _ = trainer.local_train(
                    cs, Xc, yc, nc, lr, epochs=o.epochs,
                    batch_size=o.batch_size, max_samples=max_samples)
                return cs.params, cs.batch_stats

            p, b = jax.vmap(local)(rngs, data.X_train, data.y_train,
                                   data.n_train)
            return p, b

        return jax.jit(ft)

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        per = self.broadcast_states(
            ClientState(params=gs.params, batch_stats=gs.batch_stats,
                        opt_state=None, rng=None), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats
        g_params, g_bstats = gs.params, gs.batch_stats
        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            g_params, g_bstats = (restored["g_params"],
                                  restored["g_bstats"])
            history = restored["history"]
        for round_idx in range(start, cfg.fed.comm_round):
            M_np = self.mixing_matrix(round_idx)
            plan, plan_arrays = self.gossip_plan(M_np)
            M = jnp.asarray(M_np)
            rngs = self.per_client_rngs(round_idx,
                                        np.arange(self.num_clients))
            if self.stream is not None:
                per_params, per_bstats, g_params, g_bstats, loss = \
                    self._round_streaming(per_params, per_bstats, M, rngs,
                                          self.round_lr(round_idx),
                                          plan=plan,
                                          plan_arrays=plan_arrays)
            else:
                per_params, per_bstats, g_params, g_bstats, loss = \
                    self._round_jit_for(plan)(
                        per_params, per_bstats, self.data, M, rngs,
                        self.round_lr(round_idx), plan_arrays)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                self.record_privacy(round_idx)
                mg = self._eval_g(g_params, g_bstats)
                mp = self._eval_p(per_params, per_bstats)
                self.stat_info["global_test_acc"].append(mg["acc"])
                self.log.metrics(round_idx, train_loss=loss, global_=mg,
                                 personal=mp)
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "global_acc": mg["acc"],
                                "personal_acc": mp["acc"]})
            if round_idx % 100 == 99 and self.stream is not None \
                    and not getattr(self, "_warned_ft_skip", False):
                self._warned_ft_skip = True
                self.log.info(
                    "streaming run: skipping the every-100-rounds "
                    "fine-tune DIAGNOSTIC pass (its models are evaluated "
                    "then discarded; no training state depends on it)")
            if round_idx % 100 == 99 and self.stream is None:
                # fine-tune pass: lr uses round=-1 (client.train(..., -1),
                # dpsgd_api.py:97 -> lr * decay^-1). Streaming runs skip
                # this DIAGNOSTIC pass (the fine-tuned models are
                # evaluated then discarded, dpsgd_api.py:101 w_per_tmp —
                # no training state depends on it); the per-round metrics
                # above stream fine.
                ft_rngs = self.per_client_rngs(-1,
                                               np.arange(self.num_clients))
                ft_p, ft_b = self._finetune_jit(g_params, g_bstats, self.data,
                                                ft_rngs, self.round_lr(-1))
                mft = self.eval_personalized(ClientState(
                    params=ft_p, batch_stats=ft_b, opt_state=None, rng=None))
                self.log.metrics(-1, finetune_after_round=round_idx,
                                 finetune_personal=mft)
            self.maybe_checkpoint(round_idx, {
                "per_params": per_params, "per_bstats": per_bstats,
                "g_params": g_params, "g_bstats": g_bstats,
                "history": history})
        return {"personal_params": per_params, "global_params": g_params,
                "history": history,
                "final_global": self._eval_g(g_params, g_bstats)}
