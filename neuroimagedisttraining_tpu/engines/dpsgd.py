"""D-PSGD: decentralized gossip SGD (fedml_api/standalone/dpsgd/dpsgd_api.py).

Behavior parity (dpsgd_api.py:41-139):
- Per round, every client picks neighbors by the ``cs`` selector: "random"
  (seeded np.random.seed(round_idx + client), resampled while it contains
  self, then self appended), "ring" (left/right), or "full" (everyone).
- Consensus: uniform average over {neighbors ∪ self} of LAST round's
  personal models (dpsgd_api.py:169-178), then local training from the
  consensus point.
- ``w_global`` = plain mean of all personal models, used for global eval
  (dpsgd_api.py:161-167).
- Every 100 rounds a fine-tune-from-global evaluation pass
  (dpsgd_api.py:89-101).

TPU-native: neighbor choices become one row-stochastic mixing matrix
``M[C,C]`` per round. For ``cs="ring"`` at full activity the matrix is
CIRCULANT and the consensus lowers to ``lax.ppermute`` shifts of 1-row
slices between neighboring devices (parallel/gossip.py) — per-device
traffic O(model), independent of C. For ``cs="random"`` (a fresh
k-regular draw every round) the consensus lowers to a routed, capped
``lax.all_to_all`` whose routing tables are traced operands
(parallel/gossip.py::sparse_plan) — per-device traffic O(D * m * model),
m ~ B(k+1)/D rows, one compiled program per size bucket. Only when
neither structure applies (dense patterns) does it fall back to the
``einsum('cj,j...->c...')`` all-gather.

The round is DECLARED through the round-program builder
(engines/program.py, ISSUE 11): consensus + local training is the train
stage (the mixing matrix and the sparse plan's routing arrays are
``per_round`` operands; the hashable plan spec keys the compiled
program), the all-real mean over trained stacks is a custom aggregate
stage, and ``w_global`` is an epilogue — computed once per dispatch from
the final stacks, which over a fused window is bitwise-identical to the
last round's (same op on the same values). The builder supplies fused
``--rounds_per_dispatch K`` windows (shrunk to the maximal equal-plan
prefix when per-round gossip plans change shape) and ``--client_mesh``
sharding of the local-train stage (the gossip consensus itself already
runs mesh collectives); the every-100-rounds fine-tune pass is declared
as an extra window-boundary hook.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.parallel.gossip import (
    SparseSpec, gossip_apply, gossip_apply_sparse, make_plan,
)

#: fold_in tag separating the DP noise stream from the training stream
#: (both derive from the same config-seeded per-client round key)
_DP_STREAM = 0x0D9


def benefit_choose(round_idx: int, cur_clnt: int, total: int,
                   per_round: int, cs: str) -> np.ndarray:
    """Neighbor selection, reference parity (dpsgd_api.py:116-139)."""
    if total == per_round:
        return np.arange(total)
    if cs == "random":
        num = min(per_round, total)
        np.random.seed(round_idx + cur_clnt)  # nidt: allow[determinism-global-random] -- reference-parity shim (dpsgd_api.py:116-139)
        idx = np.random.choice(range(total), num, replace=False)  # nidt: allow[determinism-global-random] -- reference-parity shim (dpsgd_api.py:116-139)
        while cur_clnt in idx:
            idx = np.random.choice(range(total), num, replace=False)  # nidt: allow[determinism-global-random] -- reference-parity shim (dpsgd_api.py:116-139)
        return idx
    if cs == "ring":
        return np.asarray([(cur_clnt - 1) % total, (cur_clnt + 1) % total])
    if cs == "full":
        return np.delete(np.arange(total), cur_clnt)
    raise ValueError(f"unknown cs {cs!r}")


class DPSGDEngine(FederatedEngine):
    name = "dpsgd"
    #: round-level DP (--dp_clip/--dp_sigma, privacy/ ISSUE 8): in a
    #: decentralized federation every client REVEALS its personal model
    #: to its gossip neighbors each round — there is no trusted server
    #: to defend at, so the only privacy boundary is the client's own
    #: upload. When armed, each client's post-training delta vs its
    #: consensus point is clipped to dp_clip and noised with
    #: N(0, (dp_sigma * dp_clip)^2) INSIDE the jitted round, before
    #: anything leaves the per-client row (neighbors, w_global, and
    #: eval all consume the noised models); the RDP accountant reports
    #: the running per-silo (epsilon, dp_delta) in stat_info
    #: (record_privacy: q = 1 full participation, z = dp_sigma).
    supports_dp = True

    def mixing_matrix(self, round_idx: int) -> np.ndarray:
        """Row c = uniform weights over {neighbors(c) ∪ c} among real
        clients; padding clients keep themselves."""
        C = self.num_clients
        total = self.real_clients
        per_round = min(self.cfg.fed.client_num_per_round, total)
        M = np.zeros((C, C), np.float32)
        for c in range(total):
            nei = benefit_choose(round_idx, c, total, per_round,
                                 self.cfg.fed.cs)
            if total != per_round:
                nei = np.append(nei, c)
            nei = np.unique(nei)
            M[c, nei] = 1.0 / len(nei)
        for c in range(total, C):
            M[c, c] = 1.0
        return M

    # Streaming (cohort > HBM): like DisPFL, every client trains each
    # round, so the streamed round runs the state-only gossip consensus
    # first and then local-trains client CHUNKS against host-fetched
    # shards.
    supports_streaming = True
    supports_cohort_sharding = True  # the local-train stage (every
    # client, every round) shards over the --client_mesh; the consensus
    # already runs mesh collectives (parallel/gossip.py)

    def _consensus(self, per_params, per_bstats, M, plan_arrays=None, *,
                   plan=None):
        """Gossip consensus over last round's models: ppermute ring shifts
        when the round's matrix is circulant and tiles the mesh (Plan
        tuple), a routed all_to_all for per-round sparse random topologies
        (SparseSpec + traced ``plan_arrays``), else one all-gather matmul
        against the mixing matrix."""
        if isinstance(plan, SparseSpec):
            mix = lambda t: gossip_apply_sparse(t, plan, plan_arrays,
                                                self.mesh)
        elif plan is not None:
            mix = lambda t: gossip_apply(t, plan, self.mesh)
        else:
            mix = lambda t: jax.tree.map(
                lambda x: jnp.einsum("cj,j...->c...", M, x), t)
        return mix(per_params), mix(per_bstats)

    def gossip_plan(self, M_np: np.ndarray):
        """``(plan, plan_arrays)`` for this round's matrix: a hashable
        circulant Plan tuple (ppermute shifts, round-invariant ring
        topologies), a SparseSpec + routing arrays (routed all_to_all,
        per-round random topologies — the spec keys the jit cache, the
        arrays are traced operands), or (None, {}) for the dense einsum.
        Detection cost: O(C^2) host compares / O(C*k) bucketing per
        round."""
        return make_plan(M_np, self.mesh, self.num_clients)

    # ---------- the declared round (engines/program.py) ----------

    def round_stages(self):
        return round_program.RoundStages(
            carry=("per_params", "per_bstats"),
            train=self._train_stage,
            aggregate=self._aggregate_stage,
            epilogue=self._epilogue_stage,
            outputs=("loss",),
            per_round=("M", "plan_arrays"),
            gathers_cohort=False,
            window_extras=self._window_extras,
            extra_hooked=self._finetune_hooked,
        )

    def _finetune_hooked(self, r: int) -> bool:
        """The every-100-rounds fine-tune-from-global evaluation pass is
        a host-side hook — the window planner pins it to a boundary."""
        return r % 100 == 99

    def _train_stage(self, ctx) -> round_program.TrainOut:
        """Consensus over last round's models (per-round mixing matrix /
        routed plan arrays), then every client trains from its consensus
        point — vmapped, or sharded over the client mesh (the full
        cohort tiles it by construction: the data layer pads
        num_clients; perms hoisted out of the partition)."""
        o = self.cfg.optim
        Xs, ys, ns = ctx.Xs, ctx.ys, ctx.ns
        mixed_p, mixed_b = self._consensus(
            ctx.carry["per_params"], ctx.carry["per_bstats"],
            ctx.per_round["M"], ctx.per_round["plan_arrays"],
            plan=ctx.static)
        new_p, new_b, losses = ctx.client_map(
            self._dp_local_fn(ctx.lr), mixed_p, mixed_b, ctx.rngs, Xs,
            ys, ns,
            hoisted=(lambda: ctx.local_perms(ctx.rngs, ns, o.epochs),))
        return round_program.TrainOut(
            losses=losses, extra={"new_p": new_p, "new_b": new_b})

    def _aggregate_stage(self, ctx, upload, w, tr):
        """No server aggregation in a decentralized round: the trained
        stacks ARE next round's carry; the round's scalar is the mean
        loss over real clients."""
        real = (ctx.ns > 0).astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(real), 1.0)
        mean_loss = jnp.sum(tr.losses * real) / denom
        return ({"per_params": tr.extra["new_p"],
                 "per_bstats": tr.extra["new_b"]},
                {"loss": mean_loss})

    @staticmethod
    def _global_mean(new_p, new_b, n_train):
        real = (n_train > 0).astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(real), 1.0)
        gmean = lambda t: jax.tree.map(
            lambda x: jnp.einsum(
                "c,c...->...", real / denom, x.astype(jnp.float32)
            ).astype(x.dtype), t)
        return gmean(new_p), gmean(new_b), real, denom

    def _epilogue_stage(self, eng, carry, data) -> tuple:
        """``w_global`` — the plain mean of all personal models
        (dpsgd_api.py:161-167), computed once per dispatch from the
        final stacks (bitwise the last round's: same op, same values)."""
        wp, wb, _, _ = self._global_mean(carry["per_params"],
                                         carry["per_bstats"],
                                         data.n_train)
        return (wp, wb)

    def _window_extras(self, round_idx: int, k: int
                       ) -> round_program.WindowInputs:
        """Window prologue: per-round mixing matrices + gossip plans.
        The scan needs ONE compiled consensus, so the window shrinks to
        the maximal prefix whose plan spec (the program's static key)
        and routing-array shapes match round 0's — ring/full topologies
        are round-invariant (full windows), random topologies fuse while
        their sparse bucketing stays shape-stable."""
        Ms, plans, arrays = [], [], []
        for off in range(k):
            M_np = self.mixing_matrix(round_idx + off)
            plan, pa = self.gossip_plan(M_np)
            Ms.append(M_np)
            plans.append(plan)
            arrays.append(pa)

        def compatible(i: int) -> bool:
            if plans[i] != plans[0]:
                return False
            a0 = jax.tree.leaves(arrays[0])
            ai = jax.tree.leaves(arrays[i])
            return (jax.tree.structure(arrays[i])
                    == jax.tree.structure(arrays[0])
                    and all(np.shape(x) == np.shape(y)
                            for x, y in zip(ai, a0)))

        keep = 1
        while keep < k and compatible(keep):
            keep += 1
        k = keep
        for off in range(k):
            self.log.info("################ round %d: decentralized "
                          "cohort (fused window of %d)", round_idx + off,
                          k)
        C = self.num_clients
        M = jnp.asarray(np.stack(Ms[:k]))
        if jax.tree.leaves(arrays[0]):
            pa = jax.tree.map(lambda *xs: jnp.stack(xs), *arrays[:k])
        else:
            pa = arrays[0]
        rngs = jnp.stack([self.per_client_rngs(round_idx + off,
                                               np.arange(C))
                          for off in range(k)])
        lrs = jnp.asarray([self.round_lr(round_idx + off)
                           for off in range(k)], jnp.float32)
        return round_program.WindowInputs(
            sampled=None, idx=None, rngs=rngs, lrs=lrs, byz=None, k=k,
            n_real=None, static_key=plans[0],
            per_round={"M": M, "plan_arrays": pa})

    # ---------- legacy-signature program adapters ----------

    def _round_jit_for(self, plan):
        prog = self.program.round_jit(static_key=plan,
                                      sharded=self._cohort_on)

        def round_call(per_params, per_bstats, data, M, rngs, lr,
                       plan_arrays):
            return prog((per_params, per_bstats), data, (), None, rngs,
                        lr, None, None, (M, plan_arrays))

        def lower(per_params, per_bstats, data, M, rngs, lr,
                  plan_arrays):
            # legacy-signature .lower passthrough (compile-text pins,
            # tests/test_gossip.py)
            return prog.jit.lower((per_params, per_bstats), data, (),
                                  None, rngs, lr, None, None,
                                  (M, plan_arrays))

        round_call.jit = prog.jit
        round_call.lower = lower
        return round_call

    @property
    def _round_jit(self):
        return self._round_jit_for(None)

    # ---------- streaming round (chunked; outside the program) ----------

    def _consensus_jit_for(self, plan):
        # donation: the streamed round never rereads the pre-consensus
        # stacks once mixed
        return self._plan_cached(
            "_consensus_jit_cache", plan,
            lambda: jax.jit(functools.partial(self._consensus, plan=plan),
                            donate_argnums=self._donate_argnums(0, 1)))

    @property
    def _consensus_jit(self):
        return self._consensus_jit_for(None)

    def _dp_local_fn(self, lr):
        """The per-client train + DP-boundary closure shared by the
        resident train stage and the streamed block — the DP transform
        lives ONCE. Clip the update delta vs THIS client's consensus
        point (its round input ``p`` — the model its neighbors already
        hold), then Gaussian noise at sigma = dp_sigma * dp_clip from
        the config-folded key. batch_stats are never clipped/noised
        (structural parity with the weak_dp is_weight_param
        exclusion)."""
        trainer = self.trainer
        o = self.cfg.optim
        f = self.cfg.fed
        max_samples = self._max_samples()
        dp_on = f.dp_sigma > 0 or f.dp_clip > 0

        def local(p, b, rng, Xc, yc, nc, perms_c=None):
            cs = ClientState(params=p, batch_stats=b,
                             opt_state=trainer.opt.init(p), rng=rng)
            cs, loss = trainer.local_train(
                cs, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                perms=perms_c)
            out_p = cs.params
            if dp_on:
                out_p = robust.norm_diff_clip(out_p, p, f.dp_clip)
                if f.dp_sigma > 0:
                    out_p = robust.add_weak_dp_noise(
                        out_p, jax.random.fold_in(rng, _DP_STREAM),
                        f.dp_sigma * f.dp_clip)
            return out_p, cs.batch_stats, loss

        return local

    def _local_block(self, mixed_p, mixed_b, rngs, X, y, n, lr):
        """The streamed per-chunk training block (the resident path's
        local stage lives in ``_train_stage``)."""
        return jax.vmap(self._dp_local_fn(lr))(mixed_p, mixed_b, rngs,
                                               X, y, n)

    @functools.cached_property
    def _block_jit(self):
        # consumes the consensus output chunks (gathered fresh per chunk)
        return jax.jit(self._local_block,
                       donate_argnums=self._donate_argnums(0, 1))

    @functools.cached_property
    def _tail_jit(self):
        def tail(new_p, new_b, losses, n_train):
            w_global_p, w_global_b, real, denom = self._global_mean(
                new_p, new_b, n_train)
            mean_loss = jnp.sum(losses * real) / denom
            return w_global_p, w_global_b, mean_loss

        return jax.jit(tail)

    def _round_streaming(self, per_params, per_bstats, M, rngs, lr,
                         plan=None, plan_arrays=None):
        mixed_p, mixed_b = self._consensus_jit_for(plan)(
            per_params, per_bstats, M, plan_arrays or {})
        (new_p, new_b), losses = self.stream_map_train_chunks(
            self._block_jit, (mixed_p, mixed_b), rngs, lr)
        w_global_p, w_global_b, mean_loss = self._tail_jit(
            new_p, new_b, losses, jnp.asarray(self._n_train_host))
        return new_p, new_b, w_global_p, w_global_b, mean_loss

    @functools.cached_property
    def _finetune_jit(self):
        """Every-100-rounds fine-tune-from-global evaluation pass
        (dpsgd_api.py:89-101): each client trains one round from w_global;
        the fine-tuned models are evaluated then DISCARDED (w_per_tmp)."""
        trainer = self.trainer
        o = self.cfg.optim
        max_samples = int(self.data.X_train.shape[1])

        def ft(params, bstats, data, rngs, lr):
            def local(rng, Xc, yc, nc):
                cs = ClientState(
                    params=params, batch_stats=bstats,
                    opt_state=trainer.opt.init(params), rng=rng)
                cs, _ = trainer.local_train(
                    cs, Xc, yc, nc, lr, epochs=o.epochs,
                    batch_size=o.batch_size, max_samples=max_samples)
                return cs.params, cs.batch_stats

            p, b = jax.vmap(local)(rngs, data.X_train, data.y_train,
                                   data.n_train)
            return p, b

        return jax.jit(ft)

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        per = self.broadcast_states(
            ClientState(params=gs.params, batch_stats=gs.batch_stats,
                        opt_state=None, rng=None), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats
        g_params, g_bstats = gs.params, gs.batch_stats
        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            g_params, g_bstats = (restored["g_params"],
                                  restored["g_bstats"])
            history = restored["history"]
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                ((per_params, per_bstats), (g_params, g_bstats), outs,
                 wi) = self.program.run_window(
                    (per_params, per_bstats), round_idx, k)
                loss, k = outs["loss"][-1], wi.k
                round_idx += k - 1
            else:
                M_np = self.mixing_matrix(round_idx)
                plan, plan_arrays = self.gossip_plan(M_np)
                M = jnp.asarray(M_np)
                rngs = self.per_client_rngs(round_idx,
                                            np.arange(self.num_clients))
                if self.stream is not None:
                    per_params, per_bstats, g_params, g_bstats, loss = \
                        self._round_streaming(per_params, per_bstats, M,
                                              rngs,
                                              self.round_lr(round_idx),
                                              plan=plan,
                                              plan_arrays=plan_arrays)
                else:
                    per_params, per_bstats, g_params, g_bstats, loss = \
                        self._round_jit_for(plan)(
                            per_params, per_bstats, self.data, M, rngs,
                            self.round_lr(round_idx), plan_arrays)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                # the shared OBS/health boundary: record_privacy runs
                # first inside the flush (the historic dpsgd call), and
                # the stat/DP/health gauges + rule evaluation publish
                # at this already-synced point (engines/base.py)
                self._flush_nonfinite(round_idx)
                mg = self._eval_g(g_params, g_bstats)
                mp = self._eval_p(per_params, per_bstats)
                self.stat_info["global_test_acc"].append(mg["acc"])
                self.log.metrics(round_idx, train_loss=loss, global_=mg,
                                 personal=mp)
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "global_acc": mg["acc"],
                                "personal_acc": mp["acc"]})
            if round_idx % 100 == 99 and self.stream is not None \
                    and not getattr(self, "_warned_ft_skip", False):
                self._warned_ft_skip = True
                self.log.info(
                    "streaming run: skipping the every-100-rounds "
                    "fine-tune DIAGNOSTIC pass (its models are evaluated "
                    "then discarded; no training state depends on it)")
            if round_idx % 100 == 99 and self.stream is None:
                # fine-tune pass: lr uses round=-1 (client.train(..., -1),
                # dpsgd_api.py:97 -> lr * decay^-1). Streaming runs skip
                # this DIAGNOSTIC pass (the fine-tuned models are
                # evaluated then discarded, dpsgd_api.py:101 w_per_tmp —
                # no training state depends on it); the per-round metrics
                # above stream fine. The window planner pins this round
                # to a boundary (round_stages.extra_hooked).
                ft_rngs = self.per_client_rngs(-1,
                                               np.arange(self.num_clients))
                ft_p, ft_b = self._finetune_jit(g_params, g_bstats, self.data,
                                                ft_rngs, self.round_lr(-1))
                mft = self.eval_personalized(ClientState(
                    params=ft_p, batch_stats=ft_b, opt_state=None, rng=None))
                self.log.metrics(-1, finetune_after_round=round_idx,
                                 finetune_personal=mft)
            self.maybe_checkpoint(round_idx, {
                "per_params": per_params, "per_bstats": per_bstats,
                "g_params": g_params, "g_bstats": g_bstats,
                "history": history})
            round_idx += 1
        return {"personal_params": per_params, "global_params": g_params,
                "history": history,
                "final_global": self._eval_g(g_params, g_bstats)}
