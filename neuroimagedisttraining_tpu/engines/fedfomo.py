"""FedFomo: personalized client-to-client weighted aggregation
(fedml_api/standalone/fedfomo/fedfomo_api.py:53-218).

Behavior parity:

- Every client trains from its own previous personal model each round
  (fedfomo_api.py:68-76); aggregation then mixes NEIGHBORS' pre-round
  (last-round) models with the client's own freshly-trained one.
- Neighbor choice (``_benefit_choose``, fedfomo_api.py:130-144): at full
  participation, everyone; otherwise a coin flip between (a) the top-M
  clients by accumulated ``p_choose`` score and (b) uniform random
  (resample-while-self quirk), with the client's own ``p_choose`` entry
  permanently zeroed. ``M = fomo_m`` (the reference reuses
  client_num_per_round; we honor ``fomo_m`` and default it the same way).
- Fomo weights (``_updates_weight_local``, fedfomo_api.py:147-171):
  ``w[c,n] = (valloss_c(own lstrd) - valloss_c(model_n)) / ||theta_n -
  theta_c^lstrd||`` on client c's VALIDATION split; the self entry compares
  the freshly-trained model. Zero parameter distance -> weight 0. Non-
  neighbor entries keep their previous value (array persists across
  rounds, initialized to 1/C).
- ``p_choose[c] += weights[c]`` every round (fedfomo_api.py:93).
- Aggregation (``_aggregate_func``, fedfomo_api.py:200-218): ReLU the
  weights, normalize over the neighbor set, and apply as a delta from the
  client's last-round model; all-nonpositive weights -> keep last model.
- Dtype discipline (SURVEY §3.5: the reference crashed on Long/Float casts
  in aggregation): all aggregation math here runs in float32 pytrees; there
  are no integer leaves in params by construction.

TPU-native: one jitted round program; the val-loss and parameter-distance
matrices are computed ONLY at the (client, neighbor) pairs the round's
adjacency selects — a lax.scan over the padded pair list, each step
dynamically gathering one owner model — matching the reference's cost of
evaluating just the RECEIVED models (fedfomo_api.py:147-171): per round
that is at most real*(fomo_m+1) evaluations instead of C^2 (they coincide
at full participation, where every client receives every model). Results
are scattered into [C, C] matrices; non-pair entries are masked out by the
adjacency before use. Aggregation is two einsums against the
row-normalized ReLU weight matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.ops import flops as flops_ops
from neuroimagedisttraining_tpu.utils import pytree as pt


class FedFomoEngine(FederatedEngine):
    name = "fedfomo"
    # Streaming (cohort > HBM): FedFomo's per-client MODELS must stay
    # resident (the pair evals gather arbitrary owners), but its TRAIN
    # shards chunk through stream_map_train_chunks exactly like DisPFL's,
    # and the val split is val_fraction-small so it is fetched resident
    # once (stream.get_val_resident) — the last engine off the streaming
    # list (VERDICT r3 next-step #5).
    supports_streaming = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.stream is not None:
            if self.stream.val_map is None:
                raise ValueError(
                    "FedFomo streaming requires a val split: build the "
                    "StreamingFederation with val_map (val_fraction > 0)")
        elif self.data.X_val is None:
            raise ValueError(
                "FedFomo requires a validation split: build the federation "
                "with val_fraction > 0 (reference 9-tuple val loaders, "
                "main_fedfomo.py:114-134)")

    # ---------- host-side neighbor choice ----------

    def benefit_choose(self, round_idx: int, c: int,
                       p_choose_row: np.ndarray) -> np.ndarray:
        """fedfomo_api.py:130-144. Coin flip between top-M by p_choose and
        uniform random (resample-while-self). Deviation: the reference's
        coin is unseeded ``random.random()``; we seed per (round, client)
        for reproducibility."""
        total = self.real_clients
        # clamp like base.client_sampling: per_round can exceed the real
        # client count (e.g. default 21-client config on a 4-site cohort)
        per_round = min(self.cfg.fed.client_num_per_round, total)
        if per_round == total:
            return np.arange(total)
        m = min(self.cfg.fed.fomo_m, per_round)  # m < total, so the
        # resample-while-self loop below always terminates
        rs = np.random.RandomState(self.cfg.seed * 131 + round_idx * 17 + c)
        if rs.random() >= 0.5:
            row = p_choose_row[:total].copy()
            row[c] = 0.0  # reference zeroes own entry before top-M pick
            nei = np.argsort(row)[-m:]
        else:
            nei = rs.choice(range(total), m, replace=False)
            while c in nei:
                nei = rs.choice(range(total), m, replace=False)
        return np.append(nei, c)

    # ---------- the round program ----------

    def pairs_from_adjacency(self, A: np.ndarray):
        """Static-shape (client, owner) pair list of the round's nonzero
        adjacency entries. The pad size is fixed by the config (so the
        round program compiles once): real*(m+1) under partial
        participation, real^2 at full participation. Pad slots point at
        (0, 0) — always a real pair (every client is its own neighbor), so
        duplicate scatters write identical values."""
        real = self.real_clients
        per_round = min(self.cfg.fed.client_num_per_round, real)
        if per_round == real:
            P = real * real
        else:
            P = real * (min(self.cfg.fed.fomo_m, per_round) + 1)
        cs, ns = np.nonzero(A[:real, :real])
        assert len(cs) <= P, (len(cs), P)
        pair_c = np.zeros(P, np.int32)
        pair_n = np.zeros(P, np.int32)
        pair_c[: len(cs)] = cs
        pair_n[: len(ns)] = ns
        return pair_c, pair_n, len(cs)

    def _local_block(self, per_p, per_b, rngs, Xs, ys, ns, lr):
        """Local training from each client's own previous model over a
        block of clients (fedfomo_api.py:68-76) — per-client independent,
        so the streamed chunked composition equals the fused resident
        program."""
        trainer = self.trainer
        o = self.cfg.optim
        max_samples = self._max_samples()

        def local(p, b, rng, Xc, yc, nc):
            cs_c = ClientState(params=p, batch_stats=b,
                               opt_state=trainer.opt.init(p), rng=rng)
            cs_c, loss = trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples)
            return cs_c.params, cs_c.batch_stats, loss

        return jax.vmap(local)(per_p, per_b, rngs, Xs, ys, ns)

    def _fomo_agg(self, lstrd_p, lstrd_b, new_p, new_b, losses, weights,
                  p_choose, A, pair_c, pair_n, Xval, yval, nval, n_train):
        """Pair-list val evals + fomo weight update + ReLU-normalized delta
        aggregation (stages 2-5 of the round); val shards are explicit
        arguments so the streamed path can pass the resident val fetch."""
        trainer = self.trainer
        C = self.num_clients

        # --- 2+3. val-loss + parameter-distance at NEIGHBOR PAIRS
        # only (reference evaluates just the received models,
        # fedfomo_api.py:147-171): scan the pair list, gathering one
        # owner model per step ---
        def pair_step(_, cn):
            c, n = cn
            pn = pt.tree_stack_index(lstrd_p, n)
            bn = pt.tree_stack_index(lstrd_b, n)
            pc = pt.tree_stack_index(lstrd_p, c)
            Xv = Xval[c]
            yv = yval[c]
            nv = nval[c]
            valid = jnp.arange(Xv.shape[0]) < nv
            m = trainer.evaluate(pn, bn, Xv, yv, valid)
            lval = m["test_loss"] / jnp.maximum(m["test_total"], 1.0)
            diff = pt.tree_sub(pn, pc)
            return None, (lval, pt.tree_dot(diff, diff))

        _, (Lp, D2p) = jax.lax.scan(pair_step, None, (pair_c, pair_n))
        L = jnp.zeros((C, C), jnp.float32).at[pair_c, pair_n].set(Lp)
        D = jnp.sqrt(jnp.maximum(
            jnp.zeros((C, C), jnp.float32).at[pair_c, pair_n].set(D2p),
            0.0))

        def self_loss(p, b, Xv, yv, nv):
            valid = jnp.arange(Xv.shape[0]) < nv
            m = trainer.evaluate(p, b, Xv, yv, valid)
            return m["test_loss"] / jnp.maximum(m["test_total"], 1.0)

        L_self = jax.vmap(self_loss)(new_p, new_b, Xval, yval, nval)
        loss_cur = jnp.diagonal(L)             # own lstrd model
        d_self = jax.vmap(lambda a, b: pt.tree_norm(pt.tree_sub(a, b)))(
            new_p, lstrd_p)
        D = D.at[jnp.arange(C), jnp.arange(C)].set(d_self)
        Lmat = L.at[jnp.arange(C), jnp.arange(C)].set(L_self)

        # --- 4. fomo weight update on neighbor entries only ---
        w_new = jnp.where(D > 0, (loss_cur[:, None] - Lmat)
                          / jnp.maximum(D, 1e-20), 0.0)
        weights = jnp.where(A > 0, w_new, weights)
        p_choose = p_choose + weights          # fedfomo_api.py:93

        # --- 5. ReLU-normalized delta aggregation ---
        wpos = jnp.maximum(weights, 0.0) * A
        denom = jnp.sum(wpos, axis=1)          # [c]
        B = jnp.where(denom[:, None] > 0, wpos
                      / jnp.maximum(denom[:, None], 1e-20), 0.0)
        B_off = B * (1.0 - jnp.eye(C))
        b_diag = jnp.diagonal(B)
        rowsum = jnp.sum(B, axis=1)            # 1 where denom>0 else 0

        def agg_leaf(lst, new):
            lst32 = lst.astype(jnp.float32)
            t1 = jnp.einsum("cn,n...->c...", B_off, lst32)
            bd = b_diag.reshape((-1,) + (1,) * (lst.ndim - 1))
            rs_ = rowsum.reshape((-1,) + (1,) * (lst.ndim - 1))
            out = lst32 + t1 + bd * new.astype(jnp.float32) - rs_ * lst32
            return out.astype(lst.dtype)

        agg_p = jax.tree.map(agg_leaf, lstrd_p, new_p)
        agg_b = jax.tree.map(agg_leaf, lstrd_b, new_b)

        real = (n_train > 0).astype(jnp.float32)
        mean_loss = jnp.sum(losses * real) / jnp.maximum(jnp.sum(real),
                                                         1.0)
        return agg_p, agg_b, weights, p_choose, mean_loss

    @functools.cached_property
    def _round_jit(self):
        def round_fn(per_params, per_bstats, weights, p_choose, A,
                     pair_c, pair_n, data, rngs, lr):
            new_p, new_b, losses = self._local_block(
                per_params, per_bstats, rngs, data.X_train, data.y_train,
                data.n_train, lr)
            return self._fomo_agg(per_params, per_bstats, new_p, new_b,
                                  losses, weights, p_choose, A, pair_c,
                                  pair_n, data.X_val, data.y_val,
                                  data.n_val, data.n_train)

        # donation: the per-client model stacks and the persistent fomo
        # state (weights, p_choose) are consumed; the driver rebinds all
        # four (the next round's benefit_choose reads the NEW p_choose)
        return jax.jit(round_fn,
                       donate_argnums=self._donate_argnums(0, 1, 2, 3))

    # ---------- streamed round (data per chunk, models resident) ----------

    @functools.cached_property
    def _local_chunk_jit(self):
        # consumes gathered per-chunk copies (fresh each chunk)
        return jax.jit(self._local_block,
                       donate_argnums=self._donate_argnums(0, 1))

    @functools.cached_property
    def _agg_jit(self):
        # donation: lstrd stacks + fomo state; NOT new_p/new_b (each
        # output has exactly one donatable source buffer) and NOT the
        # resident val shards / n_train, which are reused every round
        return jax.jit(self._fomo_agg,
                       donate_argnums=self._donate_argnums(0, 1, 5, 6))

    # ---------- training loop ----------

    def train(self):
        cfg = self.cfg
        C = self.num_clients
        gs = self.init_global_state()
        per = self.broadcast_states(
            ClientState(params=gs.params, batch_stats=gs.batch_stats,
                        opt_state=None, rng=None), C)
        per_params, per_bstats = per.params, per.batch_stats
        # persistent fomo state (fedfomo_api.py:60-61)
        weights = jnp.full((C, C), 1.0 / max(self.real_clients, 1),
                           jnp.float32)
        p_choose = jnp.ones((C, C), jnp.float32)
        flops_per_sample = flops_ops.count_training_flops_per_sample(
            self.trainer.model, gs.params,
            self.trainer._prep(self.sample_input()),
            batch_stats=gs.batch_stats)
        n_params = pt.tree_size(gs.params)

        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            weights = jnp.asarray(restored["weights"])
            p_choose = jnp.asarray(restored["p_choose"])
            history = restored["history"]
        if self.stream is not None:
            # val shards are val_fraction-small: resident once, reused
            # every round by the pair evals
            Xval, yval, nval = self.stream.get_val_resident()
            n_train_dev = jnp.asarray(self._n_train_host)
        for round_idx in range(start, cfg.fed.comm_round):
            pch = np.asarray(jax.device_get(p_choose))
            A = np.zeros((C, C), np.float32)
            n_model_transfers = 0
            for c in range(self.real_clients):
                nei = np.unique(self.benefit_choose(round_idx, c, pch[c]))
                A[c, nei] = 1.0
                n_model_transfers += len(nei) - (1 if c in nei else 0)
            pair_c, pair_n, n_pairs = self.pairs_from_adjacency(A)
            self._last_eval_pairs = n_pairs  # true neighbor-eval count
            self.log.info("################ round %d (%d neighbor evals)",
                          round_idx, n_pairs)
            rngs = self.per_client_rngs(round_idx, np.arange(C))
            if self.stream is not None:
                # train-all-clients stage over host-streamed chunks (state
                # resident), then the resident-state agg program
                (new_p, new_b), losses = self.stream_map_train_chunks(
                    self._local_chunk_jit, (per_params, per_bstats), rngs,
                    self.round_lr(round_idx))
                per_params, per_bstats, weights, p_choose, loss = \
                    self._agg_jit(per_params, per_bstats, new_p, new_b,
                                  losses, weights, p_choose,
                                  jnp.asarray(A), jnp.asarray(pair_c),
                                  jnp.asarray(pair_n), Xval, yval, nval,
                                  n_train_dev)
            else:
                per_params, per_bstats, weights, p_choose, loss = \
                    self._round_jit(per_params, per_bstats, weights,
                                    p_choose, jnp.asarray(A),
                                    jnp.asarray(pair_c),
                                    jnp.asarray(pair_n), self.data, rngs,
                                    self.round_lr(round_idx))
            n_samples = float(np.sum(self._n_train_host
                                     [: self.real_clients]))
            self.stat_info["sum_training_flops"] += (
                flops_per_sample * cfg.optim.epochs * n_samples)
            self.stat_info["sum_comm_params"] += float(
                n_model_transfers * n_params)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                mp = self._eval_p(per_params, per_bstats)
                self.stat_info["person_test_acc"].append(mp["acc"])
                self.log.metrics(round_idx, train_loss=loss, personal=mp)
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "personal_acc": mp["acc"]})
            self.maybe_checkpoint(round_idx, {
                "per_params": per_params, "per_bstats": per_bstats,
                "weights": weights, "p_choose": p_choose,
                "history": history})
        m_person = self._eval_p(per_params, per_bstats)
        self.log.metrics(-1, personal=m_person)
        return {"personal_params": per_params, "weights": weights,
                "p_choose": p_choose, "history": history,
                "final_personal": m_person}
