"""Local-only baseline: every client trains its own model forever, no
communication (fedml_api/standalone/local/local_api.py:51-80).

The whole federation's persistent states live as one stacked pytree; every
round is one vmapped/sharded jitted program over ALL clients. The optimizer
is re-created each round (reference builds a fresh torch SGD per call).

DECLARED through the round-program builder (engines/program.py, ROADMAP
item 1(a)): the carry is the per-client state stacks, the train stage is
the vmapped/sharded local pass, and a custom aggregate stage simply
promotes the trained stacks to next round's carry (there is no server
aggregation in a local-only run). The declaration is what buys the
engine fused ``--rounds_per_dispatch K`` windows and ``--client_mesh``
cohort sharding — K=4 fused == 4x K=1 BITWISE (tests/test_program.py)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.engines.base import FederatedEngine


class LocalEngine(FederatedEngine):
    name = "local"
    # Streaming (cohort > HBM): clients are fully independent, so the
    # streamed round trains client CHUNKS against host-fetched shards and
    # concatenates the resident per-client state back (same chunked shape
    # as DisPFL's streamed round, minus any consensus).
    supports_streaming = True
    supports_cohort_sharding = True  # the train stage (every client,
    # every round) shards over the --client_mesh like dpsgd's; there is
    # no aggregation tail to replicate

    # ---------- the declared round (engines/program.py) ----------

    def round_stages(self):
        return round_program.RoundStages(
            carry=("per_params", "per_bstats"),
            train=self._train_stage,
            aggregate=self._aggregate_stage,
            outputs=("loss",),
            gathers_cohort=False,
            window_extras=self._window_extras,
        )

    def _train_stage(self, ctx) -> round_program.TrainOut:
        """Every client trains its own persistent model — vmapped, or
        sharded over the client mesh (perms hoisted out of the
        partition, parallel/cohort.py)."""
        trainer = self.trainer
        o = self.cfg.optim
        max_samples = self._max_samples()
        lr = ctx.lr

        def local(p, b, rng, Xc, yc, nc, perms_c=None):
            cs = ClientState(params=p, batch_stats=b,
                             opt_state=trainer.opt.init(p), rng=rng)
            cs, loss = trainer.local_train(
                cs, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                perms=perms_c)
            return cs.params, cs.batch_stats, loss

        new_p, new_b, losses = ctx.client_map(
            local, ctx.carry["per_params"], ctx.carry["per_bstats"],
            ctx.rngs, ctx.Xs, ctx.ys, ctx.ns,
            hoisted=(lambda: ctx.local_perms(ctx.rngs, ctx.ns,
                                             o.epochs),))
        return round_program.TrainOut(
            losses=losses, extra={"new_p": new_p, "new_b": new_b})

    def _aggregate_stage(self, ctx, upload, w, tr):
        """No server aggregation: the trained stacks ARE next round's
        carry; the round's scalar is the sample-weighted mean loss
        (bitwise the legacy ``_round_jit``'s)."""
        mean_loss = jnp.sum(tr.losses * w) / jnp.maximum(jnp.sum(w),
                                                         1e-9)
        return ({"per_params": tr.extra["new_p"],
                 "per_bstats": tr.extra["new_b"]},
                {"loss": mean_loss})

    def _window_extras(self, round_idx: int, k: int
                       ) -> round_program.WindowInputs:
        """Window prologue: no sampling (every client trains every
        round), just the stacked per-round rngs/lrs."""
        C = self.num_clients
        for off in range(k):
            self.log.info("################ round %d: local-only cohort "
                          "(fused window of %d)", round_idx + off, k)
        rngs = jnp.stack([self.per_client_rngs(round_idx + off,
                                               np.arange(C))
                          for off in range(k)])
        lrs = jnp.asarray([self.round_lr(round_idx + off)
                           for off in range(k)], jnp.float32)
        return round_program.WindowInputs(
            sampled=None, idx=None, rngs=rngs, lrs=lrs, byz=None, k=k,
            n_real=None)

    # ---------- legacy-signature program adapters ----------

    @functools.cached_property
    def _round_jit(self):
        prog = self.program.round_jit(sharded=self._cohort_on)

        def round_call(per_params, per_bstats, data, rngs, lr):
            return prog((per_params, per_bstats), data, (), None, rngs,
                        lr)

        return round_call

    @functools.cached_property
    def _block_jit(self):
        # the streamed chunk program consumes gathered per-chunk copies
        # (stream_map_train_chunks builds them fresh each chunk)
        trainer = self.trainer
        o = self.cfg.optim
        max_samples = self._max_samples()

        def block(per_params, per_bstats, rngs, X, y, n, lr):
            def local(p, b, rng, Xc, yc, nc):
                cs = ClientState(params=p, batch_stats=b,
                                 opt_state=trainer.opt.init(p), rng=rng)
                cs, loss = trainer.local_train(
                    cs, Xc, yc, nc, lr, epochs=o.epochs,
                    batch_size=o.batch_size, max_samples=max_samples)
                return cs.params, cs.batch_stats, loss

            return jax.vmap(local)(per_params, per_bstats, rngs, X, y, n)

        return jax.jit(block, donate_argnums=self._donate_argnums(0, 1))

    def _round_streaming(self, per_params, per_bstats, rngs, lr):
        (new_p, new_b), losses = self.stream_map_train_chunks(
            self._block_jit, (per_params, per_bstats), rngs, lr)
        w = jnp.asarray(self._n_train_host, jnp.float32)
        mean_loss = jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1e-9)
        return new_p, new_b, mean_loss

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        per = self.broadcast_states(
            ClientState(params=gs.params, batch_stats=gs.batch_stats,
                        opt_state=None, rng=None), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats
        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            history = restored["history"]
        # fused K-round windows (builder-owned, ROADMAP 1(a)): the
        # window planner pins eval/checkpoint rounds to boundaries, so
        # the fused driver's observable behavior matches the per-round
        # loop
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                ((per_params, per_bstats), _, outs,
                 wi) = self.program.run_window(
                    (per_params, per_bstats), round_idx, k)
                loss, k = outs["loss"][-1], wi.k
                round_idx += k - 1
            elif self.stream is not None:
                rngs = self.per_client_rngs(round_idx,
                                            np.arange(self.num_clients))
                per_params, per_bstats, loss = self._round_streaming(
                    per_params, per_bstats, rngs,
                    self.round_lr(round_idx))
            else:
                rngs = self.per_client_rngs(round_idx,
                                            np.arange(self.num_clients))
                per_params, per_bstats, loss = self._round_jit(
                    per_params, per_bstats, self.data, rngs,
                    self.round_lr(round_idx))
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self._eval_p(per_params, per_bstats)
                # the shared OBS/health boundary (engines/base.py) —
                # the eval above already synced
                self._flush_nonfinite(round_idx)
                self.stat_info["person_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss, **m)
                history.append({"round": round_idx,
                                "train_loss": float(loss), **m})
            self.maybe_checkpoint(round_idx, {
                "per_params": per_params, "per_bstats": per_bstats,
                "history": history})
            round_idx += 1
        m = self._eval_p(per_params, per_bstats)
        self.log.metrics(-1, personal=m)
        return {"personal_params": per_params,
                "personal_batch_stats": per_bstats, "history": history,
                "final_personal": m}
