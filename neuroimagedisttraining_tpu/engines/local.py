"""Local-only baseline: every client trains its own model forever, no
communication (fedml_api/standalone/local/local_api.py:51-80).

The whole federation's persistent states live as one stacked pytree; every
round is one vmapped/sharded jitted program over ALL clients. The optimizer
is re-created each round (reference builds a fresh torch SGD per call)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine


class LocalEngine(FederatedEngine):
    name = "local"
    # Streaming (cohort > HBM): clients are fully independent, so the
    # streamed round trains client CHUNKS against host-fetched shards and
    # concatenates the resident per-client state back (same chunked shape
    # as DisPFL's streamed round, minus any consensus).
    supports_streaming = True

    def _local_block(self, per_params, per_bstats, rngs, X, y, n, lr):
        """Vmapped local training over a block of clients."""
        trainer = self.trainer
        o = self.cfg.optim
        max_samples = self._max_samples()

        def local(p, b, rng, Xc, yc, nc):
            cs = ClientState(params=p, batch_stats=b,
                             opt_state=trainer.opt.init(p), rng=rng)
            cs, loss = trainer.local_train(
                cs, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples)
            return cs.params, cs.batch_stats, loss

        return jax.vmap(local)(per_params, per_bstats, rngs, X, y, n)

    @functools.cached_property
    def _round_jit(self):
        def round_fn(per_params, per_bstats, data, rngs, lr):
            new_p, new_b, losses = self._local_block(
                per_params, per_bstats, rngs, data.X_train, data.y_train,
                data.n_train, lr)
            w = data.n_train.astype(jnp.float32)
            mean_loss = jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1e-9)
            return new_p, new_b, mean_loss

        # donation: the persistent per-client stacks are consumed; the
        # driver rebinds them on return
        return jax.jit(round_fn, donate_argnums=self._donate_argnums(0, 1))

    @functools.cached_property
    def _block_jit(self):
        # the streamed chunk program consumes gathered per-chunk copies
        # (stream_map_train_chunks builds them fresh each chunk)
        return jax.jit(self._local_block,
                       donate_argnums=self._donate_argnums(0, 1))

    def _round_streaming(self, per_params, per_bstats, rngs, lr):
        (new_p, new_b), losses = self.stream_map_train_chunks(
            self._block_jit, (per_params, per_bstats), rngs, lr)
        w = jnp.asarray(self._n_train_host, jnp.float32)
        mean_loss = jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1e-9)
        return new_p, new_b, mean_loss

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        per = self.broadcast_states(
            ClientState(params=gs.params, batch_stats=gs.batch_stats,
                        opt_state=None, rng=None), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats
        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            history = restored["history"]
        for round_idx in range(start, cfg.fed.comm_round):
            rngs = self.per_client_rngs(round_idx,
                                        np.arange(self.num_clients))
            if self.stream is not None:
                per_params, per_bstats, loss = self._round_streaming(
                    per_params, per_bstats, rngs, self.round_lr(round_idx))
            else:
                per_params, per_bstats, loss = self._round_jit(
                    per_params, per_bstats, self.data, rngs,
                    self.round_lr(round_idx))
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self._eval_p(per_params, per_bstats)
                self.stat_info["person_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss, **m)
                history.append({"round": round_idx,
                                "train_loss": float(loss), **m})
            self.maybe_checkpoint(round_idx, {
                "per_params": per_params, "per_bstats": per_bstats,
                "history": history})
        m = self._eval_p(per_params, per_bstats)
        self.log.metrics(-1, personal=m)
        return {"personal_params": per_params,
                "personal_batch_stats": per_bstats, "history": history,
                "final_personal": m}
