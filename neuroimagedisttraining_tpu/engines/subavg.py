"""Sub-FedAvg: per-client iterative magnitude pruning with an accept-test,
mask-overlap-count averaging (fedml_api/standalone/subavg/).

Behavior parity (subavg_api.py:43-92, subavg/client.py:36-64,
subavg/my_model_trainer.py:48-82):

- Initial masks are all-ones (my_model_trainer.py:28-40); every client
  maintains a personal mask that only ever loses entries.
- Per round, sampled clients receive ``w_global * mask_c`` and train with
  masked gradients (``param.grad *= mask``, my_model_trainer.py:66-68; with
  pruned weights starting at zero this equals our post-step re-mask).
- Prune candidates: ``fake_prune`` percentile masks computed after the FIRST
  epoch (m1) and after the LAST epoch (m2) (my_model_trainer.py:76-79);
  with epochs==1, m1 == m2 and pruning never triggers — reference parity.
- Accept-test (client.py:50-58): prune only if
  (a) hamming-fraction(m1, m2) > ``dist_thresh``,
  (b) pre-train density of the client model > ``dense_ratio`` (floor), and
  (c) accuracy of the m2-pruned trained model on the client's TRAINING data
      (local_test(..., False)) > ``acc_thresh``.
  On accept: weights *= m2 and the personal mask becomes m2.
- Aggregation (subavg_api.py:123-140): per weight, ``count`` = number of
  sampled clients whose OLD mask keeps it; server value becomes
  ``sum_i w_i / count`` where count > 0, and keeps its previous value where
  no sampled client keeps the weight (the reference's non-finite guard).
- Personalized model of client c = ``w_global * mask_c``
  (_local_test_on_all_clients, subavg_api.py:150-170).

The round is DECLARED through the round-program builder
(engines/program.py, ISSUE 11): the per-client prune/accept composite is
the train stage, the overlap-count average is a CUSTOM aggregate stage
(it replaces the weighted mean — order-statistic defenses have nothing
to select over a count-quotient), and the personal-mask scatter is the
update stage. The builder supplies fused ``--rounds_per_dispatch K``
windows (per-round ``up_nnz``/dist/accept scalars come back [K]-stacked)
and ``--client_mesh`` cohort sharding of the per-client composite — the
two-call epoch split hoists BOTH calls' permutations out of the
partition (ctx.rng_after_local_train replays the rng chain).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.losses import binary_auc
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.engines.base import FederatedEngine
from neuroimagedisttraining_tpu.obs import health as obs_health
from neuroimagedisttraining_tpu.ops import flops as flops_ops
from neuroimagedisttraining_tpu.ops import prune as P
from neuroimagedisttraining_tpu.ops.masks import ones_mask
from neuroimagedisttraining_tpu.utils import pytree as pt


class SubFedAvgEngine(FederatedEngine):
    name = "subavg"
    # Streaming (cohort > HBM): the round only consumes the SAMPLED clients'
    # data shards (same shape as FedAvg's streaming round); per-client masks
    # and the global model stay device-resident.
    supports_streaming = True
    supports_cohort_sharding = True  # the per-client prune/accept
    # composite runs as unbatched loops under the --client_mesh shard_map
    #: current per-client personal masks, tracked for the codec handoff
    _mask_pers = None

    def wire_masks(self):
        """Mask handoff (codec/): the per-client personal masks, stacked
        [C, ...]. They evolve by pruning (monotone entry loss) on
        accepted rounds, so a cross-silo deployment ships the bitmap
        frame with the surviving values (as DisPFL)."""
        return self._mask_pers

    # ---------- the declared round (engines/program.py) ----------

    def round_stages(self):
        return round_program.RoundStages(
            carry=("params", "batch_stats", "mask_pers"),
            train=self._train_stage,
            aggregate=self._aggregate_stage,
            update=self._update_stage,
            outputs=("loss", "mean_dist", "n_accept", "up_nnz"),
            health=self._health_stage,
            health_outputs=obs_health.MASK_STAT_NAMES,
        )

    def _health_stage(self, ctx, tr, new_carry) -> dict:
        """Mask-health leg (ISSUE 15, armed under ``--health_stats``):
        density of the sampled cohort's ACCEPTED masks plus their
        round-over-round overlap/churn vs the masks the cohort entered
        the round with — the in-dispatch mirror of
        ``warn_if_masks_collapsed``'s post-hoc nnz fetch."""
        return round_program.mask_health_stats(tr.extra["new_m"],
                                               tr.extra["Ms"])

    def _train_stage(self, ctx) -> round_program.TrainOut:
        """The per-client composite: masked epoch-1 train -> fake_prune
        m1 -> masked tail epochs -> fake_prune m2 -> accept-test. On the
        sharded path both ``local_train`` calls' epoch permutations are
        hoisted out of the partition: the tail call's entry rngs are the
        chain ``local_train`` leaves after epoch 1, replayed outside the
        shard_map (ctx.rng_after_local_train)."""
        trainer = self.trainer
        o = self.cfg.optim
        s = self.cfg.sparsity
        params = ctx.carry["params"]
        bstats = ctx.carry["batch_stats"]
        Xs, ys, ns = ctx.Xs, ctx.ys, ctx.ns
        lr = ctx.lr
        max_samples = self._max_samples()
        epochs_tail = max(o.epochs - 1, 0)
        Ms = pt.tree_stack_index(ctx.carry["mask_pers"], ctx.sampled_idx)

        def per_client(m, rng, Xc, yc, nc, perms1_c=None, perms2_c=None):
            w_per = jax.tree.map(jnp.multiply, params, m)
            dense = P.density_all_leaves(w_per)
            cs_c = ClientState(params=w_per, batch_stats=bstats,
                               opt_state=trainer.opt.init(w_per),
                               rng=rng)
            # epoch 1, then fake_prune -> m1
            cs_c, loss1 = trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=1, batch_size=o.batch_size,
                max_samples=max_samples, mask=m, perms=perms1_c)
            m1 = P.fake_prune(s.each_prune_ratio, cs_c.params, m)
            # remaining epochs, then fake_prune -> m2
            if epochs_tail:
                cs_c, loss2 = trainer.local_train(
                    cs_c, Xc, yc, nc, lr, epochs=epochs_tail,
                    batch_size=o.batch_size, max_samples=max_samples,
                    mask=m, perms=perms2_c)
                loss = (loss1 + epochs_tail * loss2) / o.epochs
            else:
                loss = loss1
            m2 = P.fake_prune(s.each_prune_ratio, cs_c.params, m)
            dist = P.mask_distance_mean(m1, m2)

            # accept-test: acc of the m2-pruned model on TRAIN data
            pruned = jax.tree.map(jnp.multiply, cs_c.params, m2)
            valid = jnp.arange(Xc.shape[0]) < nc
            metrics = trainer.evaluate(pruned, cs_c.batch_stats, Xc, yc,
                                       valid)
            acc = metrics["test_correct"] / jnp.maximum(
                metrics["test_total"], 1.0)
            accept = ((dist > s.dist_thresh)
                      & (dense > s.dense_ratio)
                      & (acc > s.acc_thresh))
            sel = lambda a, b: jax.tree.map(
                lambda x, y: jnp.where(accept, x, y), a, b)
            new_params = sel(pruned, cs_c.params)
            new_mask = sel(m2, m)
            return (new_params, cs_c.batch_stats, new_mask, loss, dist,
                    accept)

        hoisted = [lambda: ctx.local_perms(ctx.rngs, ns, 1)]
        if epochs_tail:
            hoisted.append(lambda: ctx.local_perms(
                ctx.rng_after_local_train(ctx.rngs, 1), ns, epochs_tail))
        (new_p, new_b, new_m, losses, dists, accepts) = ctx.client_map(
            per_client, Ms, ctx.rngs, Xs, ys, ns, hoisted=tuple(hoisted))
        return round_program.TrainOut(
            losses=losses,
            upload={"params": new_p, "batch_stats": new_b},
            extra={"Ms": Ms, "new_m": new_m, "dists": dists,
                   "accepts": accepts})

    def _aggregate_stage(self, ctx, upload, w, tr):
        """Overlap-count aggregation against the OLD masks
        (subavg_api.py:123-140) — a custom aggregate stage: per weight,
        ``count`` = sampled clients whose old mask keeps it, server
        value = sum/count where count > 0, previous value elsewhere.
        Mesh-tiling pad entries (ns == 0, possibly duplicate ids from
        stream_sampling) contribute nothing."""
        params = ctx.carry["params"]
        Ms, new_m = tr.extra["Ms"], tr.extra["new_m"]
        new_p, new_b = upload["params"], upload["batch_stats"]
        real = (ctx.ns > 0).astype(jnp.float32)
        rb = lambda x: real.reshape((-1,) + (1,) * (x.ndim - 1))
        count = jax.tree.map(lambda m: jnp.sum(m * rb(m), axis=0), Ms)
        summed = jax.tree.map(
            lambda p: jnp.sum(p.astype(jnp.float32) * rb(p), axis=0),
            new_p)
        agg = jax.tree.map(
            lambda sm, ct, old: jnp.where(ct > 0, sm
                                          / jnp.maximum(ct, 1.0), old),
            summed, count, params)
        n_real = jnp.maximum(jnp.sum(real), 1.0)
        new_bstats = jax.tree.map(
            lambda b: jnp.sum(b.astype(jnp.float32) * rb(b), axis=0)
            / n_real, new_b)
        mean_loss = jnp.sum(tr.losses * real) / n_real
        # per-sampled-client nnz of the NEW masks: the true uplink volume
        # (reference nonzero-comm metric, model_trainer.py:49-53)
        up_nnz = jax.vmap(lambda m: sum(
            jnp.sum(x) for x in jax.tree.leaves(m)))(new_m)
        return ({"params": agg, "batch_stats": new_bstats},
                {"loss": mean_loss,
                 "mean_dist": jnp.sum(tr.extra["dists"] * real) / n_real,
                 "n_accept": jnp.sum(tr.extra["accepts"] * real),
                 "up_nnz": jnp.sum(up_nnz * real)})

    def _update_stage(self, ctx, tr, new_carry) -> dict:
        """Scatter updated personal masks back; pad entries are dropped,
        never written (base.scatter_sampled_rows)."""
        mask_pers = self.scatter_sampled_rows(
            ctx.carry["mask_pers"], tr.extra["new_m"], ctx.sampled_idx,
            ctx.ns > 0)
        return {"mask_pers": mask_pers}

    # ---------- legacy-signature program adapters ----------

    @functools.cached_property
    def _round_jit(self):
        prog = self.program.round_jit()

        def round_call(params, bstats, mask_pers, data, sampled_idx,
                       rngs, lr):
            return prog((params, bstats, mask_pers), data, (),
                        sampled_idx, rngs, lr)

        return round_call

    def _sharded_round_jit(self, n_real: int):
        prog = self.program.round_jit(n_real=n_real)

        def sharded_round_call(params, bstats, mask_pers, data,
                               sampled_idx, rngs, lr):
            return prog((params, bstats, mask_pers), data, (),
                        sampled_idx, rngs, lr)

        return sharded_round_call

    @functools.cached_property
    def _round_stream_jit(self):
        prog = self.program.stream_jit()

        def stream_round_call(params, bstats, mask_pers, Xs, ys, ns,
                              sampled_idx, rngs, lr):
            return prog((params, bstats, mask_pers), (), Xs, ys, ns,
                        sampled_idx, rngs, lr)

        return stream_round_call

    # ---------- personalized (masked-global) evaluation ----------

    @functools.cached_property
    def _eval_masked_global_jit(self):
        """Personalized eval: client c evaluates w_global * mask_c
        (subavg_api.py:150-170)."""
        trainer = self.trainer

        def eval_all(params, bstats, mask_pers, X, y, n):
            def per_client(m, Xc, yc, nc):
                p = jax.tree.map(jnp.multiply, params, m)
                valid = jnp.arange(Xc.shape[0]) < nc
                mt = trainer.evaluate(p, bstats, Xc, yc, valid)
                auc = binary_auc(mt["scores"], yc, valid)
                return mt["test_correct"], mt["test_loss"], mt["test_total"], auc

            return jax.vmap(per_client)(mask_pers, X, y, n)

        return jax.jit(eval_all)

    def eval_masked_global(self, params, bstats, mask_pers) -> dict:
        if self.stream is not None:
            return self.eval_masked_global_stream(params, bstats, mask_pers)
        X, y, n = self.data.X_test, self.data.y_test, self.data.n_test
        if self.cfg.fed.ci:
            X, y, n = X[:1], y[:1], n[:1]
            mask_pers = pt.tree_stack_index(mask_pers, slice(0, 1))
        out = self._eval_masked_global_jit(params, bstats, mask_pers, X, y, n)
        return self._summarize(*out, n=n)

    def eval_masked_global_stream(self, params, bstats, mask_pers) -> dict:
        """Streamed variant: test shards arrive in client chunks; each
        chunk's personal masks are gathered from the resident stack."""
        chunk = self._eval_chunk_size()
        parts, ns = [], []
        for ch in self.stream.eval_chunks(chunk, "test"):
            m = pt.tree_stack_index(mask_pers, ch.padded_ids)
            out = self._eval_masked_global_jit(params, bstats, m, ch.X,
                                               ch.y, ch.n)
            parts.append(tuple(np.asarray(o)[: len(ch.ids)] for o in out))
            ns.append(np.asarray(jax.device_get(ch.n))[: len(ch.ids)])
            if self.cfg.fed.ci:
                break
        cat = [np.concatenate([p[i] for p in parts]) for i in range(4)]
        n_all = np.concatenate(ns)
        if self.cfg.fed.ci:
            cat, n_all = [c[:1] for c in cat], n_all[:1]
        return self._summarize(*cat, n=n_all)

    # ---------- driver ----------

    def _account_round(self, sampled, up_nnz, n_params, flops_per_sample
                       ) -> None:
        """Per-round host-side stat accounting, shared by the per-round
        and fused-window drivers. ``up_nnz`` is the round's device
        scalar (already synced by the caller)."""
        n_samples = float(np.sum(self._n_train_host[sampled]))
        self.stat_info["sum_training_flops"] += (
            flops_per_sample * self.cfg.optim.epochs * n_samples)
        # down: the dense w_global per sampled client; up: the pruned
        # client models' TRUE nonzero count (reference nonzero-comm
        # metric, model_trainer.py:49-53) — computed inside the round
        # program, so the "device pull" is one scalar per round
        self.stat_info["sum_comm_params"] += (
            n_params * len(sampled) + float(up_nnz))

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        params, bstats = gs.params, gs.batch_stats
        mask_pers = self.broadcast_states(ones_mask(params),
                                          self.num_clients)
        flops_per_sample = flops_ops.count_training_flops_per_sample(
            self.trainer.model, params,
            self.trainer._prep(self.sample_input()), batch_stats=bstats)
        n_params = pt.tree_size(params)

        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            mask_pers, history = restored["mask_pers"], restored["history"]
        if self.stream is not None:
            self.stream.prefetch_train(*self.stream_sampling(start))
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                ((params, bstats, mask_pers), _, outs,
                 wi) = self.program.run_window(
                    (params, bstats, mask_pers), round_idx, k)
                k = wi.k
                loss, mean_dist = outs["loss"][-1], outs["mean_dist"][-1]
                n_accept = outs["n_accept"][-1]
                # one batched sync for the window's K per-round upload
                # nnz scalars (the sequential loop syncs one per round)
                nnz_rounds = np.asarray(jax.device_get(outs["up_nnz"]))
                for off, s in enumerate(wi.sampled):
                    self._account_round(s, nnz_rounds[off], n_params,
                                        flops_per_sample)
                round_idx += k - 1
            else:
                sampled = self.client_sampling(round_idx)
                self.log.info("################ round %d: clients %s",
                              round_idx, sampled.tolist())
                if self.stream is not None:
                    fed_ids, n_real = self.stream_sampling(round_idx,
                                                           sampled)
                    rngs = self.per_client_rngs(round_idx, fed_ids)
                    Xs, ys, ns = self.stream.get_train(fed_ids, n_real)
                    if round_idx + 1 < cfg.fed.comm_round:
                        self.stream.prefetch_train(
                            *self.stream_sampling(round_idx + 1))
                    (params, bstats, mask_pers, loss, mean_dist, n_accept,
                     up_nnz) = self._round_stream_jit(
                        params, bstats, mask_pers, Xs, ys, ns,
                        jnp.asarray(fed_ids), rngs,
                        self.round_lr(round_idx))
                else:
                    # cohort sharding (ISSUE 6): the sharded program
                    # gathers the mesh-padded set; the accounting stays
                    # on the REAL sampled set
                    ids, round_prog = self._cohort_round_prog(sampled)
                    rngs = self.per_client_rngs(round_idx, ids)
                    (params, bstats, mask_pers, loss, mean_dist, n_accept,
                     up_nnz) = round_prog(
                        params, bstats, mask_pers, self.data,
                        jnp.asarray(ids), rngs, self.round_lr(round_idx))
                self._account_round(sampled, up_nnz, n_params,
                                    flops_per_sample)
            self._mask_pers = mask_pers
            # NaN-poisoned-mask diagnosability (ADVICE r5): a NaN in the
            # trained params poisons fake_prune's percentile into an
            # all-False m2; if the accept-test then fires, the client's
            # personal mask collapses — make it visible immediately
            # (fused windows check once per window, at the boundary the
            # driver already syncs)
            self.warn_if_masks_collapsed(mask_pers, round_idx)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                mp = self.eval_masked_global(params, bstats, mask_pers)
                # the shared OBS/health boundary (engines/base.py): the
                # eval above already synced, so the queued in-dispatch
                # health stats drain here (subavg has no n_bad output —
                # the flush is its health/stat boundary, not a
                # non-finite one)
                self._flush_nonfinite(round_idx)
                self.stat_info["person_test_acc"].append(mp["acc"])
                self.log.metrics(round_idx, train_loss=loss,
                                 personal=mp,
                                 mean_mask_dist=float(mean_dist),
                                 prunes_accepted=int(n_accept))
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "personal_acc": mp["acc"],
                                "mean_mask_dist": float(mean_dist),
                                "prunes_accepted": int(n_accept)})
            self.maybe_checkpoint(round_idx, {
                "params": params, "batch_stats": bstats,
                "mask_pers": mask_pers, "history": history})
            round_idx += 1
        self._flush_nonfinite(cfg.fed.comm_round - 1)
        m_person = self.eval_masked_global(params, bstats, mask_pers)
        self.log.metrics(-1, personal=m_person)
        densities = np.asarray(jax.device_get(jax.vmap(
            P.density_all_leaves)(jax.vmap(
                lambda m: jax.tree.map(jnp.multiply, params, m))(mask_pers))))
        return {"params": params, "batch_stats": bstats,
                "mask_pers": mask_pers, "history": history,
                "final_personal": m_person,
                "client_densities": densities[: self.real_clients]}
