"""Algorithm engines (the reference's fedml_api/standalone family)."""

from neuroimagedisttraining_tpu.engines.base import FederatedEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.fedavg import FedAvgEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.fedprox import FedProxEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.salientgrads import SalientGradsEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.local import LocalEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.ditto import DittoEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.dpsgd import DPSGDEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.dispfl import DisPFLEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.subavg import SubFedAvgEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.fedfomo import FedFomoEngine  # noqa: F401
from neuroimagedisttraining_tpu.engines.turboaggregate import TurboAggregateEngine  # noqa: F401

ENGINES = {
    "fedavg": FedAvgEngine,
    "fedprox": FedProxEngine,
    "salientgrads": SalientGradsEngine,
    "sailentgrads": SalientGradsEngine,  # reference spelling
    "local": LocalEngine,
    "ditto": DittoEngine,
    "dpsgd": DPSGDEngine,
    "dispfl": DisPFLEngine,
    "subavg": SubFedAvgEngine,
    "sub-fedavg": SubFedAvgEngine,
    "fedfomo": FedFomoEngine,
    "turboaggregate": TurboAggregateEngine,
}


def create_engine(name: str, *args, **kwargs) -> FederatedEngine:
    try:
        cls = ENGINES[name.lower()]
    except KeyError:
        raise ValueError(f"unknown algorithm {name!r}; have {sorted(ENGINES)}")
    return cls(*args, **kwargs)
