"""TurboAggregate: FedAvg with secure (secret-shared) aggregation.

The reference's TurboAggregate is a vanilla-FedAvg scaffold
(TA_trainer.py:38-97 — TA_topology_vanilla is an explicit stub) plus a
standalone finite-field MPC toolkit (mpc_function.py:4-275). Here the
toolkit (ops/mpc.py) is actually WIRED into the round: each sampled client's
weighted model is fixed-point-quantized into GF(p), split into additive
secret shares (Gen_Additive_SS semantics), the server accumulates each share
SLOT across all clients and only combines slots at the very end, and the
aggregate is dequantized — the server never sees an individual client's
update in the clear (every pre-final intermediate is uniformly-random
masked; tests/test_mpc.py asserts it). Exactness: the share sum equals the plain
weighted sum mod p, so the only deviation from FedAvg is fixed-point
rounding (2^-frac_bits per parameter, default 2^-16).

Local training is the same one-program SPMD round as FedAvg. The MPC stage
runs on the accelerator by default (ops/mpc_device.py: the quantize /
share / slot-accumulate pipeline as jitted uint32 mod-p ops — no host
round-trip); ``mpc_backend="host"`` keeps the numpy path that models the
client<->server communication boundary (which the multi-aggregator
cross-silo deployment exercises over real processes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.fedavg import FedAvgEngine
from neuroimagedisttraining_tpu.ops import mpc


class TurboAggregateEngine(FedAvgEngine):
    name = "turboaggregate"
    # Streaming (cohort > HBM): the train-only stage consumes just the
    # sampled clients' shards (FedAvg's streaming shape); the MPC stage
    # follows mpc_backend (device-jitted by default). The streamed round
    # loop itself is inherited from FedAvgEngine._train_streaming via
    # _round_stream_jit below.
    supports_streaming = True
    # Inherits FedAvgEngine's train loop but NOT its codec branch: its
    # round replaces the plain aggregation with the MPC share pipeline,
    # whose GF(p) field embedding the codec's delta/top-k/quant stages
    # would corrupt (same incompatibility as cross_silo's
    # SecureFedAvgServer, and the inherited codec call path would pass
    # this engine's 6-arg round program 7 args anyway).
    supports_wire_codec = False
    # Byzantine simulation + order-statistic defenses are likewise OUT:
    # secure aggregation is a linear sum over additive shares — the
    # server never observes individual updates, so trimmed-mean/Krum
    # style order statistics have nothing to select over (the same
    # tension ARCHITECTURE.md's Byzantine-robustness section documents
    # for cross_silo's SecureFedAvgServer). Clipping still composes:
    # each silo clips its OWN update before sharing it.
    supports_byz_faults = False
    # Cohort sharding (ISSUE 6) is likewise out: the round crosses the
    # host for the MPC share pipeline every round (the client<->server
    # boundary is the point), and this engine overrides the round
    # programs the sharded driver would dispatch — --client_mesh falls
    # back to the unsharded round with the logged reason below.
    supports_cohort_sharding = False
    supported_defenses = robust.CLIP_DEFENSES

    def round_stages(self):
        # no declared stages: the round is a host-driven two-stage
        # dispatch (train program -> MPC share/aggregate program with a
        # per-round host-side mask seed), which the scan-fused builder
        # cannot express — the overrides below name the table reasons
        return None

    def cohort_fallback_key(self) -> str | None:
        return "mpc-host-boundary"

    def _train_only_body(self, params, bstats, Xs, ys, ns, rngs, lr):
        """Local training WITHOUT the in-program aggregation: returns the
        stacked client params (pre-weighted by n_c / sum n) for the MPC
        stage, plus the plain-averaged batch_stats (BN stats are not secret-
        shared — parity with robust aggregation's is_weight_param exclusion)."""
        trainer = self.trainer
        o = self.cfg.optim
        max_samples = self._max_samples()
        S = Xs.shape[0]
        cs = ClientState(
            params=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), params),
            batch_stats=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), bstats),
            opt_state=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape),
                trainer.opt.init(params)),
            rng=rngs,
        )

        def local(cs_c, Xc, yc, nc):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples)

        cs, losses = jax.vmap(local)(cs, Xs, ys, ns)
        w = ns.astype(jnp.float32)
        # non-finite upload guard (ISSUE 5 satellite): a NaN client
        # would poison the GF(p) quantization AND the plain bstats mean;
        # its row becomes the broadcast reference at weight 0
        upload = {"params": cs.params, "batch_stats": cs.batch_stats}
        ref = {"params": params, "batch_stats": bstats}
        finite = robust.finite_per_client(upload)
        upload = robust.replace_nonfinite_clients(upload, ref, finite)
        n_bad = jnp.sum(~finite).astype(jnp.int32)
        w = w * finite.astype(jnp.float32)
        wn = w / jnp.maximum(jnp.sum(w), 1e-12)
        # robust defenses apply BEFORE weighting/sharing, same stage as
        # FedAvgEngine._round_body (clipping composes with secure agg:
        # each silo clips its own update before secret-sharing it)
        f = self.cfg.fed
        client_params = robust.defend_stacked(
            upload["params"], params, defense=f.defense_type,
            norm_bound=f.norm_bound, stddev=f.stddev, rngs=cs.rng)
        weighted = jax.tree.map(
            lambda x: x.astype(jnp.float32)
            * wn.reshape((-1,) + (1,) * (x.ndim - 1)), client_params)
        # batch_stats are not secret-shared; route them through the
        # silo-aware aggregate so the non-MPC half of the round keeps the
        # two-level ICI/DCN layout (params cross the host MPC boundary
        # regardless — that boundary IS the cross-silo link)
        new_bstats = self.aggregate(upload["batch_stats"], w)
        safe_losses = jnp.where(jnp.isfinite(losses), losses, 0.0)
        mean_loss = jnp.sum(safe_losses * w) / jnp.maximum(jnp.sum(w),
                                                           1e-9)
        return weighted, new_bstats, mean_loss, n_bad

    @functools.cached_property
    def _train_only_jit(self):
        def round_fn(params, bstats, data, sampled_idx, rngs, lr):
            Xs = jnp.take(data.X_train, sampled_idx, axis=0)
            ys = jnp.take(data.y_train, sampled_idx, axis=0)
            ns = jnp.take(data.n_train, sampled_idx, axis=0)
            return self._train_only_body(params, bstats, Xs, ys, ns, rngs,
                                         lr)

        # donation: bstats only — the [S, ...]-stacked ``weighted`` output
        # has no input of matching shape, so donating ``params`` would be
        # an unusable donation (ignored with a warning), and the wrapper
        # below never rereads either input after dispatch
        return jax.jit(round_fn, donate_argnums=self._donate_argnums(1))

    @functools.cached_property
    def _train_only_stream_jit(self):
        return jax.jit(self._train_only_body,
                       donate_argnums=self._donate_argnums(1))

    def fused_fallback_key(self) -> str | None:
        # overrides FedAvg's: even the device MPC backend is a host-driven
        # two-stage dispatch (train program -> share/aggregate program with
        # a per-round host-side mask seed), and the host backend crosses
        # the process boundary by design
        return "mpc-host-stage"

    @functools.cached_property
    def _secure_agg_jit(self):
        from neuroimagedisttraining_tpu.ops import mpc_device

        f = self.cfg.fed

        def agg(weighted, key):
            return mpc_device.secure_aggregate_tree(
                weighted, key, f.mpc_n_shares, frac_bits=f.mpc_frac_bits)

        return jax.jit(agg)

    def secure_aggregate(self, weighted_stacked, call_idx: int):
        """Additive-share aggregation over GF(p): quantize each client's
        weighted update, share it ``mpc_n_shares`` ways, accumulate
        slot-major (share slot j across ALL clients before combining any
        slots), reconstruct. No server-side intermediate equals an
        individual client's quantized update (tested in tests/test_mpc.py
        for both backends).

        Default backend "device" runs the whole pipeline as jitted uint32
        mod-p ops on the accelerator (ops/mpc_device.py) — no host
        round-trip, round time ~FedAvg's (VERDICT r4 weak #3). Backend
        "host" keeps the numpy toolkit path that models the
        client<->server boundary (and is what the multi-aggregator
        cross-silo deployment exercises over real processes).

        The share randomness cancels EXACTLY in the sum (additive shares by
        construction), so the aggregate is independent of ``call_idx``/rng —
        the seed only decorrelates the masking material across calls."""
        f = self.cfg.fed
        if f.mpc_backend == "device":
            key = jax.random.fold_in(
                jax.random.key(self.cfg.seed * 7919 + 1), call_idx)
            return self._secure_agg_jit(weighted_stacked, key)
        if f.mpc_backend != "host":
            raise ValueError(f"unknown mpc_backend {f.mpc_backend!r} "
                             "(device | host)")
        rng = np.random.default_rng(self.cfg.seed * 7919 + call_idx)
        leaves, treedef = jax.tree.flatten(weighted_stacked)
        # ONE batched device_get for the whole tree: every copy_to_host
        # is issued before any blocks, so the per-leaf transfer round
        # trips overlap instead of serializing with the MPC compute
        # (~16 leaves x tunnel latency on this harness). The rng draw
        # order (per leaf, per client) is unchanged, so the aggregate is
        # bitwise-identical to the per-leaf formulation.
        host = [np.asarray(x) for x in jax.device_get(leaves)]  # [S, ...] each
        agg = [mpc.secure_sum(arr, n_shares=f.mpc_n_shares,
                              frac_bits=f.mpc_frac_bits, rng=rng)
               .astype(np.float32) for arr in host]
        out = jax.device_put(agg)  # one batched upload
        return jax.tree.unflatten(treedef, out)

    # mask-material seed counter; the aggregate itself is rng-independent
    # (see secure_aggregate), so resume determinism of the training result
    # is unaffected. Instance assignment (+= 1) shadows the class default.
    _mpc_calls = 0

    @functools.cached_property
    def _round_jit(self):
        """FedAvg's round program signature, with the aggregation swapped
        for the MPC path (two jitted stages on the default device backend;
        a host callback between them on mpc_backend='host')."""
        train_only = self._train_only_jit

        def round_fn(params, bstats, data, sampled_idx, rngs, lr):
            weighted, new_bstats, loss, n_bad = train_only(
                params, bstats, data, sampled_idx, rngs, lr)
            new_params = self.secure_aggregate(weighted, self._mpc_calls)
            self._mpc_calls += 1
            return new_params, new_bstats, loss, n_bad

        return round_fn  # wrapper (not one jit): tracks _mpc_calls and
        # dispatches the MPC stage per mpc_backend

    @functools.cached_property
    def _round_stream_jit(self):
        """Streamed counterpart consumed by the inherited
        FedAvgEngine._train_streaming loop: jitted train-only stage on the
        host-fetched shards, then the host-side MPC aggregation."""
        train_only = self._train_only_stream_jit

        def round_fn(params, bstats, Xs, ys, ns, rngs, lr):
            weighted, new_bstats, loss, n_bad = train_only(
                params, bstats, Xs, ys, ns, rngs, lr)
            new_params = self.secure_aggregate(weighted, self._mpc_calls)
            self._mpc_calls += 1
            return new_params, new_bstats, loss, n_bad

        return round_fn
