"""Ditto: personalized FL with a proximal personal track.

Behavior parity with fedml_api/standalone/ditto/ditto_api.py:40-78 +
ditto/my_model_trainer.py:38-68:

- Global track: sampled clients train the global model normally for
  ``epochs`` epochs; sample-weighted FedAvg.
- Personal track: each sampled client also trains its PERSISTENT personal
  model for ``local_epochs`` epochs, pulling toward the round's incoming
  global model after every step: ``w -= lr * lamda * (w - w_global)``
  (my_model_trainer.py:63-64).
- Evaluation reports the personal models (ditto_api.py:74-78).

Both tracks run inside one jitted round program, DECLARED through the
round-program builder (engines/program.py, ISSUE 11): the builder
supplies fused ``--rounds_per_dispatch K`` windows, ``--client_mesh``
cohort sharding of both training tracks, buffer donation, the Byzantine
attack plan + non-finite guard + ``--defense`` dispatch on the global
track's uploads (the personal track keeps each client's honest local
result), all as config knobs — none of which this engine had before the
builder.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.engines.base import FederatedEngine


class DittoEngine(FederatedEngine):
    name = "ditto"
    # Streaming (cohort > HBM): both tracks only consume the SAMPLED
    # clients' shards, so the streamed round has FedAvg's shape — data per
    # round on device, persistent personal state resident.
    supports_streaming = True
    supports_secure_quant = True  # default aggregate tail on the
    # global track — the secure fold protects exactly that upload
    supports_byz_faults = True  # the builder's attack stage hits the
    # global-track upload; the personal track stays honest
    supports_cohort_sharding = True  # both tracks run as unbatched
    # per-client loops under the --client_mesh shard_map
    supported_defenses = robust.DEFENSES

    # ---------- the declared round (engines/program.py) ----------

    def round_stages(self):
        return round_program.RoundStages(
            carry=("params", "batch_stats", "per_params", "per_bstats"),
            train=self._train_stage,
            update=self._update_stage,
            supports_attack=True,
        )

    def _train_stage(self, ctx) -> round_program.TrainOut:
        """Both tracks. Global: the incoming global model broadcast over
        the cohort, trained ``epochs`` epochs (its trained states are
        the round's upload). Personal: each sampled client's persistent
        model, trained ``local_epochs`` epochs with the proximal pull
        toward the round's incoming global model."""
        trainer = self.trainer
        o = self.cfg.optim
        f = self.cfg.fed
        params = ctx.carry["params"]
        bstats = ctx.carry["batch_stats"]
        Xs, ys, ns = ctx.Xs, ctx.ys, ctx.ns
        lr = ctx.lr
        S = Xs.shape[0]
        max_samples = self._max_samples()
        lamda = float(f.lamda)  # nidt: allow[trace-host-sync] -- cfg.fed.lamda is a static Python scalar bound at trace time, not a tracer

        def bcast(t):
            return jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), t)

        # -- global track --
        cs = ClientState(params=bcast(params), batch_stats=bcast(bstats),
                         opt_state=bcast(trainer.opt.init(params)),
                         rng=ctx.rngs)

        def global_local(cs_c, Xc, yc, nc, perms_c=None):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                perms=perms_c)

        cs, losses = ctx.client_map(
            global_local, cs, Xs, ys, ns,
            hoisted=(lambda: ctx.local_perms(ctx.rngs, ns, o.epochs),))

        # -- personal track (persistent, proximal to incoming global) --
        pp = jax.tree.map(lambda t: jnp.take(t, ctx.sampled_idx, axis=0),
                          ctx.carry["per_params"])
        pb = jax.tree.map(lambda t: jnp.take(t, ctx.sampled_idx, axis=0),
                          ctx.carry["per_bstats"])
        rngs2 = jax.vmap(lambda r: jax.random.fold_in(r, 1))(ctx.rngs)

        def personal_local(p, b, rng, Xc, yc, nc, perms_c=None):
            cs_p = ClientState(params=p, batch_stats=b,
                               opt_state=trainer.opt.init(p), rng=rng)
            cs_p, _ = trainer.local_train(
                cs_p, Xc, yc, nc, lr, epochs=f.local_epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                prox_lamda=lamda, prox_ref=params, perms=perms_c)
            return cs_p.params, cs_p.batch_stats

        new_pp, new_pb = ctx.client_map(
            personal_local, pp, pb, rngs2, Xs, ys, ns,
            hoisted=(lambda: ctx.local_perms(rngs2, ns, f.local_epochs),))
        return round_program.TrainOut(
            losses=losses,
            upload={"params": cs.params, "batch_stats": cs.batch_stats},
            state=cs,
            extra={"pp": new_pp, "pb": new_pb})

    def _update_stage(self, ctx, tr, new_carry) -> dict:
        """Scatter the personal track back into the persistent per-client
        stacks; pad entries from stream_sampling / mesh tiling are
        dropped, never written (base.scatter_sampled_rows)."""
        real = ctx.ns > 0
        per_params = self.scatter_sampled_rows(
            ctx.carry["per_params"], tr.extra["pp"], ctx.sampled_idx,
            real)
        per_bstats = self.scatter_sampled_rows(
            ctx.carry["per_bstats"], tr.extra["pb"], ctx.sampled_idx,
            real)
        return {"per_params": per_params, "per_bstats": per_bstats}

    # ---------- legacy-signature program adapters ----------

    @functools.cached_property
    def _round_jit(self):
        prog = self.program.round_jit()

        def round_call(params, bstats, per_params, per_bstats, data,
                       sampled_idx, rngs, lr, byz=None):
            return prog((params, bstats, per_params, per_bstats), data,
                        (), sampled_idx, rngs, lr, None, byz)

        return round_call

    def _sharded_round_jit(self, n_real: int):
        prog = self.program.round_jit(n_real=n_real)

        def sharded_round_call(params, bstats, per_params, per_bstats,
                               data, sampled_idx, rngs, lr, byz=None):
            return prog((params, bstats, per_params, per_bstats), data,
                        (), sampled_idx, rngs, lr, None, byz)

        return sharded_round_call

    @functools.cached_property
    def _round_stream_jit(self):
        prog = self.program.stream_jit()

        def stream_round_call(params, bstats, per_params, per_bstats,
                              Xs, ys, ns, sampled_idx, rngs, lr,
                              byz=None):
            return prog((params, bstats, per_params, per_bstats), (),
                        Xs, ys, ns, sampled_idx, rngs, lr, None, byz)

        return stream_round_call

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        params, bstats = gs.params, gs.batch_stats
        per = self.broadcast_states(
            ClientState(params=params, batch_stats=bstats, opt_state=None,
                        rng=None), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats
        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            history = restored["history"]
        if self.stream is not None:
            self.stream.prefetch_train(*self.stream_sampling(start))
        # fused K-round windows (builder-owned, ISSUE 11): the window
        # planner pins eval/checkpoint rounds to boundaries, so the
        # fused driver's observable behavior matches the per-round loop
        fuse = (cfg.fed.rounds_per_dispatch > 1
                and self.fused_fallback_reason() is None)
        round_idx = start
        while round_idx < cfg.fed.comm_round:
            k = self._dispatch_window(round_idx) if fuse else 1
            if k > 1:
                ((params, bstats, per_params, per_bstats), _, outs,
                 wi) = self.program.run_window(
                    (params, bstats, per_params, per_bstats), round_idx,
                    k)
                loss, k = outs["loss"][-1], wi.k
                round_idx += k - 1
            elif self.stream is not None:
                sampled = self.client_sampling(round_idx)
                fed_ids, n_real = self.stream_sampling(round_idx, sampled)
                rngs = self.per_client_rngs(round_idx, fed_ids)
                byz = self._byz_round_plan(round_idx, fed_ids)
                Xs, ys, ns = self.stream.get_train(fed_ids, n_real)
                if round_idx + 1 < cfg.fed.comm_round:
                    self.stream.prefetch_train(
                        *self.stream_sampling(round_idx + 1))
                (params, bstats, per_params, per_bstats, loss,
                 n_bad) = self._round_stream_jit(
                    params, bstats, per_params, per_bstats, Xs, ys, ns,
                    jnp.asarray(fed_ids), rngs, self.round_lr(round_idx),
                    byz)
                self._note_nonfinite(n_bad)
            else:
                sampled = self.client_sampling(round_idx)
                self.log.info("################ round %d: clients %s",
                              round_idx, sampled.tolist())
                # cohort sharding (ISSUE 6): the sharded program gathers
                # the mesh-padded set (and takes rngs for it); the byz
                # plan stays on the REAL sampled set (the builder slices
                # pads off before the attack/defense/scatter tail)
                ids, round_prog = self._cohort_round_prog(sampled)
                rngs = self.per_client_rngs(round_idx, ids)
                byz = self._byz_round_plan(round_idx, sampled)
                (params, bstats, per_params, per_bstats, loss,
                 n_bad) = round_prog(
                    params, bstats, per_params, per_bstats, self.data,
                    jnp.asarray(ids), rngs, self.round_lr(round_idx),
                    byz)
                self._note_nonfinite(n_bad)
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self._eval_p(per_params, per_bstats)
                mg = self._eval_g(params, bstats)
                self._flush_nonfinite(round_idx)
                self.stat_info["person_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss,
                                 personal=m, global_=mg)
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "personal_acc": m["acc"],
                                "global_acc": mg["acc"]})
            self.maybe_checkpoint(round_idx, {
                "params": params, "batch_stats": bstats,
                "per_params": per_params, "per_bstats": per_bstats,
                "history": history})
            round_idx += 1
        self._flush_nonfinite(cfg.fed.comm_round - 1)
        m = self._eval_p(per_params, per_bstats)
        return {"params": params, "personal_params": per_params,
                "history": history, "final_personal": m}
