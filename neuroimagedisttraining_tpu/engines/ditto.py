"""Ditto: personalized FL with a proximal personal track.

Behavior parity with fedml_api/standalone/ditto/ditto_api.py:40-78 +
ditto/my_model_trainer.py:38-68:

- Global track: sampled clients train the global model normally for
  ``epochs`` epochs; sample-weighted FedAvg.
- Personal track: each sampled client also trains its PERSISTENT personal
  model for ``local_epochs`` epochs, pulling toward the round's incoming
  global model after every step: ``w -= lr * lamda * (w - w_global)``
  (my_model_trainer.py:63-64).
- Evaluation reports the personal models (ditto_api.py:74-78).

Both tracks run inside one jitted SPMD round program over the sampled set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.core.trainer import ClientState
from neuroimagedisttraining_tpu.engines.base import FederatedEngine


class DittoEngine(FederatedEngine):
    name = "ditto"
    # Streaming (cohort > HBM): both tracks only consume the SAMPLED
    # clients' shards, so the streamed round has FedAvg's shape — data per
    # round on device, persistent personal state resident.
    supports_streaming = True

    def _round_body(self, params, bstats, per_params, per_bstats, Xs, ys,
                    ns, sampled_idx, rngs, lr):
        trainer = self.trainer
        o = self.cfg.optim
        f = self.cfg.fed
        S = Xs.shape[0]
        max_samples = self._max_samples()
        lamda = float(f.lamda)  # nidt: allow[trace-host-sync] -- cfg.fed.lamda is a static Python scalar bound at trace time, not a tracer

        def bcast(t):
            return jax.tree.map(
                lambda x: jnp.broadcast_to(x, (S,) + x.shape), t)

        # -- global track --
        cs = ClientState(params=bcast(params), batch_stats=bcast(bstats),
                         opt_state=bcast(trainer.opt.init(params)),
                         rng=rngs)

        def global_local(cs_c, Xc, yc, nc):
            return trainer.local_train(
                cs_c, Xc, yc, nc, lr, epochs=o.epochs,
                batch_size=o.batch_size, max_samples=max_samples)

        cs, losses = jax.vmap(global_local)(cs, Xs, ys, ns)
        w = ns.astype(jnp.float32)
        # silo-aware aggregation of the global track (base.aggregate):
        # silo-first ICI/DCN routing on a two-level mesh, flat mean
        # otherwise — identical result (tests/test_sharding.py)
        new_params = self.aggregate(cs.params, w)
        new_bstats = self.aggregate(cs.batch_stats, w)

        # -- personal track (persistent, proximal to incoming global) --
        pp = jax.tree.map(lambda t: jnp.take(t, sampled_idx, axis=0),
                          per_params)
        pb = jax.tree.map(lambda t: jnp.take(t, sampled_idx, axis=0),
                          per_bstats)
        rngs2 = jax.vmap(lambda r: jax.random.fold_in(r, 1))(rngs)

        def personal_local(p, b, rng, Xc, yc, nc):
            cs_p = ClientState(params=p, batch_stats=b,
                               opt_state=trainer.opt.init(p), rng=rng)
            cs_p, _ = trainer.local_train(
                cs_p, Xc, yc, nc, lr, epochs=f.local_epochs,
                batch_size=o.batch_size, max_samples=max_samples,
                prox_lamda=lamda, prox_ref=params)
            return cs_p.params, cs_p.batch_stats

        new_pp, new_pb = jax.vmap(personal_local)(pp, pb, rngs2, Xs, ys, ns)
        # pad entries from stream_sampling are dropped, never written
        # (base.scatter_sampled_rows)
        real = ns > 0
        per_params = self.scatter_sampled_rows(per_params, new_pp,
                                               sampled_idx, real)
        per_bstats = self.scatter_sampled_rows(per_bstats, new_pb,
                                               sampled_idx, real)
        mean_loss = jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1e-9)
        return new_params, new_bstats, per_params, per_bstats, mean_loss

    @functools.cached_property
    def _round_jit(self):
        def round_fn(params, bstats, per_params, per_bstats, data,
                     sampled_idx, rngs, lr):
            Xs = jnp.take(data.X_train, sampled_idx, axis=0)
            ys = jnp.take(data.y_train, sampled_idx, axis=0)
            ns = jnp.take(data.n_train, sampled_idx, axis=0)
            return self._round_body(params, bstats, per_params, per_bstats,
                                    Xs, ys, ns, sampled_idx, rngs, lr)

        # donation: global model + persistent per-client stacks are
        # consumed (outputs reuse their buffers); the driver rebinds all
        # four on return and reads none of the donated inputs after
        return jax.jit(round_fn,
                       donate_argnums=self._donate_argnums(0, 1, 2, 3))

    @functools.cached_property
    def _round_stream_jit(self):
        return jax.jit(self._round_body,
                       donate_argnums=self._donate_argnums(0, 1, 2, 3))

    def train(self):
        cfg = self.cfg
        gs = self.init_global_state()
        params, bstats = gs.params, gs.batch_stats
        per = self.broadcast_states(
            ClientState(params=params, batch_stats=bstats, opt_state=None,
                        rng=None), self.num_clients)
        per_params, per_bstats = per.params, per.batch_stats
        history = []
        start, restored = self.restore_checkpoint()
        if restored is not None:
            params, bstats = restored["params"], restored["batch_stats"]
            per_params, per_bstats = (restored["per_params"],
                                      restored["per_bstats"])
            history = restored["history"]
        if self.stream is not None:
            self.stream.prefetch_train(*self.stream_sampling(start))
        for round_idx in range(start, cfg.fed.comm_round):
            sampled = self.client_sampling(round_idx)
            if self.stream is not None:
                fed_ids, n_real = self.stream_sampling(round_idx, sampled)
                rngs = self.per_client_rngs(round_idx, fed_ids)
                Xs, ys, ns = self.stream.get_train(fed_ids, n_real)
                if round_idx + 1 < cfg.fed.comm_round:
                    self.stream.prefetch_train(
                        *self.stream_sampling(round_idx + 1))
                (params, bstats, per_params, per_bstats,
                 loss) = self._round_stream_jit(
                    params, bstats, per_params, per_bstats, Xs, ys, ns,
                    jnp.asarray(fed_ids), rngs, self.round_lr(round_idx))
            else:
                rngs = self.per_client_rngs(round_idx, sampled)
                (params, bstats, per_params, per_bstats,
                 loss) = self._round_jit(
                    params, bstats, per_params, per_bstats, self.data,
                    jnp.asarray(sampled), rngs, self.round_lr(round_idx))
            if round_idx % cfg.fed.frequency_of_the_test == 0 \
                    or round_idx == cfg.fed.comm_round - 1:
                m = self._eval_p(per_params, per_bstats)
                mg = self._eval_g(params, bstats)
                self.stat_info["person_test_acc"].append(m["acc"])
                self.log.metrics(round_idx, train_loss=loss,
                                 personal=m, global_=mg)
                history.append({"round": round_idx,
                                "train_loss": float(loss),
                                "personal_acc": m["acc"],
                                "global_acc": mg["acc"]})
            self.maybe_checkpoint(round_idx, {
                "params": params, "batch_stats": bstats,
                "per_params": per_params, "per_bstats": per_bstats,
                "history": history})
        m = self._eval_p(per_params, per_bstats)
        return {"params": params, "personal_params": per_params,
                "history": history, "final_personal": m}
