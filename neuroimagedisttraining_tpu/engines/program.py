"""Declarative round-program builder (ISSUE 11, ROADMAP item 1).

Every federated round in this tree has the same skeleton:

    sample -> [local-train] -> [attack] -> [codec] -> sanitize ->
    defend -> aggregate -> [update persistent state] -> privacy-account

but until this module each engine hand-rolled the skeleton into its own
``_round_jit`` / ``_fused_round_jit`` / ``_sharded_round_jit`` bodies, so
the fast-path machinery built over ISSUEs 4-10 — fused K-round
``lax.scan`` dispatch, ``--client_mesh`` cohort sharding, buffer
donation, Byzantine defenses, the wire codec — reached only the engines
that had copied the machinery in (fedavg/fedprox/salientgrads), and
every other engine collapsed to K=1 unfused sequential dispatch with a
logged reason.

This module inverts the ownership. An engine DECLARES its round as a
:class:`RoundStages` value — which pytrees it carries between rounds,
its local-training stage, optionally a custom aggregation and a
persistent-state update stage — and :class:`RoundProgram` compiles the
declaration into the exact jitted round bodies the hand-written paths
produced, with the orthogonal knobs applied by the BUILDER:

- buffer donation of the carried state (+ codec EF rows) on every
  compiled program (ISSUE 4 contract, donation-discipline lint);
- ``--rounds_per_dispatch K`` window planning and the fused
  ``lax.scan`` driver, hooks pinned to window boundaries (ISSUE 4);
- ``--client_mesh`` cohort sharding of the local-train stage with the
  epoch-permutation hoist the toolchain requires (ISSUE 6,
  parallel/cohort.py — in-partition argsort miscompiles);
- the Byzantine attack plan + non-finite guard + ``--defense`` dispatch
  (ISSUE 5) and the wire codec's lossy roundtrip with error feedback
  (ISSUE 3) on engines whose stages opt in.

fedavg/fedprox/salientgrads ride the builder with BITWISE parity against
their pre-builder paths (the regression oracle: tests/test_dispatch.py,
test_cohort.py, test_byzantine.py pins are unchanged); ditto, dpsgd and
subavg are expressed as stage declarations and gain fused windows and
cohort sharding for the first time (tests/test_program.py).

Fallback reporting is unified here too: :data:`REASONS` is the single
source of truth for every "falls back with a logged reason" site, and
:func:`report_fallback` increments the structured
``nidt_fallback_total{plane, engine, reason}`` counter in the obs
registry alongside the log line — fast-path coverage is scrapeable, not
grep-able.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.faults import adversary
from neuroimagedisttraining_tpu.obs import compute as obs_compute
from neuroimagedisttraining_tpu.obs import health as obs_health
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.parallel import cohort

PyTree = Any

# ---------------------------------------------------------------------------
# fallback reason table — the single source of truth (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

#: reason key -> (plane, message). Every "falls back with a logged
#: reason" site in the tree resolves its message HERE; engines override
#: ``*_fallback_key`` hooks with keys from this table, never ad-hoc
#: strings (tests/test_program.py asserts no orphaned or unknown keys).
REASONS: dict[str, tuple[str, str]] = {
    # -- fused multi-round dispatch (plane "fused") --
    "no-fused-body": ("fused", (
        "engine has no fused round body (host-side state between "
        "rounds)")),
    "streaming-host-data": ("fused", (
        "streaming rounds cross the host for data every round")),
    "wire-codec-host-bytes": ("fused", (
        "--wire_codec accounts encoded bytes on the host every round")),
    "mpc-host-stage": ("fused", (
        "the MPC aggregation stage is host-driven between rounds")),
    # -- cohort sharding (plane "sharding") --
    "no-sharded-body": ("sharding", (
        "engine has no cohort-sharded round body (its round crosses the "
        "host or exchanges per-client state outside the declared-stage "
        "shape)")),
    "two-level-mesh": ("sharding", (
        "two-level (silos, clients) mesh routes aggregation silo-first "
        "(parallel/hierarchical.py); cohort sharding arms on 1-D client "
        "meshes")),
    "one-device": ("sharding", (
        "only one device visible — the unsharded round IS the "
        "single-device program")),
    "streaming-sharded-feed": ("sharding", (
        "streaming rounds host-stage each round's shards; the streamed "
        "feed already device_puts them client-sharded over the mesh")),
    "batch-order-replacement": ("sharding", (
        "batch_order=replacement draws per-step randint batches inside "
        "the shard_map partition, where the partitioned RNG+gather "
        "lowering miscompiles on this toolchain (measured, "
        "parallel/cohort.py); the shuffle path hoists its permutations "
        "out of the partition — i.i.d. per-step draws cannot be "
        "hoisted")),
    "gossip-mesh-collectives": ("sharding", (
        "dispfl's decentralized round already runs client-sharded "
        "gossip collectives on the mesh (parallel/gossip.py); "
        "--client_mesh adds nothing")),
    "mpc-host-boundary": ("sharding", (
        "turboaggregate's round crosses the host at the MPC share "
        "boundary every round (quantize/share/aggregate models the "
        "client<->server link); no sharded round body")),
    "cohort-not-tiling": ("sharding", (
        "the full client axis does not tile the client mesh (the data "
        "layer pads resident cohorts to a device multiple; this one is "
        "not)")),
    # -- the distributed transport (distributed/run.py startup notes) --
    "distributed-control-plane": ("fused", (
        "the distributed transport dispatches one round at a time "
        "(every round crosses the control plane: broadcast/upload/"
        "aggregate over sockets)")),
    "distributed-no-client-axis": ("sharding", (
        "the distributed transport has no in-process client axis to "
        "shard (each rank trains its own silo) — flag accepted for "
        "config parity with the main CLI only")),
    # -- autotuner recipes (plane "recipe", tune/recipe.py) --
    "recipe-override": ("recipe", (
        "an explicit CLI flag overrides the loaded recipe's value for "
        "this knob (--recipe applies as config DEFAULTS; flags the "
        "operator spells win)")),
}


def reason(key: str) -> str:
    """The logged message for a fallback ``key`` (KeyError on unknown
    keys — an engine naming a reason outside the table is a bug)."""
    return REASONS[key][1]


def report_fallback(engine_name: str, key: str) -> str:
    """Count one structured fallback announcement and return its message.
    The caller owns the log line (each site keeps its historic wording
    around the message); the counter is the scrapeable half:
    ``nidt_fallback_total{plane, engine, reason}``."""
    plane, msg = REASONS[key]
    obs_metrics.counter(
        obs_names.FALLBACK_TOTAL,
        "fast-path fallback announcements by plane (fused dispatch / "
        "cohort sharding / fused streaming), engine, and reason key "
        "(engines/program.py REASONS)",
        labelnames=("plane", "engine", "reason"),
    ).labels(plane=plane, engine=engine_name, reason=key).inc()
    return msg


# ---------------------------------------------------------------------------
# stage declarations
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TrainOut:
    """What an engine's local-train stage hands the downstream stages.

    Every array field is CLIENT-STACKED along axis 0 over the round's
    cohort; on the cohort-sharded path the builder statically slices the
    mesh-pad rows off all of them before the attack/codec/defense tail.

    - ``upload``: the ``{"params", "batch_stats"}`` payload the clients
      would put on the wire (what attack/codec/sanitize/defend consume),
      or None when the engine's custom aggregate stage consumes ``extra``
      directly.
    - ``losses``: per-client training losses ``[C]``.
    - ``state``: the trained per-client state (``ClientState``) — its
      ``rng`` leaves seed the weak_dp defense, and update stages scatter
      from its params/batch_stats (the client's HONEST local result,
      pre-attack/codec by design).
    - ``extra``: engine-private client-stacked auxiliaries for the
      aggregate/update stages.
    """

    losses: jax.Array
    upload: dict | None = None
    state: Any = None
    extra: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class RoundStages:
    """An engine's declared round: the builder compiles this (and only
    this) into every dispatch variant — single-round, cohort-sharded,
    fused K-round windows, streamed — with donation, window planning and
    the attack/codec/defense stages applied by the builder.

    ``carry``: names of the device pytrees carried round to round, in
    program-argument (and return) order; all are donated.
    ``consts``: loop-constant operands after the federation data (e.g.
    salientgrads' phase-1 mask).
    ``per_round``: per-round operand names beyond the builder-owned
    sampling/rng/lr (e.g. dpsgd's mixing matrix) — stacked along K in
    fused windows.
    ``train``: the local-train stage, ``(ctx: RoundCtx) -> TrainOut``.
    ``aggregate``: custom aggregation stage
    ``(ctx, upload, w, tr) -> (new_carry: dict, outs: dict)``; None
    routes through the builder's sanitize -> defend -> weighted-mean
    tail (:func:`sanitize_defend_aggregate`).
    ``update``: persistent per-client state stage
    ``(ctx, tr, new_carry) -> dict`` of carry updates (scatters).
    ``epilogue``: window-final outputs derived from the carry
    ``(eng, carry: dict, data) -> tuple`` (e.g. dpsgd's ``w_global``) —
    computed once per dispatch, after the scan.
    ``outputs``: names of the per-round scalar outputs, stacked ``[K]``
    over fused windows. ``"n_bad"`` wires into the engine's non-finite
    accounting automatically.
    ``gathers_cohort``: the builder gathers the sampled clients' shards
    from the federation data by ``sampled_idx`` (False: the train stage
    consumes the full data, dpsgd-style).
    ``uses_ef``: the program takes (and donates) wire-codec
    error-feedback rows and returns the updated rows + the
    byte-accounting sample ``u0``.
    ``supports_attack``: the program takes the [C]-planned Byzantine
    attack and applies it to ``upload`` before codec/defense.
    ``codec_masks``: ``(ctx) -> masks_full`` handed to the codec
    roundtrip (salientgrads' phase-1 mask handoff), or None.
    ``window_extras``: custom window prologue for engines whose rounds
    consume ``per_round`` operands, ``(round_idx, k) -> WindowInputs``.
    ``extra_hooked``: extra host-boundary predicate for the window
    planner (e.g. dpsgd's every-100-rounds fine-tune pass).
    ``health``: engine-private health-stats stage for the in-dispatch
    training-health leg (ISSUE 15), ``(ctx, tr, new_carry) -> dict`` of
    scalar stats named by ``health_outputs`` (the masked engines emit
    ``obs/health.py MASK_STAT_NAMES``); traced with the round, emitted
    only when ``--health_stats`` arms the leg.
    """

    carry: tuple[str, ...]
    train: Callable
    aggregate: Callable | None = None
    update: Callable | None = None
    epilogue: Callable | None = None
    outputs: tuple[str, ...] = ("loss", "n_bad")
    consts: tuple[str, ...] = ()
    per_round: tuple[str, ...] = ()
    gathers_cohort: bool = True
    uses_ef: bool = False
    supports_attack: bool = False
    codec_masks: Callable | None = None
    window_extras: Callable | None = None
    extra_hooked: Callable | None = None
    health: Callable | None = None
    health_outputs: tuple[str, ...] = ()


@dataclasses.dataclass
class WindowInputs:
    """Host prologue of one fused window (see
    :meth:`RoundProgram.window_inputs`)."""

    sampled: list | None
    idx: jax.Array | None
    rngs: jax.Array
    lrs: jax.Array
    byz: tuple | None
    k: int
    n_real: int | None
    static_key: Any = None
    per_round: dict | None = None


class RoundCtx:
    """Everything a stage sees about the round being traced. Built by
    the program body; stages read operands off it and use
    :meth:`client_map` for their per-client loops so the builder decides
    vmap vs the cohort-sharded mesh loop."""

    def __init__(self, eng, stages: RoundStages, carry: dict, data,
                 consts: dict, Xs, ys, ns, sampled_idx, rngs, lr,
                 per_round: dict, static_key, n_real, sharded: bool):
        self.eng = eng
        self.stages = stages
        self.carry = carry
        self.data = data
        self.consts = consts
        self.Xs, self.ys, self.ns = Xs, ys, ns
        self.sampled_idx = sampled_idx
        self.rngs = rngs
        self.lr = lr
        self.per_round = per_round
        self.static = static_key
        self.n_real = n_real
        self.sharded = sharded

    # -- the local-train placement contract (ISSUE 6) --

    def client_map(self, fn, *stacked, hoisted: tuple = ()):
        """Run the unbatched per-client ``fn`` over the client-stacked
        operands: plain ``vmap`` on the unsharded path (bitwise-identical
        to the pre-builder engines), the cohort-sharded mesh loop
        (``FederatedEngine._cohort_map`` -> parallel/cohort.py) when this
        program was built sharded. ``hoisted`` are thunks producing extra
        client-stacked operands passed ONLY on the sharded path — the
        epoch-permutation hoist that keeps argsort-lowered RNG out of the
        shard_map partition (the measured miscompile,
        parallel/cohort.py); ``fn`` takes them as trailing defaulted
        params."""
        if self.sharded:
            extra = tuple(h() for h in hoisted)
            return self.eng._cohort_map(fn, *stacked, *extra)
        return jax.vmap(fn)(*stacked)

    def local_perms(self, rngs, ns, epochs: int):
        """Hoisted per-client epoch permutations for a sharded
        local-train stage: exactly what each client's ``local_train``
        would derive from ``rngs`` (core/trainer.py ``epoch_perms_for``),
        computed OUTSIDE the shard_map partition."""
        return hoisted_epoch_perms(self.eng, rngs, ns, epochs)

    def rng_after_local_train(self, rngs, epochs: int):
        """The per-client rng values ``local_train`` leaves in
        ``cs.rng`` after ``epochs`` epochs — the entry rngs of a SECOND
        ``local_train`` call in the same per-client stage (subavg's
        epoch-1 / tail split), replayed outside the partition so the
        tail call's permutations can be hoisted too. Mirrors
        ``local_train``'s stream exactly: one (rng0, perm) split at
        entry, then one 3-way split per scan step."""
        import math

        o = self.eng.cfg.optim
        steps = epochs * max(1, math.ceil(self.eng._max_samples()
                                          / o.batch_size))

        def chain(rng):
            r0, _ = jax.random.split(rng)

            def step(r, _):
                return jax.random.split(r, 3)[0], None

            r, _ = jax.lax.scan(step, r0, None, length=steps)
            return r

        return jax.vmap(chain)(rngs)

    @property
    def upload_ref(self) -> dict:
        """The broadcast reference the attack/codec/sanitize stages
        compare uploads against: the round's incoming global model."""
        return {"params": self.carry["params"],
                "batch_stats": self.carry["batch_stats"]}


# ---------------------------------------------------------------------------
# builder-owned stages
# ---------------------------------------------------------------------------


def hoisted_epoch_perms(eng, rngs, ns, epochs: int):
    """The per-client epoch permutations ``local_train`` would derive
    from ``rngs``, vmapped over the cohort — computed OUTSIDE a
    shard_map partition (the argsort-lowered permutation MISCOMPILES
    inside one on this toolchain; parallel/cohort.py documents the
    measurement) and passed in via ``perms=``. The rng stream is
    identical either way."""
    from neuroimagedisttraining_tpu.core.trainer import epoch_perms_for

    ms = eng._max_samples()
    return jax.vmap(
        lambda r, n: epoch_perms_for(r, epochs, ms, n))(rngs, ns)


def cohort_local_stage(eng, fn, cs, Xs, ys, ns):
    """A hoisted-perms cohort-sharded local stage for driver code
    OUTSIDE the round program (fedavg's final fine-tune pass): hoist the
    epoch permutations from ``cs.rng``, then run the per-client loop
    under the client mesh. Cohort sharding only arms under
    ``batch_order=shuffle`` (the program's mode checks), so hoistable
    perms always exist here."""
    perms = hoisted_epoch_perms(eng, cs.rng, ns, eng.cfg.optim.epochs)
    return eng._cohort_map(fn, cs, Xs, ys, ns, perms)


def sanitize_defend_aggregate(eng, upload, ref, w, losses, rngs=None):
    """The shared tail of a defended round body (trace-safe; the builder
    runs it for every engine without a custom aggregate stage):

    1. non-finite upload guard (runs with or without ``--defense``): a
       single NaN/Inf client would poison ``tree_weighted_mean``, so its
       row is swapped for the broadcast ``ref`` and zero-weighted (the
       count comes back as ``n_bad``);
    2. defense dispatch (core/robust.py): order-statistic defenses
       consume the whole upload payload (a Byzantine silo poisons its
       batch_stats too) and replace the weighted mean; the clip family
       transforms params per client (batch_stats are never clipped —
       structural parity with ``is_weight_param``,
       robust_aggregation.py:28-29) then reduces with the engine's
       silo-aware ``aggregate``. A cohort too small for the configured
       aggregator (fault-schedule shrinkage) falls back to the plain
       mean with a warning — resolved at trace time, the cohort axis is
       static.

    ``upload``/``ref`` are ``{"params", "batch_stats"}`` dicts (stacked /
    unstacked); ``rngs`` are the per-client keys weak_dp noise draws
    from. Returns ``(new_params, new_bstats, mean_loss, n_bad)``."""
    f = eng.cfg.fed
    finite = robust.finite_per_client(upload)
    upload = robust.replace_nonfinite_clients(upload, ref, finite)
    n_bad = jnp.sum(~finite).astype(jnp.int32)
    w = w * finite.astype(jnp.float32)
    C = int(jax.tree.leaves(upload)[0].shape[0])
    # the engine's ACTIVE defense, not the config literal: the reflex
    # plane's escalate_defense handler can raise it mid-run (ISSUE 20),
    # after which the invalidated round programs re-trace through here
    defense = robust.effective_defense(eng.active_defense(), C, f.byz_f,
                                       warn=eng.log.warning)
    if defense in robust.ROBUST_AGGREGATORS:
        agg = robust.robust_aggregate(
            upload, w, defense=defense, byz_f=f.byz_f,
            geomed_iters=f.geomed_iters)
        new_params, new_bstats = agg["params"], agg["batch_stats"]
    else:
        client_params = robust.defend_stacked(
            upload["params"], ref["params"], defense=defense,
            norm_bound=f.norm_bound, stddev=f.stddev, rngs=rngs)
        new_params = eng.aggregate(client_params, w)
        new_bstats = eng.aggregate(upload["batch_stats"], w)
    safe_losses = jnp.where(jnp.isfinite(losses), losses, 0.0)
    mean_loss = jnp.sum(safe_losses * w) / jnp.maximum(jnp.sum(w), 1e-9)
    return new_params, new_bstats, mean_loss, n_bad


def sq_integer_weights(w, shift: int):
    """The per-round integer fold weights of the in-process secure-quant
    stage: ``max(rint(w / max(w) * 2^shift), 1)``. Every operation is a
    single correctly-rounded f32 op (or exact: max, rint, the power-of-
    two multiply), so the identical numpy formula over the same f32
    weights reproduces these integers EXACTLY — the bridge the bitwise
    host-fold pin crosses (tests/test_program.py). Ratios are preserved
    to ~2^-shift relative; an admitted client never folds at zero."""
    wn = w.astype(jnp.float32) / jnp.max(w.astype(jnp.float32))
    return jnp.maximum(jnp.rint(wn * jnp.float32(1 << shift)),
                       jnp.float32(1.0)).astype(jnp.uint32)


def secure_quant_aggregate(eng, upload, ref, w, losses, rngs=None):
    """The in-process secure QUANTIZED aggregation stage (ROADMAP item
    1(b)): ``--secure_quant`` swaps the builder's sanitize/defend/
    aggregate tail for the jitted one-phase GF(p) fold — the CODEC-
    family emulation of privacy/secure_quant.py inside the round body,
    so simulated runs train on exactly the numbers the encoded secure
    wire would deliver.

    Per leaf: scale (static ``sq_scales`` from the init model), quantize
    into the field (ops/mpc_device.quantize_device — bitwise
    ``mpc.quantize32``), multiply by the integer fold weight INSIDE the
    field (shift-add mulmod: products of residues never materialize, so
    uint32 suffices for p < 2^31 with x64 disabled), residue-sum over
    clients, dequantize, undo the scale, divide by the integer mass.
    Every step is exact field/integer arithmetic or one correctly-
    rounded f32 op, so the aggregate is BITWISE what the host fold — a
    ``SlotAccumulator`` over ``encode_secure_quant`` frames at the same
    ``(p, frac_bits, scales, weights)`` — produces (pinned in
    tests/test_program.py; masks cancel exactly mod p, which is why the
    mask-free device fold can BE the parity reference).

    The privacy-plane matrix applies: clip-family defenses run
    CLIENT-side pre-quantize (``SecureFedAvgClientProc`` precedent);
    order statistics were rejected at startup; there is no server-side
    non-finite gate — a NaN quantizes to the neutral zero residue (its
    weight still enters the mass, exactly like the real protocol, and
    ``n_bad`` reports the count without changing the fold)."""
    from neuroimagedisttraining_tpu.codec.wire import (
        _named_leaves, _rebuild_like,
    )
    from neuroimagedisttraining_tpu.ops import mpc_device

    f = eng.cfg.fed
    spec, scales = eng.sq_spec, eng.sq_scales
    shift = int(eng.sq_weight_shift)
    p, fb = int(spec.p), int(spec.frac_bits)
    pp = jnp.uint32(p)
    if f.defense_type != "none":
        # client-side clip family (the ctor admitted nothing else)
        upload = dict(upload, params=robust.defend_stacked(
            upload["params"], ref["params"], defense=f.defense_type,
            norm_bound=f.norm_bound, stddev=f.stddev, rngs=rngs))
    finite = robust.finite_per_client(upload)
    n_bad = jnp.sum(~finite).astype(jnp.int32)
    wi = sq_integer_weights(w, shift)
    # integer mass < cohort * 2^shift < the startup capacity bound,
    # well inside f32's 2^24 exact-integer range
    denom = jnp.sum(wi).astype(jnp.float32)
    C = int(jax.tree.leaves(upload)[0].shape[0])
    out = {}
    for name, x in _named_leaves(upload):
        s_leaf = jnp.float32(scales.get(name, 1.0))
        q = mpc_device.quantize_device(
            x.astype(jnp.float32) / s_leaf, p=p, frac_bits=fb)
        # (wi_c * q_c) mod p by shift-add doubling: wi < 2^(shift+1), so
        # shift+1 conditional field-adds — addmod keeps everything < p,
        # no uint32 wrap for any admissible field
        wib = wi.reshape((-1,) + (1,) * (q.ndim - 1))
        acc = jnp.zeros_like(q)
        cur = q
        for b in range(shift + 1):
            bit = ((wib >> b) & jnp.uint32(1)) > 0
            acc = jnp.where(bit, mpc_device._addmod(acc, cur, pp), acc)
            cur = mpc_device._addmod(cur, cur, pp)
        # ascending client order, like secure_sum_device — mod-p adds
        # are exact, so the order is convention, not a numerics choice
        total = jax.lax.fori_loop(
            1, C, lambda c, t: mpc_device._addmod(t, acc[c], pp),
            acc[0])
        deq = mpc_device.dequantize_device(total, p=p,
                                           frac_bits=fb) * s_leaf
        out[name] = (deq / denom).astype(x.dtype)
    agg = _rebuild_like(ref, out)
    safe_losses = jnp.where(jnp.isfinite(losses), losses, 0.0)
    mean_loss = jnp.sum(safe_losses * w) / jnp.maximum(jnp.sum(w), 1e-9)
    return agg["params"], agg["batch_stats"], mean_loss, n_bad


def health_update_stats(upload, ref, new_params, w) -> dict:
    """The builder's default in-dispatch training-health leg (ISSUE
    15): per-client update L2 norms vs the round's broadcast params,
    cosine similarity of each client update to the aggregated update,
    update-norm dispersion, and the global param / aggregate-update
    norms — all pure jnp on values the round body already holds, traced
    with the round and threaded through the fused-K scan like any other
    output. Names/semantics: ``obs/health.py UPDATE_STAT_NAMES`` (the
    host-side publisher); batch_stats are running moments, not an
    optimization direction, so the geometry is measured on params only.

    ``upload`` is the post-attack/post-codec payload the aggregation
    consumed — the wire's truth, which is exactly what a divergence
    rule should judge.

    The cosine is LEAVE-ONE-OUT: client i scores against the aggregate
    minus its own weighted contribution. Against the raw aggregate, a
    sign-flipping silo's own mass flips its cosine back toward +1
    (measured: +0.09 for a 1/3-weight flipped client whose honest twin
    reads -0.5), burying exactly the signal the divergence rule exists
    for. The subtraction is exact for the weighted-mean tail and an
    approximation under robust defenses — a diagnostic, not a parity
    surface. Everything reduces to per-client dot products, so the
    leave-one-out costs nothing extra."""
    up = [x.astype(jnp.float32) for x in jax.tree.leaves(upload["params"])]
    rf = [x.astype(jnp.float32) for x in jax.tree.leaves(ref["params"])]
    nw = [x.astype(jnp.float32) for x in jax.tree.leaves(new_params)]
    C = int(up[0].shape[0]) if up else 1
    sq = jnp.zeros((C,), jnp.float32)
    dots = jnp.zeros((C,), jnp.float32)
    agg_sq = jnp.float32(0.0)
    gsq = jnp.float32(0.0)
    for u, r, n in zip(up, rf, nw):
        du = (u - r[None]).reshape(C, -1)
        da = (n - r).reshape(-1)
        sq = sq + jnp.sum(du * du, axis=1)
        dots = dots + du @ da
        agg_sq = agg_sq + jnp.sum(da * da)
        gsq = gsq + jnp.sum(n.reshape(-1) ** 2)
    norms = jnp.sqrt(sq)
    agg_norm = jnp.sqrt(agg_sq)
    wf = w.astype(jnp.float32)
    p = wf / jnp.maximum(jnp.sum(wf), jnp.float32(1e-12))
    # leave-one-out: loo_i = agg - p_i * d_i (direction of everyone
    # else's mass; the (W - w_i)/W scale cancels in the cosine)
    loo_dot = dots - p * sq
    loo_sq = jnp.maximum(agg_sq - 2.0 * p * dots + p * p * sq,
                         jnp.float32(0.0))
    cos = loo_dot / jnp.maximum(norms * jnp.sqrt(loo_sq),
                                jnp.float32(1e-12))
    med = jnp.median(norms)
    return {
        "h_up_norms": norms,
        "h_up_max": jnp.max(norms),
        "h_up_med": med,
        "h_cos_min": jnp.min(cos),
        "h_cos_mean": jnp.mean(cos),
        "h_disp": jnp.max(norms) / jnp.maximum(med, jnp.float32(1e-12)),
        "h_gnorm": jnp.sqrt(gsq),
        "h_agg_up": agg_norm,
        # the full [C] leave-one-out cosine vector rides out too (no
        # gauge — the reflex plane's quarantine handler attributes a
        # divergence alert to the offending SAMPLED client with it;
        # engines/base.py _register_reflexes, ISSUE 20)
        "h_cos": cos,
    }


def mask_health_stats(new_masks, old_masks) -> dict:
    """Mask-health stats (``obs/health.py MASK_STAT_NAMES``) for a
    masked engine's ``RoundStages.health`` hook: mean kept fraction,
    round-over-round kept-weight overlap, and churn — computed over
    congruent mask pytrees (client-stacked or global) inside the round
    body. ``old_masks=None`` (a static mask) reads as overlap 1."""
    kept = jnp.float32(0.0)
    total = 0.0
    both = jnp.float32(0.0)
    was = jnp.float32(0.0)
    old_leaves = (jax.tree.leaves(old_masks) if old_masks is not None
                  else None)
    for i, m in enumerate(jax.tree.leaves(new_masks)):
        mb = m > 0
        kept = kept + jnp.sum(mb)
        total += float(np.prod(m.shape))
        if old_leaves is not None:
            ob = old_leaves[i] > 0
            both = both + jnp.sum(mb & ob)
            was = was + jnp.sum(ob)
    density = kept / jnp.float32(max(total, 1.0))
    if old_masks is None:
        overlap = jnp.float32(1.0)
    else:
        overlap = both / jnp.maximum(was, jnp.float32(1.0))
    return {"h_mask_density": density, "h_mask_overlap": overlap,
            "h_mask_churn": jnp.float32(1.0) - overlap}


def _codec_stage(eng, stages: RoundStages, ctx: RoundCtx, upload, efs):
    """The wire codec's lossy roundtrip over the whole upload payload
    (codec/device.py) — delta vs the round's broadcast reference,
    optional top-k with per-client error feedback (``uses_ef`` engines),
    mask handoff for engines that own one (``codec_masks``),
    quantization. Returns ``(decoded_upload, new_efs, u0)`` where ``u0``
    is client 0's decoded upload for the host-side byte accounting."""
    from neuroimagedisttraining_tpu.codec import device as codec_dev

    spec = eng.wire_spec
    ref = ctx.upload_ref
    masks_full = stages.codec_masks(ctx) if stages.codec_masks else None
    new_efs = None
    if stages.uses_ef and spec.needs_ef:
        dec, new_efs = jax.vmap(
            lambda u, e: codec_dev.lossy_roundtrip(
                spec, u, reference=ref, ef=e))(upload, efs)
        # a non-finite upload row (byz nonfinite attack, diverged
        # optimizer) would park NaN in the EF stack FOREVER — EF =
        # u - decode(u) is NaN, and every later encode consumes it, so
        # the guard would zero-weight the client for the rest of the
        # run. Zero those rows so the value fault stays transient (the
        # engine-side mirror of the server's post-quarantine
        # ARG_EF_RESET invariant).
        fin = robust.finite_per_client(upload)
        new_efs = jax.tree.map(
            lambda e: jnp.where(
                fin.reshape((-1,) + (1,) * (e.ndim - 1)),
                e, jnp.zeros_like(e)), new_efs)
    else:
        dec, _ = jax.vmap(
            lambda u: codec_dev.lossy_roundtrip(
                spec, u, reference=ref, masks=masks_full))(upload)
    u0 = jax.tree.map(lambda x: x[0], dec)
    return dec, new_efs, u0


# ---------------------------------------------------------------------------
# the program builder
# ---------------------------------------------------------------------------


class RoundProgram:
    """Compiles an engine's :class:`RoundStages` declaration into every
    dispatch variant and owns the window planning + fallback reporting
    that drives them. One instance per engine
    (``FederatedEngine.program``); compiled programs are cached on the
    ENGINE under the historic cache names
    (``_fused_round_jit_cache`` etc.), so the one-compiled-program-per-
    window pins keep reading the same place.

    ``built`` counts program compilations (cache misses); ``dispatches``
    counts compiled-program invocations — the bench's
    dispatch-amortization evidence (bench.py ``round_program`` cell).
    """

    def __init__(self, eng, stages: RoundStages | None):
        if stages is not None and stages.uses_ef \
                and stages.codec_masks is not None:
            # lossy_roundtrip tracks EF only when masks are absent
            # (codec/device.py: the mask handoff REPLACES top-k error
            # feedback), so a declaration naming both would silently
            # drop one of them inside _codec_stage
            raise ValueError(
                f"{type(eng).__name__} declares both uses_ef and "
                "codec_masks: the codec's mask handoff replaces error "
                "feedback — declare one")
        self.eng = eng
        self.stages = stages
        if stages is not None and (stages.health is None) \
                != (not stages.health_outputs):
            raise ValueError(
                f"{type(eng).__name__}: RoundStages.health and "
                "health_outputs must be declared together (the hook's "
                "returned stat names ARE the flattened-output order)")
        #: the in-dispatch training-health leg (ISSUE 15): stat names
        #: appended after the declared outputs (and the EF tail) when
        #: --health_stats arms it; the dispatch wrapper strips them
        #: back off and queues the device values, so every legacy
        #: driver/adapter arity is untouched
        self.health_names: tuple[str, ...] = ()
        if stages is not None and getattr(eng.cfg, "health_stats",
                                          False):
            self.health_names = obs_health.stat_names_for(
                stages.carry, stages.health_outputs)
        self.built = 0
        self.dispatches = 0
        #: builds per exact plan-cache key — a key building TWICE is a
        #: recompile (LRU thrash / shape leak), the storm the compute
        #: profiler warns about (obs/compute.py)
        self._build_counts: dict[tuple, int] = {}

    # ---------- fallback reporting ----------

    def fused_fallback_key(self) -> str | None:
        """Why the engine dispatches one round at a time even when
        ``--rounds_per_dispatch K`` asks for fused windows — a
        :data:`REASONS` key, or None when the declared stages support
        the K-round scan driver. Resident-mode checks shared by every
        declared engine: streaming feeds cross the host per round
        (unless the engine fuses streamed windows), and the wire codec
        accounts bytes on the host per round."""
        if self.stages is None:
            return "no-fused-body"
        if self.eng.stream is not None \
                and not self.eng.supports_fused_streaming:
            return "streaming-host-data"
        if self.eng.wire_spec is not None:
            return "wire-codec-host-bytes"
        return None

    def cohort_fallback_key(self) -> str | None:
        """Why the engine runs unsharded even when ``--client_mesh``
        asks for the cohort-sharded mesh — a :data:`REASONS` key, or
        None when the sharded path arms (mode checks shared by every
        capable engine; mirrors the fused contract)."""
        eng = self.eng
        if self.stages is None or not eng.supports_cohort_sharding:
            return eng.cohort_fallback_key()
        if eng.mesh is not None and len(eng.mesh.axis_names) != 1:
            return "two-level-mesh"
        if eng.mesh is not None and eng.mesh.devices.size == 1:
            return "one-device"
        if eng.stream is not None:
            return "streaming-sharded-feed"
        if eng.cfg.optim.batch_order != "shuffle":
            return "batch-order-replacement"
        if not self.stages.gathers_cohort \
                and eng.num_clients % eng.mesh.devices.size != 0:
            return "cohort-not-tiling"
        return None

    # ---------- window planning (ISSUE 4, absorbed from base.py) ----------

    def dispatch_window(self, round_idx: int) -> int:
        """Length of the fused window starting at ``round_idx``: grows
        up to ``rounds_per_dispatch`` but stops so that any round with a
        host-side hook — eval (``frequency_of_the_test``), checkpoint
        (``checkpoint_every``), the final round, an engine-declared
        extra hook — lands on the WINDOW BOUNDARY, where the driver runs
        the hooks exactly as the sequential loop would have. Interior
        rounds are hook-free by construction, so fusing changes no
        observable behavior."""
        eng = self.eng
        f = eng.cfg.fed
        K = max(1, int(f.rounds_per_dispatch))
        extra = self.stages.extra_hooked if self.stages else None

        def hooked(r: int) -> bool:
            return (r % f.frequency_of_the_test == 0
                    or r == f.comm_round - 1
                    or (eng._ckpt_active()
                        and (r + 1) % eng.cfg.checkpoint_every == 0)
                    or (extra is not None and extra(r)))

        k = 1
        while (k < K and round_idx + k < f.comm_round
               and not hooked(round_idx + k - 1)):
            k += 1
        return k

    def window_sampling(self, round_idx: int, k: int
                        ) -> tuple[list[np.ndarray], int]:
        """Host-precomputed per-round cohorts for a fused window,
        preserving the reference's ``np.random.seed(round_idx)``
        sampling contract round by round. The scan needs one static
        cohort size, so when a fault schedule varies the survivor count
        mid-window the window shrinks to the maximal equal-size prefix
        (still fused, still bit-identical cohorts)."""
        eng = self.eng
        sampled = [eng.client_sampling(r)
                   for r in range(round_idx, round_idx + k)]
        keep = 1
        while keep < len(sampled) and \
                len(sampled[keep]) == len(sampled[0]):
            keep += 1
        return sampled[:keep], keep

    def window_inputs(self, round_idx: int, k: int) -> WindowInputs:
        """Host prologue of a fused window: per-round cohorts (via
        ``window_sampling``, which may shrink ``k``), the per-round log
        lines the sequential loop would have emitted, and the stacked
        device inputs for the scan — including the [K, C]-stacked
        Byzantine attack plan when the fault schedule carries value
        faults. With cohort sharding armed, ``idx`` and ``rngs`` cover
        the mesh-padded per-round sets ([K, P]) while the byz plan stays
        on the REAL sampled sets (the sharded round body slices pad rows
        off before the attack/defense tail); ``n_real`` is the static
        real cohort size (None when unsharded). Engines with
        ``window_extras`` (per-round operands, no cohort sampling) build
        their own."""
        if self.stages is not None and self.stages.window_extras:
            return self.stages.window_extras(round_idx, k)
        eng = self.eng
        sampled, k = self.window_sampling(round_idx, k)
        for off, s in enumerate(sampled):
            eng.log.info("################ round %d: clients %s (fused "
                         "window of %d)", round_idx + off, s.tolist(), k)
        if eng._cohort_on:
            ids = [eng._cohort_pad(s)[0] for s in sampled]
            n_real = len(sampled[0])
        else:
            ids, n_real = sampled, None
        idx = jnp.asarray(np.stack(ids))
        rngs = jnp.stack([eng.per_client_rngs(round_idx + off, s)
                          for off, s in enumerate(ids)])
        lrs = jnp.asarray([eng.round_lr(round_idx + off)
                           for off in range(k)], jnp.float32)
        byz = None
        if eng._byz_on():
            plans = [eng._byz_round_plan(round_idx + off, s)
                     for off, s in enumerate(sampled)]
            byz = tuple(jnp.stack([p[i] for p in plans])
                        for i in range(4))
        return WindowInputs(sampled=sampled, idx=idx, rngs=rngs, lrs=lrs,
                            byz=byz, k=k, n_real=n_real)

    def stream_window_inputs(self, round_idx: int, k: int):
        """Host prologue of a fused STREAMED window (ISSUE 10): the
        per-round cohorts (``window_sampling`` — may shrink ``k``), each
        round's mesh-tiling padded id set (``stream_sampling`` — pads
        train as zero-weight no-ops exactly like the round-granular
        feed), the stacked per-round rngs/lrs over the PADDED ids (what
        the streamed round body consumes), and the [K, P]-stacked byz
        plan over the padded ids. Returns
        ``(ids_per_round, rngs, lrs, byz, k, n_real)``."""
        eng = self.eng
        sampled, k = self.window_sampling(round_idx, k)
        padded = [eng.stream_sampling(round_idx + off, sampled=s)
                  for off, s in enumerate(sampled)]
        ids_per_round = [p[0] for p in padded]
        n_real = padded[0][1]
        for off, s in enumerate(sampled):
            eng.log.info("################ round %d (stream): clients %s "
                         "(fused window of %d)", round_idx + off,
                         s.tolist(), k)
        rngs = jnp.stack([eng.per_client_rngs(round_idx + off, ids)
                          for off, ids in enumerate(ids_per_round)])
        lrs = jnp.asarray([eng.round_lr(round_idx + off)
                           for off in range(k)], jnp.float32)
        byz = None
        if eng._byz_on():
            plans = [eng._byz_round_plan(round_idx + off, ids)
                     for off, ids in enumerate(ids_per_round)]
            byz = tuple(jnp.stack([p[i] for p in plans])
                        for i in range(4))
        return ids_per_round, rngs, lrs, byz, k, n_real

    # ---------- the round body, composed from the declared stages ----------

    def _gather(self, data, idx):
        Xs = jnp.take(data.X_train, idx, axis=0)
        ys = jnp.take(data.y_train, idx, axis=0)
        ns = jnp.take(data.n_train, idx, axis=0)
        return Xs, ys, ns

    def _body(self, carry_vals: tuple, data, const_vals: tuple, Xs, ys,
              ns, idx, rngs, lr, efs, byz, per_round_vals, static_key,
              n_real, sharded: bool):
        """One round: the declared stages in builder order. Returns
        ``(new_carry: dict, outs: dict, efs_tail: tuple)``."""
        eng, st = self.eng, self.stages
        carry = dict(zip(st.carry, carry_vals))
        consts = dict(zip(st.consts, const_vals))
        per_round = dict(zip(st.per_round, per_round_vals or ()))
        if n_real is not None:
            ns = cohort.pad_row_weights(ns, n_real)
        ctx = RoundCtx(eng, st, carry, data, consts, Xs, ys, ns, idx,
                       rngs, lr, per_round, static_key, n_real, sharded)
        tr = st.train(ctx)
        S = int(tr.losses.shape[0])
        if n_real is not None and n_real < S:
            # static slice: drop the mesh-pad rows before the
            # attack/codec/defense/aggregate/update tail — it executes
            # the identical operations the sequential C-loop executes
            # (parallel/cohort.py contract)
            sl = lambda t: jax.tree.map(lambda x: x[:n_real], t)
            tr = TrainOut(losses=sl(tr.losses),
                          upload=sl(tr.upload) if tr.upload is not None
                          else None,
                          state=sl(tr.state) if tr.state is not None
                          else None,
                          extra=sl(tr.extra))
            ns = ns[:n_real]
            ctx.ns = ns
            if idx is not None:
                ctx.sampled_idx = idx[:n_real]
        w = ns.astype(jnp.float32)
        upload = tr.upload
        new_efs = u0 = None
        if byz is not None:
            if not st.supports_attack:
                # trace-time consistency check: the ctor's
                # supports_byz_faults gate should make this unreachable,
                # but the declaration is the builder's contract — a plan
                # reaching stages that never declared the attack stage
                # is a bug, not a silent skip
                raise ValueError(
                    f"{type(eng).__name__}: byz attack plan reached a "
                    "RoundStages declaration without supports_attack")
            # the attack hits the WHOLE upload payload (params + batch
            # stats — what the wire ships) before any encoding; honest
            # clients ride the plan's identity rows bitwise-untouched
            mult, std, nonfinite, keys = byz
            upload = adversary.apply_attack_stacked(
                upload, ctx.upload_ref, mult, std, nonfinite, keys)
        if eng.wire_spec is not None:
            upload, new_efs, u0 = _codec_stage(eng, st, ctx, upload, efs)
        if st.aggregate is None:
            rng_leaf = tr.state.rng if tr.state is not None else None
            if getattr(eng, "sq_spec", None) is not None:
                # --secure_quant: the field fold REPLACES the default
                # tail (the in-process codec-family stage, ROADMAP 1(b))
                new_params, new_bstats, mean_loss, n_bad = \
                    secure_quant_aggregate(eng, upload, ctx.upload_ref,
                                           w, tr.losses, rngs=rng_leaf)
            else:
                new_params, new_bstats, mean_loss, n_bad = \
                    sanitize_defend_aggregate(eng, upload, ctx.upload_ref,
                                              w, tr.losses, rngs=rng_leaf)
            new_carry = {"params": new_params, "batch_stats": new_bstats}
            outs = {"loss": mean_loss, "n_bad": n_bad}
        else:
            new_carry, outs = st.aggregate(ctx, upload, w, tr)
        if st.update is not None:
            new_carry.update(st.update(ctx, tr, new_carry))
        missing = set(st.carry) - set(new_carry)
        assert not missing, f"stages left carry entries unset: {missing}"
        if self.health_names:
            # the in-dispatch training-health leg (ISSUE 15): pure jnp
            # over values this body already computed, traced with the
            # round and returned as trailing outputs — no host touch,
            # no extra dispatch, and the carry math above is untouched
            # (the armed-vs-disarmed bitwise pin, tests/test_health.py)
            hs: dict = {}
            if obs_health.UPDATE_STAT_NAMES[0] in self.health_names:
                measured = upload
                if measured is None and tr.state is not None:
                    measured = {"params": tr.state.params,
                                "batch_stats": tr.state.batch_stats}
                if measured is None:
                    raise ValueError(
                        f"{type(eng).__name__}: health stats need an "
                        "upload payload (or TrainOut.state) to measure "
                        "— the declared train stage returned neither")
                hs.update(health_update_stats(
                    measured, ctx.upload_ref, new_carry["params"], w))
            if st.health is not None:
                hs.update(st.health(ctx, tr, new_carry))
            missing_h = set(self.health_names) - set(hs)
            assert not missing_h, \
                f"health stage left stats unset: {missing_h}"
            outs = dict(outs, **{n: hs[n] for n in self.health_names})
        efs_tail = ()
        if eng.wire_spec is not None:
            efs_tail = (new_efs, u0) if st.uses_ef else (u0,)
        return new_carry, outs, efs_tail

    def _epilogue(self, carry: dict, data) -> tuple:
        st = self.stages
        if st.epilogue is None:
            return ()
        return tuple(st.epilogue(self.eng, carry, data))

    def _flat(self, new_carry: dict, epi: tuple, outs: dict,
              efs_tail: tuple) -> tuple:
        st = self.stages
        # health stats ride LAST (after the EF tail) so the dispatch
        # wrapper can strip a fixed-length suffix without knowing the
        # program variant's tail shape
        return (*(new_carry[n] for n in st.carry), *epi,
                *(outs[o] for o in st.outputs), *efs_tail,
                *(outs[h] for h in self.health_names))

    def _note_build(self, label: str, key: tuple) -> None:
        """One program compilation: ``built`` and the scrapeable
        ``nidt_compiles_total{engine, program}`` counter move TOGETHER
        (one measurement — tests/test_program.py pins them equal). A
        rebuild of the same exact plan-cache ``key`` is a recompile
        (warning-logged + flight-recorded by the profiler)."""
        self.built += 1
        n = self._build_counts[key] = self._build_counts.get(key, 0) + 1
        obs_compute.note_compile(self.eng.name, label, recompile=n > 1)

    def _count_dispatches(self, jitted, label: str = "round",
                          rounds: int = 1,
                          health_stacked: bool = False):
        """Wrap a compiled program so invocations count toward
        ``dispatches`` (the bench's per-engine dispatch evidence) and
        feed the dispatch-boundary profiler (obs/compute.py): host
        wall around the call — compile-dominated on the first
        invocation (jit compiles at first call), enqueue thereafter —
        plus ``rounds`` (K for fused windows) toward the MFU
        numerator. No sync is added anywhere: the clock brackets the
        ENQUEUE, and MFU divides by boundary-to-boundary wall where
        the driver already blocked. ``.jit``/``.lower`` expose the
        underlying executable for compile-text tests.

        When the training-health leg is armed, the program's trailing
        ``health_names`` outputs are stripped HERE and queued on the
        engine as device arrays (``_note_health`` — drained in the
        batched ``device_get`` at the next host boundary, never synced
        per dispatch), so every legacy driver/adapter sees its historic
        arity. ``health_stacked`` marks the scan-fused variants whose
        health outputs carry a leading [K] round axis."""
        state = {"first": True}
        eng = self.eng
        health_names = self.health_names

        def dispatch(*args):
            self.dispatches += 1
            eng._arm_compute_profiler()
            # one span per dispatch (disarmed: a shared no-op) — under
            # --profile_dir the span opens a jax.profiler
            # TraceAnnotation, so this exact program invocation is the
            # shared ruler between the host and XLA timelines
            with obs_trace.span("dispatch_program", program=label,
                                engine=eng.name, rounds=rounds):
                t0 = time.perf_counter()
                out = jitted(*args)
                dur = time.perf_counter() - t0
            obs_compute.note_dispatch(
                eng.name, label, dur, rounds=rounds,
                phase="compile" if state["first"] else "execute")
            state["first"] = False
            if health_names:
                n_h = len(health_names)
                eng._note_health(dict(zip(health_names, out[-n_h:])),
                                 k=rounds, stacked=health_stacked)
                out = out[:-n_h]
            return out

        dispatch.jit = jitted
        dispatch.lower = jitted.lower
        return dispatch

    # ---------- compiled variants ----------

    def round_jit(self, n_real: int | None = None, static_key=None,
                  sharded: bool | None = None):
        """The single-round program:
        ``f(carry, data, consts, idx, rngs, lr, efs=None, byz=None,
        per_round=None)``. ``carry`` (argnum 0) and ``efs`` (argnum 6)
        are donated; ``n_real`` marks the cohort-sharded variant over the
        mesh-padded sampled set (static — fault-schedule cohort
        shrinkage re-specializes via the plan cache)."""
        shard = sharded if sharded is not None else (n_real is not None)
        key = ("round", n_real, static_key, shard)
        label = "round_sharded" if shard else "round"

        def build():
            self._note_build(label, key)

            def round_fn(carry, data, consts, idx, rngs, lr, efs=None,
                         byz=None, per_round=None):
                if self.stages.gathers_cohort:
                    Xs, ys, ns = self._gather(data, idx)
                else:
                    Xs, ys, ns = data.X_train, data.y_train, data.n_train
                new_carry, outs, efs_tail = self._body(
                    carry, data, consts, Xs, ys, ns, idx, rngs, lr, efs,
                    byz, per_round, static_key, n_real, shard)
                epi = self._epilogue(new_carry, data)
                return self._flat(new_carry, epi, outs, efs_tail)

            return self._count_dispatches(jax.jit(
                round_fn,
                donate_argnums=self.eng._donate_argnums(0, 6)),
                label=label)

        return self.eng._plan_cached("_round_prog_cache", key, build)

    def fused_jit(self, k: int, n_real: int | None = None,
                  static_key=None, sharded: bool | None = None):
        """K rounds as ONE dispatched program: a ``lax.scan`` over the
        exact per-round body, consuming host-precomputed stacks of
        sampling indices / per-client rngs / round lrs (+ the byz plan
        and any declared per-round operands). Amortizes the per-dispatch
        latency the sequential loop pays K times (PROFILE.md round 2).
        Donates the carry; cached on the engine as
        ``_fused_round_jit_cache`` (the one-compiled-program-per-window
        pin reads it)."""
        shard = sharded if sharded is not None else (n_real is not None)
        key = (k, n_real, static_key, shard)
        label = (f"fused_sharded_k{k}" if shard else f"fused_k{k}")

        def build():
            self._note_build(label, key)

            def fused_round_fn(carry, data, consts, idx, rngs, lrs,
                               byz=None, per_round=None):
                def one_round(c, xs):
                    if self.stages.gathers_cohort:
                        Xs, ys, ns = self._gather(data, xs["idx"])
                    else:
                        Xs, ys, ns = (data.X_train, data.y_train,
                                      data.n_train)
                    # per-step slices of the [K]-stacked per-round
                    # operands, already in st.per_round order
                    pr = tuple(xs["pr"]) if "pr" in xs else None
                    new_carry, outs, _ = self._body(
                        c, data, consts, Xs, ys, ns, xs.get("idx"),
                        xs["rngs"], xs["lr"], None, xs.get("byz"), pr,
                        static_key, n_real, shard)
                    return (tuple(new_carry[n]
                                  for n in self.stages.carry),
                            tuple(outs[o] for o in self.stages.outputs
                                  + self.health_names))

                xs = {"idx": idx, "rngs": rngs, "lr": lrs}
                if byz is not None:
                    xs["byz"] = byz
                if per_round is not None:
                    xs["pr"] = per_round
                carry, outs = jax.lax.scan(one_round, tuple(carry), xs)
                epi = self._epilogue(dict(zip(self.stages.carry, carry)),
                                     data)
                return (*carry, *epi, *outs)

            return self._count_dispatches(jax.jit(
                fused_round_fn,
                donate_argnums=self.eng._donate_argnums(0)),
                label=label, rounds=k, health_stacked=True)

        return self.eng._plan_cached("_fused_round_jit_cache", key,
                                     build)

    def _reject_streamed_epilogue(self):
        """The streamed programs have no resident federation data to
        hand an epilogue stage (``_epilogue`` would receive data=None
        and the fused scan drops the epilogue outputs entirely) — fail
        loudly instead of miscompiling the declaration. An engine that
        needs both keeps its streaming outside the builder (dpsgd's
        chunked ``_round_streaming`` is the precedent)."""
        if self.stages is not None and self.stages.epilogue is not None:
            raise ValueError(
                f"{type(self.eng).__name__} declares an epilogue stage "
                "and streams through the builder: the streamed round "
                "program has no resident data for the epilogue")

    def stream_jit(self):
        """The streamed single-round program: shards arrive pre-gathered
        (data/stream.py feeds the sampled clients' padded arrays), the
        federation data never enters the program."""
        self._reject_streamed_epilogue()

        def build():
            self._note_build("stream", ("stream",))

            def stream_round_fn(carry, consts, Xs, ys, ns, idx, rngs, lr,
                                efs=None, byz=None):
                new_carry, outs, efs_tail = self._body(
                    carry, None, consts, Xs, ys, ns, idx, rngs, lr, efs,
                    byz, None, None, None, False)
                epi = self._epilogue(new_carry, None)
                return self._flat(new_carry, epi, outs, efs_tail)

            return self._count_dispatches(jax.jit(
                stream_round_fn,
                donate_argnums=self.eng._donate_argnums(0)),
                label="stream")

        return self.eng._plan_cached("_round_prog_cache", ("stream",),
                                     build)

    def fused_stream_jit(self, k: int):
        """K STREAMED rounds as one dispatched program (ISSUE 10): a
        ``lax.scan`` over the exact streamed per-round body, consuming
        the window's prefetched ``[K, S, nmax, ...]`` shard stacks one
        round per step. The carried state is donated like every round
        program's; the uint8/int32 shard stacks are NOT — no output
        shares their dtype/shape, so the donation would be unusable (XLA
        warns and ignores it) and the buffers die at end of dispatch
        anyway."""
        self._reject_streamed_epilogue()
        label = f"fused_stream_k{k}"

        def build():
            self._note_build(label, ("stream", k))

            def fused_stream_round_fn(carry, consts, Xs, ys, ns, rngs,
                                      lrs, byz=None):
                def one_round(c, xs):
                    new_carry, outs, _ = self._body(
                        c, None, consts, xs["X"], xs["y"], xs["n"], None,
                        xs["rngs"], xs["lr"], None, xs.get("byz"), None,
                        None, None, False)
                    return (tuple(new_carry[n]
                                  for n in self.stages.carry),
                            tuple(outs[o] for o in self.stages.outputs
                                  + self.health_names))

                xs = {"X": Xs, "y": ys, "n": ns, "rngs": rngs, "lr": lrs}
                if byz is not None:
                    xs["byz"] = byz
                carry, outs = jax.lax.scan(one_round, tuple(carry), xs)
                return (*carry, *outs)

            return self._count_dispatches(jax.jit(
                fused_stream_round_fn,
                donate_argnums=self.eng._donate_argnums(0)),
                label=label, rounds=k, health_stacked=True)

        return self.eng._plan_cached("_fused_round_jit_cache",
                                     ("stream", k), build)

    # ---------- the fused window driver ----------

    def run_window(self, carry: tuple, round_idx: int, k: int,
                   consts: tuple = ()):
        """Dispatch rounds ``[round_idx, round_idx + k)`` as one scan.
        Sampling/rng/lr — and the Byzantine attack plan when the fault
        schedule carries value faults — are precomputed on the host
        round by round (the ``np.random.seed(round_idx)`` contract is
        untouched). Returns ``(new_carry: tuple, epilogue: tuple,
        outs: dict of [k]-stacked arrays, wi: WindowInputs)`` —
        ``wi.k`` may shrink when the fault schedule varies the cohort
        size (or an engine's per-round operands change shape). Queues
        any ``n_bad`` output into the engine's batched non-finite
        accounting."""
        eng, st = self.eng, self.stages
        # the window IS a host boundary pair (ISSUE 9): the prologue and
        # the dispatch are separate host spans — "dispatch" measures the
        # enqueue only (async dispatch races ahead; the sync lands at
        # the next eval/flush boundary, never here)
        with obs_trace.span("window", round=round_idx, k=k):
            with obs_trace.span("window_host_prologue", round=round_idx):
                wi = self.window_inputs(round_idx, k)
            with obs_trace.span("dispatch", round=round_idx, k=wi.k):
                pr = (tuple(wi.per_round[n] for n in st.per_round)
                      if wi.per_round is not None else None)
                # engines that train the FULL cohort (gathers_cohort
                # False) shard without mesh padding — n_real stays None
                # and the armed mesh alone selects the sharded variant
                shard = (wi.n_real is not None
                         or (not st.gathers_cohort and eng._cohort_on))
                out = self.fused_jit(wi.k, wi.n_real, wi.static_key,
                                     sharded=shard)(
                    carry, eng.data, consts, wi.idx, wi.rngs, wi.lrs,
                    wi.byz, pr)
        n_carry = len(st.carry)
        n_epi = len(out) - n_carry - len(st.outputs)
        new_carry = out[:n_carry]
        epi = out[n_carry:n_carry + n_epi]
        outs = dict(zip(st.outputs, out[n_carry + n_epi:]))
        if "n_bad" in outs:
            eng._note_nonfinite(outs["n_bad"])
        return new_carry, epi, outs, wi
