"""Entry point: ``python -m neuroimagedisttraining_tpu.analysis <paths>``."""

import sys

from neuroimagedisttraining_tpu.analysis.cli import main

sys.exit(main())
