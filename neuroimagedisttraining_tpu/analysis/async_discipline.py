"""Async-discipline rules: no blocking calls inside ``asyncfl/`` coroutines.

The load harness (asyncfl/loadgen.py) runs THOUSANDS of simulated
clients as coroutines on one event loop. A single blocking call inside
any ``async def`` body freezes every one of them at once — and unlike a
crash, it freezes them silently: the benchmark still "works", just with
the concurrency quietly serialized. The classic offenders all have
non-blocking spellings one import away (``asyncio.sleep``,
``loop.sock_recv``, awaited stream reads), so the rule family bans the
blocking forms lexically:

- ``async-blocking-call`` — inside an ``async def`` in ``asyncfl/``, a
  NON-awaited call to ``time.sleep``, ``select.select``, or a socket-
  style blocking method (``.accept()``/``.recv()``/``.recv_into()``/
  ``.recvfrom()``/``.sendall()``/``.connect()``) is flagged. Awaited
  calls are exempt by construction (``await loop.sock_connect(...)`` is
  the sanctioned spelling), and so are nested SYNC ``def``/``lambda``
  bodies — those are exactly what ``run_in_executor`` ships off-loop.
- ``async-queue-get`` — a ``.get()`` call with no positional arguments
  and neither ``timeout=`` nor ``block=False`` inside an ``async def``
  is a blocking ``queue.Queue.get`` (a ``dict.get`` always has a key
  argument, so it never matches); use ``asyncio.Queue`` and await it,
  or pass a timeout.

Scoped to ``asyncfl/`` like the lock rules are scoped to
``distributed/``+``faults/``: the rest of the tree has no event loop to
starve, and e.g. the engines legitimately sleep in fault-injection
paths.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)

#: dotted calls that block the thread (normalized through import aliases)
BLOCKING_DOTTED = {"time.sleep", "select.select"}
#: attribute spellings of blocking socket I/O; receivers travel under
#: too many names to resolve, so the method name is the signal
BLOCKING_SOCKET_METHODS = {"accept", "recv", "recv_into", "recvfrom",
                           "sendall", "connect"}


def _is_awaited(node: ast.Call, parents: dict[ast.AST, ast.AST]) -> bool:
    return isinstance(parents.get(node), ast.Await)


def _parent_map(root: ast.AST) -> dict[ast.AST, ast.AST]:
    return {child: parent for parent in ast.walk(root)
            for child in ast.iter_child_nodes(parent)}


def _async_body_calls(fn: ast.AsyncFunctionDef) -> Iterator[ast.Call]:
    """Calls lexically inside ``fn``'s own body: nested SYNC functions
    and lambdas are excluded (executor-shipped bodies are allowed to
    block), and nested ``async def`` are excluded HERE because
    ``check`` visits every AsyncFunctionDef itself — descending into
    them too would report each violation twice."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class AsyncDisciplineRule(Rule):
    rule_ids = ("async-blocking-call", "async-queue-get")
    description = (
        "inside async def bodies in asyncfl/, no non-awaited blocking "
        "calls: time.sleep / select.select / socket .accept/.recv/"
        ".sendall/.connect (async-blocking-call) and no bare queue "
        ".get() without timeout (async-queue-get)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if "asyncfl" not in mod.path_parts:
            return
        parents = _parent_map(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for call in _async_body_calls(node):
                if _is_awaited(call, parents):
                    continue
                yield from self._check_call(mod, node, call)

    def _check_call(self, mod: ModuleInfo, fn: ast.AsyncFunctionDef,
                    call: ast.Call) -> Iterator[Finding]:
        name = normalize(dotted_name(call.func), mod.aliases)
        if name in BLOCKING_DOTTED:
            yield Finding(
                mod.path, call.lineno, "async-blocking-call",
                f"blocking {name}() inside async def {fn.name!r} freezes "
                "every coroutine on the loop — await asyncio.sleep / use "
                "the loop's non-blocking I/O instead")
            return
        if not isinstance(call.func, ast.Attribute):
            return
        attr = call.func.attr
        if attr in BLOCKING_SOCKET_METHODS:
            yield Finding(
                mod.path, call.lineno, "async-blocking-call",
                f"blocking socket .{attr}() inside async def "
                f"{fn.name!r} — await the asyncio stream/loop.sock_* "
                "equivalent (non-awaited blocking I/O serializes the "
                "whole client fleet)")
        elif attr == "get" and not call.args and not any(
                kw.arg in ("timeout", "block") for kw in call.keywords):
            yield Finding(
                mod.path, call.lineno, "async-queue-get",
                f"bare .get() inside async def {fn.name!r} is a "
                "blocking queue read (dict.get always takes a key) — "
                "use asyncio.Queue and await it, or pass timeout=")
