"""Post-run training-health report (ISSUE 15): one joined artifact.

A finished run leaves its evidence in three places — the metrics JSONL
sink (one registry snapshot per round, keyed by the monotonic
``round``/``seq`` fields ``publish_stat_info`` stamps), the flight
recorder's dump (the last N control-plane decisions, ``alert`` events
included), and the anomaly-rule engine's end-of-run verdict
(``--health_gate``'s document). This module joins them into ONE
``run_report.json`` + a human-readable markdown summary:

- round-by-round convergence/divergence trajectory (train loss, eval
  metrics, the ``nidt_health_*`` update geometry, the epsilon spend);
- the alert timeline (verdict timeline merged with flight ``alert``
  events, in round order);
- the per-silo / per-source epsilon ledger;
- fallback + dispatch accounting (fast-path coverage, compiles,
  dispatch counts) from the final snapshot.

Joins ride the ``round``/``seq`` keys, never timestamps — the JSONL
satellite exists exactly so this module needs no clock heuristics.

CLI::

    python -m neuroimagedisttraining_tpu.analysis.run_report \
        --metrics LOG/.../run.metrics.jsonl \
        --flight  LOG/.../run.flight.json \
        --verdict LOG/.../run.health.json \
        --out /tmp/report_dir

Any input may be absent (a scrapeless run has no flight dump); the
report records what it joined. Dependency-free (stdlib json), like the
rest of ``analysis/``; the committed ``bench_matrix/health_report.json``
exemplar (scripts/run_health_report.sh) is regression-gated by
``analysis/bench_gate.py`` like every other artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

from neuroimagedisttraining_tpu.obs import names as N

__all__ = ["read_metrics_jsonl", "build_report", "render_markdown",
           "main", "SCHEMA"]

SCHEMA = "nidt-run-report-v1"

#: snapshot gauges that become per-round trajectory columns:
#: column name -> (metric name, label subset)
_ROUND_COLUMNS: tuple[tuple[str, str, dict], ...] = (
    ("train_loss", N.EXP_METRIC, {"key": "train_loss"}),
    ("acc", N.EXP_METRIC, {"key": "acc"}),
    ("up_norm_med", N.HEALTH_UPDATE_NORM_MED, {}),
    ("up_norm_max", N.HEALTH_UPDATE_NORM_MAX, {}),
    ("cos_min", N.HEALTH_COSINE_MIN, {}),
    ("cos_mean", N.HEALTH_COSINE_MEAN, {}),
    ("dispersion", N.HEALTH_DIVERGENCE, {}),
    ("param_norm", N.HEALTH_PARAM_NORM, {}),
    ("agg_update_norm", N.HEALTH_AGG_UPDATE_NORM, {}),
    ("mask_density", N.HEALTH_MASK_DENSITY, {}),
    ("epsilon", N.DP_EPSILON, {}),
    ("epsilon_per_round", N.DP_EPSILON_PER_ROUND, {}),
)


def _cells(snap: dict, metric: str) -> list[dict]:
    m = snap.get(metric) or {}
    return list(m.get("values", ()))


def _gauge(snap: dict, metric: str, labels: dict) -> float | None:
    """First matching cell's value (label-subset match); health gauges
    are engine-labeled but single-engine per run, so first == the run's
    series."""
    for cell in _cells(snap, metric):
        lb = cell.get("labels", {})
        if all(lb.get(k) == v for k, v in labels.items()):
            v = cell.get("value")
            if isinstance(v, (int, float)):
                return float(v)
            return None
    return None


def read_metrics_jsonl(path: str) -> list[dict]:
    """The sink's records, sorted by the monotonic ``seq``. Records
    without a ``round`` field (pre-ISSUE-15 sinks) are dropped — the
    join key IS the contract."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a torn final line must not kill the report
            if isinstance(rec, dict) and "round" in rec \
                    and "metrics" in rec:
                out.append(rec)
    out.sort(key=lambda r: r.get("seq", 0))
    return out


def _load(path: str | None) -> dict | None:
    if not path:
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def build_report(metrics_records: list[dict] | None,
                 flight_doc: dict | None,
                 verdict_doc: dict | None) -> dict:
    """Pure join: the three sources in, one report document out."""
    records = metrics_records or []
    rounds: list[dict] = []
    for rec in records:
        snap = rec.get("metrics", {})
        row: dict[str, Any] = {"round": int(rec["round"]),
                               "seq": int(rec.get("seq", 0))}
        for col, metric, labels in _ROUND_COLUMNS:
            v = _gauge(snap, metric, labels)
            if v is not None:
                row[col] = v
        rounds.append(row)

    # alert timeline: the verdict's (authoritative — debounced edges
    # with values) merged with flight `alert`/`alert_clear` events
    # (which survive even when the verdict was never written)
    timeline: list[dict] = []
    seen = set()
    for src, events in (
            ("verdict", (verdict_doc or {}).get("timeline", ())),
            ("flight", [e for e in (flight_doc or {}).get("events", ())
                        if e.get("kind") in ("alert", "alert_clear")])):
        for e in events:
            key = (e.get("kind"), e.get("rule"), e.get("round"))
            if key in seen:
                continue
            seen.add(key)
            timeline.append({"kind": e.get("kind"),
                             "rule": e.get("rule"),
                             "severity": e.get("severity"),
                             "round": e.get("round"),
                             "value": e.get("value"),
                             "source": src})
    timeline.sort(key=lambda e: (e["round"] if isinstance(e["round"],
                                                          int) else -1))

    final_snap = records[-1]["metrics"] if records else {}

    # epsilon ledger: running totals + burn rates per source, the
    # per-silo map when the cross-silo ledger published one
    ledger: dict[str, Any] = {"sources": {}, "per_silo": {}}
    for cell in _cells(final_snap, N.DP_EPSILON):
        src = cell.get("labels", {}).get("source", "")
        ledger["sources"][src] = {"epsilon": cell.get("value")}
    for cell in _cells(final_snap, N.DP_EPSILON_PER_ROUND):
        src = cell.get("labels", {}).get("source", "")
        ledger["sources"].setdefault(src, {})["epsilon_per_round"] = \
            cell.get("value")
    for cell in _cells(final_snap, N.DP_EPSILON_SILO):
        silo = cell.get("labels", {}).get("silo", "")
        ledger["per_silo"][silo] = cell.get("value")
    eps_rounds = [
        {"round": r["round"], "epsilon": r.get("epsilon"),
         "epsilon_per_round": r.get("epsilon_per_round")}
        for r in rounds if r.get("epsilon") is not None]
    ledger["trajectory"] = eps_rounds

    # fallback / dispatch accounting from the final snapshot
    fallbacks = [
        {"plane": c["labels"].get("plane"),
         "engine": c["labels"].get("engine"),
         "reason": c["labels"].get("reason"), "count": c["value"]}
        for c in _cells(final_snap, N.FALLBACK_TOTAL)]
    compiles = {
        f'{c["labels"].get("engine")}/{c["labels"].get("program")}':
        c["value"] for c in _cells(final_snap, N.COMPILES_TOTAL)}
    dispatch_count = sum(
        c["value"].get("count", 0)
        for c in _cells(final_snap, N.DISPATCH_MS)
        if isinstance(c.get("value"), dict))

    verdict = verdict_doc or {}
    alerts_total = int(verdict.get(
        "alerts_total",
        sum(1 for e in timeline if e["kind"] == "alert")))
    report = {
        "schema": SCHEMA,
        "summary": {
            "schema_ok": True,
            "rounds": len(rounds),
            "status": verdict.get("status", "unknown"),
            "worst_status": verdict.get("worst_status", "unknown"),
            "alerts_total": alerts_total,
            "first_round": rounds[0]["round"] if rounds else None,
            "last_round": rounds[-1]["round"] if rounds else None,
            "final": {k: rounds[-1].get(k)
                      for k in ("train_loss", "acc", "cos_min",
                                "dispersion")} if rounds else {},
            "joined": {"metrics": bool(records),
                       "flight": flight_doc is not None,
                       "verdict": verdict_doc is not None},
        },
        "rounds": rounds,
        "alerts": timeline,
        # reflex plane (ISSUE 20): the verdict carries the action log
        # (timestamp-free, rule provenance on every entry) — lift it to
        # a top-level block so the report answers "what did the run DO
        # about its alerts" next to the alerts themselves
        "actions": (verdict_doc or {}).get("actions"),
        "epsilon_ledger": ledger,
        "dispatch": {"fallbacks": fallbacks, "compiles": compiles,
                     "dispatches": dispatch_count},
        "verdict": verdict,
        "flight": ({"capacity": flight_doc.get("capacity"),
                    "evicted": flight_doc.get("evicted"),
                    "events": len(flight_doc.get("events", ()))}
                   if flight_doc else None),
    }
    return report


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_markdown(report: dict) -> str:
    """The human half: a summary header, the trajectory table (capped),
    the alert timeline, the epsilon ledger, fast-path accounting."""
    s = report["summary"]
    lines = [
        "# Run report",
        "",
        f"- **status**: {s['status']} (worst over run: "
        f"{s['worst_status']})",
        f"- **rounds joined**: {s['rounds']} "
        f"(rounds {_fmt(s['first_round'])}..{_fmt(s['last_round'])})",
        f"- **alerts**: {s['alerts_total']}",
        f"- **inputs joined**: " + ", ".join(
            k for k, v in s["joined"].items() if v) + (
            "" if all(s["joined"].values()) else
            " (missing: " + ", ".join(
                k for k, v in s["joined"].items() if not v) + ")"),
        "",
        "## Trajectory",
        "",
    ]
    cols = ("round", "train_loss", "acc", "up_norm_med", "cos_min",
            "dispersion", "epsilon")
    lines.append("| " + " | ".join(cols) + " |")
    lines.append("|" + "---|" * len(cols))
    rows = report["rounds"]
    shown = rows if len(rows) <= 60 else rows[:30] + rows[-30:]
    last_r = None
    for r in shown:
        if last_r is not None and r["round"] != last_r + 1 \
                and shown is not rows:
            lines.append("| ... |" + " |" * (len(cols) - 1))
        last_r = r["round"]
        lines.append("| " + " | ".join(_fmt(r.get(c)) for c in cols)
                     + " |")
    lines += ["", "## Alert timeline", ""]
    if report["alerts"]:
        for e in report["alerts"]:
            lines.append(
                f"- round {_fmt(e['round'])}: **{e['kind']}** "
                f"`{e['rule']}` ({e['severity']}, value "
                f"{_fmt(e['value'])})")
    else:
        lines.append("- none (a clean run)")
    acts = report.get("actions")
    if acts is not None and acts.get("mode", "unarmed") != "unarmed":
        lines += ["", "## Reflex actions", "",
                  f"- mode: `{acts['mode']}`; dispatches: "
                  f"{_fmt(acts.get('total'))}"]
        for e in acts.get("log", ()):
            tag = " (dry_run)" if e.get("dry_run") else ""
            lines.append(
                f"- round {_fmt(e.get('round'))}: **{e['action']}** "
                f"<- rule `{e['rule']}` [{e['status']}]{tag}"
                + (f" {e['detail']}" if e.get("detail") else ""))
    ledger = report["epsilon_ledger"]
    if ledger["sources"] or ledger["per_silo"]:
        lines += ["", "## Epsilon ledger", ""]
        for src, d in sorted(ledger["sources"].items()):
            lines.append(
                f"- source `{src}`: epsilon {_fmt(d.get('epsilon'))} "
                f"(last round burn "
                f"{_fmt(d.get('epsilon_per_round'))})")
        for silo, eps in sorted(ledger["per_silo"].items()):
            lines.append(f"- silo {silo}: epsilon {_fmt(eps)}")
    d = report["dispatch"]
    lines += ["", "## Fast-path accounting", "",
              f"- dispatches: {_fmt(d['dispatches'])}; program builds: "
              f"{_fmt(sum(d['compiles'].values()) if d['compiles'] else 0)}"]
    if d["fallbacks"]:
        for fb in d["fallbacks"]:
            lines.append(
                f"- fallback [{fb['plane']}] {fb['engine']}: "
                f"{fb['reason']} x{int(fb['count'])}")
    else:
        lines.append("- no fast-path fallbacks announced")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m neuroimagedisttraining_tpu.analysis.run_report",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--metrics", type=str, default="",
                    help="metrics JSONL sink (--metrics_out)")
    ap.add_argument("--flight", type=str, default="",
                    help="flight-recorder dump (--flight_out / the "
                         "LOG/... .flight.json failure dump)")
    ap.add_argument("--verdict", type=str, default="",
                    help="health verdict JSON (LOG/... .health.json)")
    ap.add_argument("--out", type=str, required=True,
                    help="output directory (run_report.json + "
                         "run_report.md)")
    ap.add_argument("--name", type=str, default="run_report",
                    help="artifact basename (default run_report)")
    args = ap.parse_args(argv)
    if not (args.metrics or args.flight or args.verdict):
        print("run_report: need at least one of --metrics/--flight/"
              "--verdict", file=sys.stderr)
        return 2
    records = None
    if args.metrics:
        try:
            records = read_metrics_jsonl(args.metrics)
        except OSError as e:
            print(f"run_report: --metrics: {e}", file=sys.stderr)
            return 2
    report = build_report(records, _load(args.flight),
                          _load(args.verdict))
    os.makedirs(args.out, exist_ok=True)
    jpath = os.path.join(args.out, args.name + ".json")
    mpath = os.path.join(args.out, args.name + ".md")
    with open(jpath, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)
    with open(mpath, "w") as f:
        f.write(render_markdown(report))
    print(json.dumps({"report": jpath, "markdown": mpath,
                      "summary": report["summary"]}, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
