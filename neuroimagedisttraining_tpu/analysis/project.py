"""nidtlint project model: the tree parsed ONCE into cross-file facts.

The per-file rule families (trace safety, lock discipline, ...) see one
module at a time, so none of the repo's *declarative* contracts are
checkable there: a flag added to one CLI but not the other, a rule
manifest naming a metric no engine publishes, or a ctor rejection that
contradicts ARCHITECTURE.md's compatibility tables all land silently.
This module builds the whole-program model those contracts are stated
against — every module parsed once into the same :class:`ModuleInfo`
the per-file rules use, plus extraction helpers for each declarative
surface:

- argparse ``add_argument`` calls (both CLIs) -> :class:`FlagInfo`
- frozen-dataclass fields + defaults (``config.py``)
- the ``config_from_args`` flag->field mapping (wrapper-aware:
  ``tuple(args.x)``, ``bool(args.x)``, ``not args.x``,
  ``args.x.lower()``)
- ``obs/names.py`` declarations, every ``names.*`` attribute use, and
  every ``obs.metrics.counter/gauge/histogram`` registration site
- the ``engines/program.py`` ``REASONS`` table and its uses
- ``analysis/bench_gate.py`` ``SPECS`` cells vs the committed
  ``bench_matrix/*.json`` artifacts
- startup-rejection sites (``parser.error``/``ap.error`` in the CLIs,
  ``raise ValueError`` in ctors) -> compatibility-matrix rows

The contract rules themselves live in ``analysis/contracts.py``; the
driver is :func:`lint_project` (CLI: ``--project``). Project findings
ride the existing pragma machinery — a ``# nidt: allow[rule-id] --
why`` on the flagged line suppresses, with the justification mandatory
as everywhere else.

Dependency-free (stdlib ``ast``/``json``), like the rest of
``analysis/``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Any, Iterable, Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    _apply_suppressions,
    _selected_rules,
    collect_aliases,
    dotted_name,
    iter_py_files,
    normalize,
    parse_pragmas,
)

#: sentinel for defaults the extractor cannot evaluate statically
UNEVAL = object()


class ProjectRule(Rule):
    """A rule family that checks the cross-file model instead of one
    module. The per-file ``check`` is a no-op so registering a project
    family never changes ``lint_paths`` output; ``project_check`` runs
    only under ``lint_project`` (CLI ``--project``)."""

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        return iter(())

    def project_check(self, model: "ProjectModel") -> Iterator[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class ProjectModel:
    """Every package module parsed once, keyed by posix path relative
    to ``root`` (the directory CONTAINING the package dir), so findings
    and committed artifacts are stable across checkouts."""

    root: str
    package: str
    modules: dict[str, ModuleInfo]

    def module(self, relpath: str) -> ModuleInfo | None:
        return self.modules.get(relpath)

    def find(self, suffix: str) -> ModuleInfo | None:
        """The unique module whose relpath ends with ``suffix`` (None
        when absent — synthetic fixture trees omit most surfaces)."""
        for rel, mod in self.modules.items():
            if rel.endswith(suffix):
                return mod
        return None


def build_model(root: str, package: str) -> ProjectModel:
    modules: dict[str, ModuleInfo] = {}
    pkg_dir = os.path.join(root, package)
    for fp in iter_py_files([pkg_dir]):
        rel = os.path.relpath(fp, root).replace(os.sep, "/")
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError:
            continue  # the per-file pass owns parse-error findings
        modules[rel] = ModuleInfo(
            path=rel, source=source, tree=tree,
            pragmas=parse_pragmas(source), aliases=collect_aliases(tree))
    return ProjectModel(root=root, package=package, modules=modules)


# ---------------------------------------------------------------------------
# argparse flag surface
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FlagInfo:
    """One ``add_argument`` call, statically evaluated."""

    options: tuple[str, ...]
    dest: str
    type: str | None        # 'int' | 'float' | 'str' | None
    default: Any            # UNEVAL when not a literal
    choices: Any            # tuple | UNEVAL | None
    action: str | None      # 'store_true' | ...
    nargs: Any
    required: bool
    lineno: int


def _literal(node: ast.AST | None, default: Any = None) -> Any:
    if node is None:
        return default
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return UNEVAL


def argparse_flags(mod: ModuleInfo) -> dict[str, FlagInfo]:
    """Every ``<parser>.add_argument("--flag", ...)`` in the module,
    keyed by dest. Positional arguments (no leading ``--``) and
    non-constant option strings are skipped."""
    flags: dict[str, FlagInfo] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            continue
        options = tuple(a.value for a in node.args
                        if isinstance(a, ast.Constant)
                        and isinstance(a.value, str)
                        and a.value.startswith("--"))
        if not options:
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        dest = _literal(kw.get("dest"))
        if not isinstance(dest, str):
            dest = options[0].lstrip("-").replace("-", "_")
        type_name = None
        if "type" in kw:
            type_name = dotted_name(kw["type"])
        action = _literal(kw.get("action"))
        default = _literal(kw.get("default"), default=None)
        if action == "store_true" and "default" not in kw:
            default = False
        elif action == "store_false" and "default" not in kw:
            default = True
        flags[dest] = FlagInfo(
            options=options, dest=dest,
            type=type_name if isinstance(type_name, str) else None,
            default=default,
            choices=_literal(kw.get("choices"), default=None),
            action=action if isinstance(action, str) else None,
            nargs=_literal(kw.get("nargs"), default=None),
            required=bool(_literal(kw.get("required"), default=False)
                          is True),
            lineno=node.lineno)
    return flags


def attr_reads(mod: ModuleInfo, base: str,
               skip_funcs: tuple[str, ...] = ()) -> set[str]:
    """Every ``<base>.<attr>`` read in the module, optionally excluding
    the bodies of the named top-level functions (``add_args`` declares
    flags, it does not consume them)."""
    skip_spans: list[tuple[int, int]] = []
    for node in ast.walk(mod.tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in skip_funcs
                and node.end_lineno is not None):
            skip_spans.append((node.lineno, node.end_lineno))
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == base):
            if any(a <= node.lineno <= b for a, b in skip_spans):
                continue
            out.add(node.attr)
    return out


# ---------------------------------------------------------------------------
# config dataclasses + the config_from_args mapping
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FieldInfo:
    cls: str
    name: str
    default: Any           # UNEVAL for default_factory / non-literals
    lineno: int


def dataclass_fields(mod: ModuleInfo) -> dict[str, dict[str, FieldInfo]]:
    """Annotated fields of every ``@dataclass`` class, keyed by class
    then field name. ``field(default_factory=...)`` and other
    non-literal defaults come back as UNEVAL (present, not comparable);
    properties and methods are not fields."""
    out: dict[str, dict[str, FieldInfo]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        is_dc = any(
            (dotted_name(d) or dotted_name(getattr(d, "func", None)) or "")
            .split(".")[-1] == "dataclass"
            for d in node.decorator_list)
        if not is_dc:
            continue
        fields: dict[str, FieldInfo] = {}
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            default: Any = UNEVAL
            if stmt.value is not None:
                default = _literal(stmt.value, default=UNEVAL)
            fields[stmt.target.id] = FieldInfo(
                cls=node.name, name=stmt.target.id,
                default=default, lineno=stmt.lineno)
        out[node.name] = fields
    return out


@dataclasses.dataclass(frozen=True)
class Mapping:
    """One ``field=<wrapper>(args.<dest>)`` assignment inside
    ``config_from_args``. ``wrapper`` is None for the identity case."""

    cls: str
    field: str
    dest: str
    wrapper: str | None    # 'tuple' | 'bool' | 'not' | 'lower' | None
    lineno: int


def _resolve_arg_expr(node: ast.AST) -> tuple[str, str | None] | None:
    """(dest, wrapper) for the recognized ``args.<dest>`` shapes."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "args"):
        return node.attr, None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        inner = _resolve_arg_expr(node.operand)
        if inner and inner[1] is None:
            return inner[0], "not"
    if isinstance(node, ast.Call):
        # tuple(args.x) / bool(args.x)
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("tuple", "bool") and node.args):
            inner = _resolve_arg_expr(node.args[0])
            if inner and inner[1] is None:
                return inner[0], node.func.id
        # args.x.lower()
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "lower" and not node.args):
            inner = _resolve_arg_expr(node.func.value)
            if inner and inner[1] is None:
                return inner[0], "lower"
    return None


def config_mapping(mod: ModuleInfo,
                   func: str = "config_from_args") -> list[Mapping]:
    """Flatten the ``<Config>(field=args.dest, sub=SubConfig(...))``
    construction inside ``func`` into per-field mappings."""
    fn = next((n for n in ast.walk(mod.tree)
               if isinstance(n, ast.FunctionDef) and n.name == func), None)
    if fn is None:
        return []
    out: list[Mapping] = []

    def visit(call: ast.Call) -> None:
        cls = (dotted_name(call.func) or "").split(".")[-1]
        if not cls.endswith("Config"):
            return
        for kwarg in call.keywords:
            if kwarg.arg is None:
                continue
            if isinstance(kwarg.value, ast.Call):
                inner_cls = (dotted_name(kwarg.value.func) or "")
                if inner_cls.split(".")[-1].endswith("Config"):
                    visit(kwarg.value)
                    continue
            resolved = _resolve_arg_expr(kwarg.value)
            if resolved is not None:
                out.append(Mapping(cls=cls, field=kwarg.arg,
                                   dest=resolved[0], wrapper=resolved[1],
                                   lineno=kwarg.value.lineno))
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            cls = (dotted_name(node.func) or "").split(".")[-1]
            if cls.endswith("Config"):
                visit(node)
                break
    return out


def config_assigned_fields(mod: ModuleInfo,
                           func: str = "config_from_args"
                           ) -> dict[str, set[str]]:
    """Every keyword name passed to a ``*Config(...)`` construction in
    ``func``, keyed by class — broader than :func:`config_mapping`: a
    field assigned a computed (non-``args``) expression is still
    deliberately covered, it just is not default-comparable."""
    fn = next((n for n in ast.walk(mod.tree)
               if isinstance(n, ast.FunctionDef) and n.name == func), None)
    out: dict[str, set[str]] = {}
    if fn is None:
        return out
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        cls = (dotted_name(node.func) or "").split(".")[-1]
        if not cls.endswith("Config"):
            continue
        for kwarg in node.keywords:
            if kwarg.arg is not None:
                out.setdefault(cls, set()).add(kwarg.arg)
    return out


def apply_wrapper(value: Any, wrapper: str | None) -> Any:
    """The argparse default as the dataclass would receive it."""
    if value is UNEVAL:
        return UNEVAL
    try:
        if wrapper == "tuple":
            return tuple(value)
        if wrapper == "bool":
            return bool(value)
        if wrapper == "not":
            return not value
        if wrapper == "lower":
            return value.lower()
    except (TypeError, AttributeError):
        return UNEVAL
    return value


# ---------------------------------------------------------------------------
# metric names: declarations, uses, registrations
# ---------------------------------------------------------------------------

def names_table(mod: ModuleInfo) -> dict[str, tuple[str, int]]:
    """``CONST -> (value, lineno)`` for the module's top-level string
    assignments (the obs/names.py declaration table)."""
    out: dict[str, tuple[str, int]] = {}
    for stmt in mod.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            out[stmt.targets[0].id] = (stmt.value.value, stmt.lineno)
    return out


def names_attr_uses(model: ProjectModel
                    ) -> list[tuple[str, str, int]]:
    """Every ``<names-alias>.CONST`` attribute access in the tree:
    ``(relpath, CONST, lineno)``. Covers rule manifests' builtin
    construction, /healthz blocks, bench plumbing — any consumer that
    spells a metric through the declared table."""
    uses: list[tuple[str, str, int]] = []
    for rel, mod in model.modules.items():
        local_names = {local for local, canon in mod.aliases.items()
                       if canon.endswith("obs.names")}
        if not local_names:
            continue
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in local_names):
                uses.append((rel, node.attr, node.lineno))
    return uses


@dataclasses.dataclass(frozen=True)
class Registration:
    """One ``obs.metrics.counter/gauge/histogram(<name>, ...)`` site."""

    relpath: str
    kind: str
    const: str | None      # names.CONST spelling, when used
    literal: str | None    # literal string spelling, when used
    lineno: int


_METRIC_FACTORIES = ("counter", "gauge", "histogram")


def metric_registrations(model: ProjectModel) -> list[Registration]:
    regs: list[Registration] = []
    for rel, mod in model.modules.items():
        metric_locals = {local for local, canon in mod.aliases.items()
                         if canon.endswith("obs.metrics")}
        names_locals = {local for local, canon in mod.aliases.items()
                        if canon.endswith("obs.names")}
        if not metric_locals:
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_FACTORIES
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in metric_locals
                    and node.args):
                continue
            arg = node.args[0]
            const = literal = None
            if (isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id in names_locals):
                const = arg.attr
            elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                literal = arg.value
            else:
                continue  # parameterized helpers register via their callers
            regs.append(Registration(relpath=rel, kind=node.func.attr,
                                     const=const, literal=literal,
                                     lineno=node.lineno))
    return regs


def string_literals(mod: ModuleInfo) -> Iterator[tuple[str, int]]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno


# ---------------------------------------------------------------------------
# REASONS fallback table
# ---------------------------------------------------------------------------

def reasons_table(model: ProjectModel) -> dict[str, int]:
    """``key -> lineno`` of the engines/program.py REASONS literal."""
    mod = model.find("engines/program.py")
    if mod is None:
        return {}
    for stmt in mod.tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if (isinstance(target, ast.Name) and target.id == "REASONS"
                and isinstance(stmt.value, ast.Dict)):
            return {k.value: k.lineno for k in stmt.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


def reasons_span(model: ProjectModel) -> tuple[int, int]:
    """Line span of the REASONS table literal itself (so the orphan
    check does not count a key's own declaration as a use)."""
    mod = model.find("engines/program.py")
    if mod is None:
        return (0, 0)
    for stmt in mod.tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if (isinstance(target, ast.Name) and target.id == "REASONS"
                and isinstance(stmt.value, ast.Dict)):
            return (stmt.lineno, stmt.end_lineno or stmt.lineno)
    return (0, 0)


def reason_key_uses(model: ProjectModel
                    ) -> list[tuple[str, str, int]]:
    """Literal reason-key uses: ``*_fallback_key`` returns plus literal
    arguments to ``report_fallback(engine, key)`` / ``reason(key)``.
    ``(relpath, key, lineno)``."""
    uses: list[tuple[str, str, int]] = []
    for rel, mod in model.modules.items():
        if rel.endswith("engines/program.py"):
            continue  # the table's own module declares, it cannot drift
        for node in ast.walk(mod.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name.endswith("_fallback_key")):
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Return)
                            and isinstance(sub.value, ast.Constant)
                            and isinstance(sub.value.value, str)):
                        uses.append((rel, sub.value.value, sub.lineno))
            if isinstance(node, ast.Call):
                fname = (dotted_name(node.func) or "").split(".")[-1]
                key_arg = None
                if fname == "report_fallback" and len(node.args) >= 2:
                    key_arg = node.args[1]
                elif fname == "reason" and len(node.args) == 1:
                    key_arg = node.args[0]
                if (isinstance(key_arg, ast.Constant)
                        and isinstance(key_arg.value, str)):
                    uses.append((rel, key_arg.value, key_arg.lineno))
    return uses


# ---------------------------------------------------------------------------
# bench_gate SPECS vs committed bench_matrix artifacts
# ---------------------------------------------------------------------------

def bench_specs(model: ProjectModel
                ) -> dict[str, list[tuple[str, int]]]:
    """``artifact.json -> [(dotted cell path, lineno), ...]`` from the
    bench_gate SPECS literal."""
    mod = model.find("analysis/bench_gate.py")
    if mod is None:
        return {}
    out: dict[str, list[tuple[str, int]]] = {}
    for stmt in mod.tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if not (isinstance(target, ast.Name) and target.id == "SPECS"
                and isinstance(stmt.value, ast.Dict)):
            continue
        for key, val in zip(stmt.value.keys, stmt.value.values):
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and isinstance(val, (ast.Tuple, ast.List))):
                continue
            cells: list[tuple[str, int]] = []
            for el in val.elts:
                if (isinstance(el, ast.Call) and el.args
                        and isinstance(el.args[0], ast.Constant)
                        and isinstance(el.args[0].value, str)):
                    cells.append((el.args[0].value, el.args[0].lineno))
            out[key.value] = cells
    return out


def resolve_cell(doc: Any, dotted: str) -> bool:
    """True when the dotted path resolves in the artifact document
    (dict keys and integer list indices)."""
    cur = doc
    for part in dotted.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        elif isinstance(cur, list) and part.isdigit() \
                and int(part) < len(cur):
            cur = cur[int(part)]
        else:
            return False
    return True


# ---------------------------------------------------------------------------
# autotuner recipe keys vs committed recipes (tune/recipe.py)
# ---------------------------------------------------------------------------

def recipe_keys_table(model: ProjectModel
                      ) -> dict[str, tuple[str, int]]:
    """``cell key -> (CLI option, lineno)`` from the tune/recipe.py
    ``RECIPE_KEYS`` literal — the declared set of knobs a recipe may
    set."""
    mod = model.find("tune/recipe.py")
    if mod is None:
        return {}
    for stmt in mod.tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if (isinstance(target, ast.Name) and target.id == "RECIPE_KEYS"
                and isinstance(stmt.value, ast.Dict)):
            out: dict[str, tuple[str, int]] = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    out[k.value] = (v.value, k.lineno)
            return out
    return {}


def committed_recipes(model: ProjectModel) -> dict[str, Any]:
    """Every committed ``bench_matrix/recipes/*.json`` parsed as JSON,
    keyed by file name; unparseable files map to None (the closure
    rule flags them — a committed recipe that does not parse would die
    at --recipe load time)."""
    rdir = os.path.join(model.root, "bench_matrix", "recipes")
    if not os.path.isdir(rdir):
        return {}
    out: dict[str, Any] = {}
    for fn in sorted(os.listdir(rdir)):
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(rdir, fn), encoding="utf-8") as fh:
                out[fn] = json.load(fh)
        except (OSError, json.JSONDecodeError):
            out[fn] = None
    return out


# ---------------------------------------------------------------------------
# reflex-action registry (obs/actions.py BUILTIN_ACTIONS, ISSUE 20)
# ---------------------------------------------------------------------------

def actions_table(model: ProjectModel) -> dict[str, int]:
    """``action name -> lineno`` of the obs/actions.py
    ``BUILTIN_ACTIONS`` literal — the declared registry every rule
    ``action:`` binding must resolve into."""
    mod = model.find("obs/actions.py")
    if mod is None:
        return {}
    for stmt in mod.tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if (isinstance(target, ast.Name)
                and target.id == "BUILTIN_ACTIONS"
                and isinstance(stmt.value, ast.Dict)):
            return {k.value: k.lineno for k in stmt.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


def action_uses(model: ProjectModel) -> list[tuple[str, str, int, str]]:
    """Every literal action-name use across the package:
    ``(relpath, name, lineno, kind)`` with kind one of

    - ``rule``     — ``action="..."`` keyword on a ``HealthRule(...)``
                     call (the binding that makes a firing rule DO it)
    - ``dispatch`` — literal first argument to ``record_action(...)`` /
                     ``on_alert(...)`` (plane-initiated dispatches)
    - ``register`` — literal first argument to ``register(...)`` on the
                     bus (an engine/server realizing the action)

    Keyword matching is restricted to ``HealthRule`` calls so argparse
    ``action="store_true"`` keywords never read as reflex names."""
    uses: list[tuple[str, str, int, str]] = []
    for rel, mod in sorted(model.modules.items()):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else "")
            if name == "HealthRule":
                for kwarg in node.keywords:
                    if (kwarg.arg == "action"
                            and isinstance(kwarg.value, ast.Constant)
                            and isinstance(kwarg.value.value, str)
                            and kwarg.value.value):
                        uses.append((rel, kwarg.value.value,
                                     kwarg.value.lineno, "rule"))
            elif name in ("record_action", "on_alert", "register"):
                if (node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    kind = ("register" if name == "register"
                            else "dispatch")
                    uses.append((rel, node.args[0].value,
                                 node.args[0].lineno, kind))
    return uses


# ---------------------------------------------------------------------------
# startup-rejection sites -> compatibility-matrix rows
# ---------------------------------------------------------------------------

def _message_text(call: ast.Call) -> str:
    """The human message of an error/raise call: constant string parts
    concatenated (f-string holes dropped), whitespace collapsed."""
    parts: list[str] = []
    for node in ast.walk(call):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            parts.append(node.value)
    text = " ".join(" ".join(parts).split())
    return text[:140]


def _cond_attr_names(test: ast.AST, bases: tuple[str, ...]) -> set[str]:
    """Terminal attribute names read off the given bases (``args.x``,
    ``cfg.fed.x`` -> x) plus bare Names, inside a guard expression."""
    out: set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute):
            base = node.value
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name) and base.id in bases:
                out.add(node.attr)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.add(node.id)
    return out


def _annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._nidt_pparent = node  # type: ignore[attr-defined]


def _enclosing_guards(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "_nidt_pparent", None)
    while cur is not None:
        if isinstance(cur, ast.If):
            yield cur.test
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        cur = getattr(cur, "_nidt_pparent", None)


def rejection_rows(model: ProjectModel,
                   knob_vocab: set[str]) -> list[dict[str, Any]]:
    """Compatibility-matrix rows extracted from startup-rejection
    sites: ``parser.error``/``ap.error`` calls in the CLIs and ``raise
    ValueError`` inside ``__init__`` bodies. A row qualifies when its
    guard reads >= 2 distinct knobs from the flag/config vocabulary —
    that is a *compatibility* rejection; single-knob range checks are
    plain validation and stay out of the matrix."""
    rows: list[dict[str, Any]] = []
    for rel, mod in model.modules.items():
        _annotate_parents(mod.tree)
        for node in ast.walk(mod.tree):
            call = None
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "error"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("parser", "ap")):
                call = node
            elif (isinstance(node, ast.Raise)
                    and isinstance(node.exc, ast.Call)
                    and (dotted_name(node.exc.func) or "")
                    .split(".")[-1] == "ValueError"):
                fn = getattr(node, "_nidt_pparent", None)
                while fn is not None and not isinstance(
                        fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = getattr(fn, "_nidt_pparent", None)
                if fn is None or fn.name != "__init__":
                    continue
                call = node.exc
            if call is None:
                continue
            knobs: set[str] = set()
            for test in _enclosing_guards(call):
                knobs |= _cond_attr_names(
                    test, ("args", "self", "cfg", "config"))
            knobs &= knob_vocab
            if len(knobs) < 2:
                continue
            rows.append({
                "where": rel,
                "knobs": tuple(sorted(knobs)),
                "message": _message_text(call),
                # anchor for drift findings; stripped from the artifact
                "_line": call.lineno,
            })
    seen: set[tuple] = set()
    uniq = []
    for row in sorted(rows, key=lambda r: (r["where"], r["knobs"],
                                           r["message"])):
        key = (row["where"], row["knobs"], row["message"])
        if key not in seen:
            seen.add(key)
            uniq.append(row)
    return uniq


def knob_vocabulary(model: ProjectModel) -> set[str]:
    """Flag dests of both CLIs + every config dataclass field — the
    identifier set a matrix row's guard is read against."""
    vocab: set[str] = set()
    for suffix in ("/__main__.py", "distributed/run.py"):
        mod = model.find(suffix)
        if mod is not None:
            vocab |= set(argparse_flags(mod))
    cfg = model.find("/config.py")
    if cfg is not None:
        for fields in dataclass_fields(cfg).values():
            vocab |= set(fields)
    return vocab


# ---------------------------------------------------------------------------
# committed compat matrix artifact + markdown twin
# ---------------------------------------------------------------------------

#: markers delimiting the generated table inside ARCHITECTURE.md
MD_BEGIN = "<!-- nidt:compat-matrix:begin (generated; do not edit) -->"
MD_END = "<!-- nidt:compat-matrix:end -->"

_MATRIX_HEADER = '''"""Generated compatibility matrix — DO NOT EDIT BY HAND.

Extracted from the tree's startup-rejection sites (``parser.error`` /
``ap.error`` in the CLIs, ``raise ValueError`` in ctors) by the
contract checker (analysis/contracts.py). Each row names WHERE the
rejection lives, WHICH knobs its guard reads, and the message —
the machine-readable twin of ARCHITECTURE.md's compatibility tables.

Regenerate (also rewrites the ARCHITECTURE.md block)::

    python -m neuroimagedisttraining_tpu.analysis --regen-compat

The project pass (``--project``) diffs this artifact against a fresh
extraction (``compat-matrix-drift``) and the markdown twin against
this artifact (``compat-matrix-doc-stale``), so a new ctor rejection
without a regenerated matrix — or a hand-edited table — fails the
lint.
"""

from __future__ import annotations

from typing import Any

MATRIX: tuple[dict[str, Any], ...] = (
'''


def render_matrix_py(rows: list[dict[str, Any]]) -> str:
    out = [_MATRIX_HEADER]
    for row in rows:
        knobs = ", ".join(repr(k) for k in row["knobs"])
        if len(row["knobs"]) == 1:
            knobs += ","
        out.append("    {\n")
        out.append(f'        "where": {row["where"]!r},\n')
        out.append(f'        "knobs": ({knobs}),\n')
        out.append(f'        "message": (\n')
        msg = row["message"]
        if not msg:
            out.append('            ""),\n')
        for i in range(0, len(msg), 60):
            tail = "" if i + 60 < len(msg) else "),"
            out.append(f'            {msg[i:i + 60]!r}{tail}\n')
        out.append("    },\n")
    out.append(")\n")
    return "".join(out)


def render_matrix_md(rows: list[dict[str, Any]]) -> str:
    lines = [MD_BEGIN,
             "",
             "| where | knobs | rejection |",
             "|---|---|---|"]
    for row in rows:
        knobs = ", ".join(f"`{k}`" for k in row["knobs"])
        msg = row["message"].replace("|", "\\|")
        lines.append(f"| `{row['where']}` | {knobs} | {msg} |")
    lines += ["", MD_END]
    return "\n".join(lines)


def committed_matrix(model: ProjectModel) -> list[dict[str, Any]] | None:
    """The MATRIX literal parsed from the committed artifact's source
    (never imported — the checker must not execute the file it is
    judging). None when the artifact does not exist yet."""
    mod = model.find("analysis/compat_matrix.py")
    if mod is None:
        return None
    for stmt in mod.tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if isinstance(target, ast.Name) and target.id == "MATRIX":
            try:
                rows = ast.literal_eval(stmt.value)
            except (ValueError, SyntaxError):
                return None
            return [dict(r, knobs=tuple(r.get("knobs", ())))
                    for r in rows]
    return None


def doc_matrix_block(model: ProjectModel
                     ) -> tuple[str | None, int]:
    """(block text between the markers, begin-marker line) from
    ARCHITECTURE.md at the project root; (None, 0) when absent."""
    path = os.path.join(model.root, "ARCHITECTURE.md")
    if not os.path.exists(path):
        return None, 0
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    begin = text.find(MD_BEGIN)
    end = text.find(MD_END)
    if begin < 0 or end < 0 or end < begin:
        return None, 0
    line = text[:begin].count("\n") + 1
    return text[begin:end + len(MD_END)], line


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def default_root() -> tuple[str, str]:
    """(repo root, package name) inferred from this file's location."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir), os.path.basename(pkg_dir)


def lint_project(root: str | None = None, package: str | None = None,
                 rules: Iterable[str] | None = None) -> list[Finding]:
    """Run every registered :class:`ProjectRule` over the cross-file
    model. Findings anchored in a parsed module honor that module's
    ``# nidt: allow[...]`` pragmas exactly like per-file findings."""
    if root is None or package is None:
        d_root, d_pkg = default_root()
        root = root or d_root
        package = package or d_pkg
    model = build_model(root, package)
    findings: list[Finding] = []
    for rule in _selected_rules(rules):
        if isinstance(rule, ProjectRule):
            findings.extend(rule.project_check(model))
    if rules is not None:
        wanted = set(rules)
        findings = [f for f in findings if f.rule in wanted]
    out: list[Finding] = []
    by_path: dict[str, list[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    for path, fs in by_path.items():
        mod = model.modules.get(path)
        if mod is None:
            out.extend(fs)
        else:
            out.extend(_apply_suppressions(mod, fs))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule, f.message))


def regen_compat(root: str | None = None,
                 package: str | None = None) -> tuple[str, str]:
    """Regenerate the committed matrix artifact and the ARCHITECTURE.md
    block from a fresh extraction; returns the two paths written."""
    if root is None or package is None:
        d_root, d_pkg = default_root()
        root = root or d_root
        package = package or d_pkg
    model = build_model(root, package)
    rows = rejection_rows(model, knob_vocabulary(model))
    py_path = os.path.join(root, package, "analysis", "compat_matrix.py")
    os.makedirs(os.path.dirname(py_path), exist_ok=True)
    with open(py_path, "w", encoding="utf-8") as fh:
        fh.write(render_matrix_py(rows))
    md_path = os.path.join(root, "ARCHITECTURE.md")
    block = render_matrix_md(rows)
    if os.path.exists(md_path):
        with open(md_path, encoding="utf-8") as fh:
            text = fh.read()
        begin = text.find(MD_BEGIN)
        end = text.find(MD_END)
        if begin >= 0 and end > begin:
            text = text[:begin] + block + text[end + len(MD_END):]
        else:
            text = text.rstrip("\n") + "\n\n" + block + "\n"
        with open(md_path, "w", encoding="utf-8") as fh:
            fh.write(text)
    else:
        with open(md_path, "w", encoding="utf-8") as fh:
            fh.write(block + "\n")
    return py_path, md_path


def load_artifact(model: ProjectModel, name: str) -> Any | None:
    """A committed bench_matrix artifact parsed as JSON, or None."""
    path = os.path.join(model.root, "bench_matrix", name)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
