"""Determinism rules: no global-state or unseeded numpy randomness.

Bit-faithful reproduction (PARITY.md) hangs on every random draw being
derived from an explicit seed: client sampling re-seeds the LEGACY global
stream per round only because the reference does (engines/base.py
``client_sampling``, fedavg_api.py:92-100) — those shims are pragma-
annotated, not silently allowed, so the next one added is a conscious
decision.

- ``determinism-global-random`` — any call through numpy's global RNG
  (``np.random.seed``/``choice``/``rand``/...): global-stream draws are
  order-dependent across threads and modules, so results stop being a
  pure function of the config seed.
- ``determinism-unseeded-rng`` — ``np.random.default_rng()`` /
  ``RandomState()`` with no seed pulls OS entropy; every generator must
  be constructed from a config-derived seed.

Seeded constructors (``default_rng(seed)``, ``RandomState(seed)``) and
``jax.random`` keys are the sanctioned APIs and are never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)

#: constructors of independent generators — fine when given a seed
_CONSTRUCTORS = {"default_rng", "RandomState", "Generator", "SeedSequence",
                 "PCG64", "Philox", "MT19937", "SFC64", "BitGenerator"}


def _np_random_member(call: ast.Call, aliases: dict[str, str]) -> str | None:
    name = normalize(dotted_name(call.func), aliases)
    if name and name.startswith("numpy.random."):
        return name[len("numpy.random."):]
    return None


@register
class DeterminismRule(Rule):
    rule_ids = ("determinism-global-random", "determinism-unseeded-rng")
    description = ("no numpy global-stream randomness (np.random.seed/"
                   "choice/...) and no unseeded default_rng()/RandomState()"
                   " — every draw must derive from a config seed")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            member = _np_random_member(node, mod.aliases)
            if member is None or "." in member:
                continue
            if member in _CONSTRUCTORS:
                if not node.args and not node.keywords:
                    yield Finding(
                        mod.path, node.lineno, "determinism-unseeded-rng",
                        f"np.random.{member}() without a seed draws OS "
                        "entropy — derive the seed from the experiment "
                        "config instead")
            else:
                yield Finding(
                    mod.path, node.lineno, "determinism-global-random",
                    f"np.random.{member} uses numpy's GLOBAL stream — "
                    "order-dependent across modules/threads; use a seeded "
                    "np.random.default_rng(...) (reference-parity shims "
                    "must carry a pragma citing the reference lines)")
