"""obs-discipline rules: telemetry stays OUT of traced code (ISSUE 9).

The obs/ plane's contract is host-boundary-only instrumentation. Inside
a function handed to jit/vmap/shard_map/lax combinators,

- a wall/monotonic clock read (``time.time``/``monotonic``/
  ``perf_counter``/...) executes ONCE at trace time and bakes that one
  Python float into the compiled executable — every subsequent dispatch
  reports the same "timestamp", which is worse than no timestamp
  because it looks plausible;
- a metrics-registry / flight-recorder / span-tracer mutation
  (``.inc()``, ``.observe()``, anything in
  ``neuroimagedisttraining_tpu.obs``) likewise runs once at trace time:
  the counter moves by one forever, the flight ring records one
  phantom event, and the span measures tracing, not execution.

Both rules ride the trace-safety resolver (``collect_traced``: decorated
jits, functions passed to tracers, lambdas, self-methods, and the
transitive call closure), so an instrumented helper CALLED from a round
body is caught just like a decorated one.

Lexical honesty: ``.set(...)`` is NOT flagged — the attribute name is
too generic (``jnp.ndarray.at[...].set`` is the single most common call
in the round programs). A gauge set inside a trace is still wrong; it
is covered whenever it is spelled through the obs package
(``obs_metrics.gauge(...)...``), which every shipped call site does.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)
from neuroimagedisttraining_tpu.analysis.trace_safety import collect_traced

#: clock reads by canonical dotted name — one trace-time value baked in
CLOCK_DOTTED = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "time.thread_time", "time.thread_time_ns",
}

#: unambiguous registry-mutation method names (Counter.inc /
#: Histogram.observe); Gauge.set is excluded — see the module docstring
MUTATION_METHODS = {"inc", "observe"}

#: any call into the obs package is telemetry (metrics, flight ring,
#: span tracer) and has no business inside a traced body
OBS_PREFIX = "neuroimagedisttraining_tpu.obs"


@register
class ObsDisciplineRule(Rule):
    rule_ids = ("obs-clock-in-trace", "obs-metrics-in-trace")
    description = (
        "no wall/monotonic clock reads (obs-clock-in-trace) or metrics-"
        "registry/flight/span mutation (obs-metrics-in-trace) lexically "
        "inside functions handed to jit/vmap/shard_map/lax combinators")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        seen: set[int] = set()
        for root in collect_traced(mod):
            for node in ast.walk(root):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                seen.add(id(node))
                yield from self._check_call(mod, node)

    def _check_call(self, mod: ModuleInfo,
                    node: ast.Call) -> Iterator[Finding]:
        name = normalize(dotted_name(node.func), mod.aliases)
        if name in CLOCK_DOTTED:
            yield Finding(
                mod.path, node.lineno, "obs-clock-in-trace",
                f"{name} inside a traced function bakes ONE trace-time "
                "clock value into the compiled executable — time at "
                "host boundaries only (obs/trace.py)")
            return
        if name is not None and (name == OBS_PREFIX
                                 or name.startswith(OBS_PREFIX + ".")):
            yield Finding(
                mod.path, node.lineno, "obs-metrics-in-trace",
                f"{name} inside a traced function runs ONCE at trace "
                "time (a frozen counter / phantom flight event / "
                "tracing-time span) — instrument at host boundaries "
                "only")
            return
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATION_METHODS):
            yield Finding(
                mod.path, node.lineno, "obs-metrics-in-trace",
                f".{node.func.attr}() (metrics-registry mutation) "
                "inside a traced function runs once at trace time and "
                "never again — publish at host boundaries only")


#: the analysis package imports this module for registration
__all__ = ["ObsDisciplineRule", "CLOCK_DOTTED", "MUTATION_METHODS"]
