"""obs-discipline rules: telemetry stays OUT of traced code (ISSUE 9).

The obs/ plane's contract is host-boundary-only instrumentation. Inside
a function handed to jit/vmap/shard_map/lax combinators,

- a wall/monotonic clock read (``time.time``/``monotonic``/
  ``perf_counter``/...) executes ONCE at trace time and bakes that one
  Python float into the compiled executable — every subsequent dispatch
  reports the same "timestamp", which is worse than no timestamp
  because it looks plausible;
- a metrics-registry / flight-recorder / span-tracer mutation
  (``.inc()``, ``.observe()``, anything in
  ``neuroimagedisttraining_tpu.obs``) likewise runs once at trace time:
  the counter moves by one forever, the flight ring records one
  phantom event, and the span measures tracing, not execution.

Both rules ride the trace-safety resolver (``collect_traced``: decorated
jits, functions passed to tracers, lambdas, self-methods, and the
transitive call closure), so an instrumented helper CALLED from a round
body is caught just like a decorated one.

ISSUE 14 extension (the dispatch profiler's own discipline):

- ``obs-sync-in-trace``: the compute-plane profiler (obs/compute.py)
  times dispatches with HOST wall around the enqueue and closes MFU
  windows at already-synced host boundaries — its contract is ZERO
  added device syncs. ``jax.block_until_ready(...)`` or
  ``.block_until_ready()`` inside a traced body is at best a trace-time
  no-op and at worst the hidden-sync bug class the profiler wiring
  could smuggle in; ``jax.device_get`` in the same position is already
  a trace-safety finding, this closes the block_until_ready gap.

Lexical honesty: ``.set(...)`` is NOT flagged — the attribute name is
too generic (``jnp.ndarray.at[...].set`` is the single most common call
in the round programs). A gauge set inside a trace is still wrong; it
is covered whenever it is spelled through the obs package
(``obs_metrics.gauge(...)...``), which every shipped call site does.

ISSUE 13 extensions (the federation-wide fan-in's own discipline):

- ``obs-trace-ctx-key``: the wire trace context rides exactly ONE
  frame key, ``distributed.message.ARG_TRACE_CTX``. A ``msg.add(
  "trace_ctx", ...)``/``msg.get("trace_ctx")`` spelled with the string
  literal works today and silently desyncs the day the constant
  changes — the same reason the ``!Q`` framing collapsed into one
  definition. Only the definition site (distributed/message.py) may
  spell the literal.
- ``obs-pipe-per-upload``: in ``asyncfl/ingest.py`` telemetry crosses
  the worker->root pipe BATCHED ("vb" verdict batches, "beats"
  heartbeat sets, "obs" telemetry payloads). A per-upload spelling —
  ``conn.send(("v", ...))`` / ``conn.send(("beat", ...))`` — reverts
  the measured fan-in win (one pipe syscall costs ~0.5-1 ms on this
  box's sandboxed kernel) and is flagged wherever it appears in that
  module.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)
from neuroimagedisttraining_tpu.analysis.trace_safety import collect_traced

#: clock reads by canonical dotted name — one trace-time value baked in
CLOCK_DOTTED = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "time.thread_time", "time.thread_time_ns",
}

#: unambiguous registry-mutation method names (Counter.inc /
#: Histogram.observe); Gauge.set is excluded — see the module docstring
MUTATION_METHODS = {"inc", "observe"}

#: device-sync spellings a dispatch timer must never smuggle into a
#: traced body (ISSUE 14, obs-sync-in-trace): ``jax.block_until_ready``
#: by dotted name plus the zero-arg ``.block_until_ready()`` method.
#: ``jax.device_get`` is already a trace-safety finding
#: (trace_safety.HOST_SYNC_DOTTED); this rule closes the
#: block-until-ready gap the compute profiler's wiring could otherwise
#: slip through — the profiler's contract is host wall around the
#: ENQUEUE, never a sync inside the program.
SYNC_DOTTED = {"jax.block_until_ready"}
SYNC_METHODS = {"block_until_ready"}

#: any call into the obs package is telemetry (metrics, flight ring,
#: span tracer) and has no business inside a traced body
OBS_PREFIX = "neuroimagedisttraining_tpu.obs"


@register
class ObsDisciplineRule(Rule):
    rule_ids = ("obs-clock-in-trace", "obs-metrics-in-trace",
                "obs-sync-in-trace")
    description = (
        "no wall/monotonic clock reads (obs-clock-in-trace), metrics-"
        "registry/flight/span mutation (obs-metrics-in-trace), or "
        "device syncs — jax.block_until_ready / .block_until_ready() "
        "(obs-sync-in-trace: dispatch timers live at host boundaries "
        "only) — lexically inside functions handed to "
        "jit/vmap/shard_map/lax combinators")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        seen: set[int] = set()
        for root in collect_traced(mod):
            for node in ast.walk(root):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                seen.add(id(node))
                yield from self._check_call(mod, node)

    def _check_call(self, mod: ModuleInfo,
                    node: ast.Call) -> Iterator[Finding]:
        name = normalize(dotted_name(node.func), mod.aliases)
        if name in CLOCK_DOTTED:
            yield Finding(
                mod.path, node.lineno, "obs-clock-in-trace",
                f"{name} inside a traced function bakes ONE trace-time "
                "clock value into the compiled executable — time at "
                "host boundaries only (obs/trace.py)")
            return
        if name in SYNC_DOTTED or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS and not node.args):
            yield Finding(
                mod.path, node.lineno, "obs-sync-in-trace",
                "device sync (block_until_ready) inside a traced "
                "function: at best a trace-time no-op, and exactly the "
                "hidden-sync class of bug the dispatch profiler's "
                "zero-sync contract forbids (obs/compute.py) — sync "
                "and time at host boundaries only")
            return
        if name is not None and (name == OBS_PREFIX
                                 or name.startswith(OBS_PREFIX + ".")):
            yield Finding(
                mod.path, node.lineno, "obs-metrics-in-trace",
                f"{name} inside a traced function runs ONCE at trace "
                "time (a frozen counter / phantom flight event / "
                "tracing-time span) — instrument at host boundaries "
                "only")
            return
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATION_METHODS):
            yield Finding(
                mod.path, node.lineno, "obs-metrics-in-trace",
                f".{node.func.attr}() (metrics-registry mutation) "
                "inside a traced function runs once at trace time and "
                "never again — publish at host boundaries only")


#: the single wire trace-context key (distributed.message.ARG_TRACE_CTX)
TRACE_CTX_LITERAL = "trace_ctx"

#: per-upload pipe-event spellings the batched protocol replaced
UNBATCHED_PIPE_KINDS = {"v", "beat"}


@register
class ObsFanInRule(Rule):
    """ISSUE 13: wire-trace-context key discipline + batched-pipe
    telemetry discipline (module docstring)."""

    rule_ids = ("obs-trace-ctx-key", "obs-pipe-per-upload")
    description = (
        "trace context must ride the single ARG_TRACE_CTX constant "
        "(obs-trace-ctx-key: no 'trace_ctx' string literals in "
        ".add()/.get() outside distributed/message.py), and "
        "asyncfl/ingest.py telemetry pipe sends must be batched "
        "(obs-pipe-per-upload: no ('v', ...)/('beat', ...) events)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        is_message_py = mod.path_parts[-2:] == ("distributed",
                                                "message.py")
        is_ingest_py = mod.path_parts[-2:] == ("asyncfl", "ingest.py")
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if (not is_message_py and node.func.attr in ("add", "get")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == TRACE_CTX_LITERAL):
                yield Finding(
                    mod.path, node.lineno, "obs-trace-ctx-key",
                    "the wire trace context rides exactly ONE frame "
                    "key — spell it M.ARG_TRACE_CTX "
                    "(distributed/message.py), not the string literal "
                    "(an ad-hoc key silently unlinks the flow chain)")
            if (is_ingest_py and node.func.attr == "send" and node.args
                    and isinstance(node.args[0], ast.Tuple)
                    and node.args[0].elts
                    and isinstance(node.args[0].elts[0], ast.Constant)
                    and node.args[0].elts[0].value
                    in UNBATCHED_PIPE_KINDS):
                kind = node.args[0].elts[0].value
                yield Finding(
                    mod.path, node.lineno, "obs-pipe-per-upload",
                    f"per-upload pipe event ({kind!r}) in the ingest "
                    "telemetry path — batch it (verdicts ride 'vb', "
                    "heartbeats 'beats', telemetry 'obs'): one pipe "
                    "send costs ~0.5-1 ms on sandboxed kernels and "
                    "per-upload sends were the measured fan-in choke")


#: the analysis package imports this module for registration
__all__ = ["ObsDisciplineRule", "ObsFanInRule", "CLOCK_DOTTED",
           "MUTATION_METHODS", "SYNC_DOTTED", "SYNC_METHODS",
           "TRACE_CTX_LITERAL", "UNBATCHED_PIPE_KINDS"]
