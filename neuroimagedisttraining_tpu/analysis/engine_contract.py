"""Engine-contract rules: every ``FederatedEngine`` subclass keeps the
round contract that ``__main__``/``create_engine`` and the streaming
dispatcher rely on.

Checked per class (ancestry resolved lexically through the file's own
classes plus sibling files in the same directory, so ``FedProxEngine
(FedAvgEngine)`` is recognized as an engine):

- ``engine-attrs``   — ``name`` must be declared in the class's OWN body
  (inheriting it would collide in the ``ENGINES`` registry);
  ``supports_streaming`` must be declared there or on a non-root ancestor
  (the root default would silently opt an engine out of streaming).
- ``engine-round``   — the required round method ``train`` must be defined
  on the class or a non-root ancestor (the root only raises).
- ``engine-signature`` — any override of a ``FederatedEngine`` method must
  keep the base positional signature (extra trailing params need
  defaults), so engines stay drop-in interchangeable.

Reference signatures come from ``engines/base.py`` next to the linted
file when present, falling back to the packaged one — fixtures in a temp
directory are checked against the real contract.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

ROOT_CLASS = "FederatedEngine"
REQUIRED_OWN_ATTRS = ("name",)
REQUIRED_INHERITABLE_ATTRS = ("supports_streaming",)
REQUIRED_ROUND_METHODS = ("train",)

#: (positional arg names, #defaults, has *args, has **kwargs)
_Sig = tuple[tuple[str, ...], int, bool, bool]


@dataclasses.dataclass
class _ClassInfo:
    name: str
    bases: tuple[str, ...]
    attrs: set[str]
    methods: dict[str, _Sig]
    method_lines: dict[str, int]
    lineno: int


def _signature(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> _Sig:
    a = fn.args
    names = tuple(p.arg for p in (*a.posonlyargs, *a.args))
    return (names, len(a.defaults), a.vararg is not None,
            a.kwarg is not None)


def _classes_of(tree: ast.Module) -> dict[str, _ClassInfo]:
    out: dict[str, _ClassInfo] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: set[str] = set()
        methods: dict[str, _Sig] = {}
        lines: dict[str, int] = {}
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                attrs.update(t.id for t in stmt.targets
                             if isinstance(t, ast.Name))
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                attrs.add(stmt.target.id)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = _signature(stmt)
                lines[stmt.name] = stmt.lineno
        bases = tuple(n.split(".")[-1] for n in
                      (dotted_name(b) for b in node.bases) if n)
        out[node.name] = _ClassInfo(node.name, bases, attrs, methods,
                                    lines, node.lineno)
    return out


_PACKAGED_BASE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "engines", "base.py")
_dir_cache: dict[str, dict[str, _ClassInfo]] = {}


def _parse_file(path: str) -> dict[str, _ClassInfo]:
    try:
        with open(path, encoding="utf-8") as fh:
            return _classes_of(ast.parse(fh.read(), filename=path))
    except (OSError, SyntaxError):
        return {}


def _sibling_classes(path: str) -> dict[str, _ClassInfo]:
    """Classes from every other .py in the linted file's directory."""
    d = os.path.dirname(os.path.abspath(path))
    if d not in _dir_cache:
        table: dict[str, _ClassInfo] = {}
        if os.path.isdir(d):
            for fn in sorted(os.listdir(d)):
                if fn.endswith(".py"):
                    table.update(_parse_file(os.path.join(d, fn)))
        _dir_cache[d] = table
    table = dict(_dir_cache[d])
    return table


@register
class EngineContractRule(Rule):
    rule_ids = ("engine-attrs", "engine-round", "engine-signature")
    description = ("FederatedEngine subclasses declare name/"
                   "supports_streaming, define the round method train, and "
                   "keep base-method signatures from engines/base.py")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        table = _sibling_classes(mod.path)
        table.update(_classes_of(mod.tree))  # the in-memory source wins
        if ROOT_CLASS not in table:
            table.update(_parse_file(_PACKAGED_BASE))
        base = table.get(ROOT_CLASS)
        for info in _classes_of(mod.tree).values():
            if info.name == ROOT_CLASS:
                continue
            chain = self._engine_ancestry(info, table)
            if chain is None:
                continue
            yield from self._check_class(mod, info, chain, base)

    @staticmethod
    def _engine_ancestry(info: _ClassInfo,
                         table: dict[str, _ClassInfo]
                         ) -> list[_ClassInfo] | None:
        """Non-root ancestors (nearest first) if ``info`` reaches
        ``FederatedEngine``; None when it is not an engine class."""
        chain: list[_ClassInfo] = []
        seen = {info.name}
        frontier = list(info.bases)
        reached = False
        while frontier:
            b = frontier.pop(0)
            if b == ROOT_CLASS:
                reached = True
                continue
            anc = table.get(b)
            if anc is None or anc.name in seen:
                continue
            seen.add(anc.name)
            chain.append(anc)
            frontier.extend(anc.bases)
        return chain if reached else None

    def _check_class(self, mod: ModuleInfo, info: _ClassInfo,
                     ancestors: list[_ClassInfo],
                     base: _ClassInfo | None) -> Iterator[Finding]:
        for attr in REQUIRED_OWN_ATTRS:
            if attr not in info.attrs:
                yield Finding(
                    mod.path, info.lineno, "engine-attrs",
                    f"engine class {info.name} must declare the class attr "
                    f"{attr!r} in its own body (an inherited value would "
                    "collide in the ENGINES registry)")
        inherited = set().union(*(a.attrs for a in ancestors), set())
        for attr in REQUIRED_INHERITABLE_ATTRS:
            if attr not in info.attrs and attr not in inherited:
                yield Finding(
                    mod.path, info.lineno, "engine-attrs",
                    f"engine class {info.name} must declare {attr!r} "
                    "(falling through to the FederatedEngine default "
                    "silently changes streaming eligibility)")
        defined = set(info.methods).union(*(a.methods for a in ancestors),
                                          set())
        for meth in REQUIRED_ROUND_METHODS:
            if meth not in defined:
                yield Finding(
                    mod.path, info.lineno, "engine-round",
                    f"engine class {info.name} must define the round "
                    f"method {meth}() (the FederatedEngine base only "
                    "raises NotImplementedError)")
        if base is not None:
            yield from self._check_signatures(mod, info, base)

    @staticmethod
    def _check_signatures(mod: ModuleInfo, info: _ClassInfo,
                          base: _ClassInfo) -> Iterator[Finding]:
        for meth, sig in info.methods.items():
            ref = base.methods.get(meth)
            if ref is None:
                continue
            names, n_defaults, has_var, _ = sig
            ref_names = ref[0]
            # a *args override may absorb the tail of the base signature
            prefix_ok = (names[:len(ref_names)] == ref_names
                         or (has_var and ref_names[:len(names)] == names))
            extras = names[len(ref_names):]
            extras_defaulted = len(extras) <= n_defaults
            if not prefix_ok or (extras and not extras_defaulted
                                 and not has_var):
                yield Finding(
                    mod.path, info.method_lines[meth], "engine-signature",
                    f"{info.name}.{meth}{tuple(names)!r} does not match "
                    f"the FederatedEngine contract {meth}"
                    f"{tuple(ref_names)!r} from engines/base.py (extra "
                    "params must be trailing with defaults)")
