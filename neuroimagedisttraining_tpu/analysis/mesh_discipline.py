"""Mesh-discipline rules: explicit shard_map specs, one pad-weight rule.

Cohort sharding (ISSUE 6, parallel/cohort.py) put a second family of
``shard_map`` programs in the tree and made zero-weight pad rows a
correctness invariant (a pad row that keeps its gathered sample count
VOTES in the aggregation — silently, since a pad often duplicates a real
client's id). Two lexical rules keep both honest:

- ``mesh-shardmap-specs`` — every ``shard_map`` call must pass BOTH
  ``in_specs`` and ``out_specs`` as explicit keywords. An omitted spec
  either crashes at trace time (hard to attribute through the engine
  stack) or — worse, on API versions that default it — silently
  replicates an axis the caller meant to shard, turning a sharded round
  into C copies of the same work. The placement contract must be
  visible at the call site.
- ``mesh-pad-weights`` — pad-row weight masks must come from THE shared
  helper (``parallel.cohort.pad_row_weights``); reconstructing the
  ``arange(...) < n_real`` position mask ad hoc is flagged anywhere
  outside ``parallel/cohort.py``. The helper is one line — the rule
  exists because the half-correct rewrite (zeroing by gathered sample
  count instead of by position) type-checks, runs, and double-counts a
  duplicated client.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)

#: the one module allowed to build pad-row position masks by hand
_PAD_HELPER_HOME = "cohort.py"


def _is_arange_call(node: ast.AST, aliases: dict) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = normalize(dotted_name(node.func), aliases) or ""
    return name.split(".")[-1] == "arange"


def _terminal_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


@register
class MeshDisciplineRule(Rule):
    rule_ids = ("mesh-shardmap-specs", "mesh-pad-weights")
    description = (
        "shard_map calls must declare explicit in_specs AND out_specs "
        "(mesh-shardmap-specs); pad-row zero-weight masks must come from "
        "parallel.cohort.pad_row_weights, not ad-hoc arange(...) < n_real "
        "comparisons (mesh-pad-weights)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        yield from self._check_shardmap_specs(mod)
        yield from self._check_pad_weights(mod)

    # ---------- mesh-shardmap-specs ----------

    def _check_shardmap_specs(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = normalize(dotted_name(node.func), mod.aliases) or ""
            if name.split(".")[-1] != "shard_map":
                continue
            kwargs = {kw.arg for kw in node.keywords}
            missing = sorted({"in_specs", "out_specs"} - kwargs)
            if missing:
                yield Finding(
                    mod.path, node.lineno, "mesh-shardmap-specs",
                    f"shard_map call omits explicit {' and '.join(missing)}"
                    " — the placement contract must be declared at the "
                    "call site (a defaulted spec silently replicates an "
                    "axis the caller meant to shard)")

    # ---------- mesh-pad-weights ----------

    def _check_pad_weights(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.path_parts and mod.path_parts[-1] == _PAD_HELPER_HOME \
                and "parallel" in mod.path_parts:
            return  # the helper's own home
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            has_arange = any(_is_arange_call(s, mod.aliases)
                             for s in sides)
            names = {_terminal_name(s) for s in sides}
            if has_arange and "n_real" in names:
                yield Finding(
                    mod.path, node.lineno, "mesh-pad-weights",
                    "ad-hoc pad-row mask (arange(...) compared against "
                    "n_real) — use parallel.cohort.pad_row_weights, the "
                    "one audited zero-weight construction (pads may "
                    "DUPLICATE a real client id; zeroing by position is "
                    "the only correct rule)")
