"""health-rule-discipline: metric names live in the declared table
(ISSUE 15).

The anomaly-rule engine (obs/rules.py) validates every rule manifest —
built-in and ``--health_rules``-loaded — against the declared
metric-name set in ``obs/names.py`` at STARTUP, so a typo'd rule fails
with the known-names list instead of silently never firing. That
contract only holds while the declared set actually covers every name
the tree publishes, which is what this lint family enforces from the
other side:

- ``health-metric-literal``: a string literal that IS a metric name
  (full-match ``nidt_[a-z0-9_]+``) anywhere outside the ``obs/``
  package is a finding — spell the ``obs/names.py`` constant instead.
  A literal spelling registers and publishes fine today and silently
  drifts out of the declared set the day it is renamed, at which point
  every rule watching it goes permanently dark (the exact failure mode
  the trace-ctx-key rule fences for the flow chain). Prose that merely
  MENTIONS a metric ("the nidt_mfu gauge's denominator") is not a full
  match and is untouched; derived exposition names are spelled as
  ``obs_names.X + "_bucket"``.

``obs/`` itself is exempt: it is the declaration side — ``names.py``
holds the constants, and the obs modules' registrations are the
definitions the table mirrors.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    register,
)

#: a whole-string metric name (not prose containing one)
METRIC_NAME_RE = re.compile(r"nidt_[a-z0-9_]+\Z")


def _in_obs_package(mod: ModuleInfo) -> bool:
    return "obs" in mod.path_parts[:-1]


@register
class HealthRuleDisciplineRule(Rule):
    rule_ids = ("health-metric-literal",)
    description = (
        "metric-name string literals (full-match nidt_*) outside the "
        "obs/ package — spell the obs/names.py constant so the "
        "declared-name set the anomaly-rule engine validates against "
        "(obs/rules.py) stays the single source of truth")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if _in_obs_package(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Constant) \
                    or not isinstance(node.value, str):
                continue
            if METRIC_NAME_RE.fullmatch(node.value):
                yield Finding(
                    mod.path, node.lineno, "health-metric-literal",
                    f"metric name {node.value!r} spelled as a string "
                    "literal outside obs/ — use the obs/names.py "
                    "constant (a renamed literal silently leaves the "
                    "declared set the health rules are validated "
                    "against)")


__all__ = ["HealthRuleDisciplineRule", "METRIC_NAME_RE"]
