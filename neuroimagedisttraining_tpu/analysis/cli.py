"""``nidtlint`` command line: ``python -m neuroimagedisttraining_tpu.analysis``.

Exit codes: 0 clean, 1 findings, 2 usage error. Default output is one
``file:line rule-id message`` per finding; ``--json`` emits a machine-
readable report for CI annotation.

Beyond the per-file pass:

- ``--project`` runs the whole-program contract checker
  (analysis/contracts.py) over the cross-file model instead of linting
  the given paths.
- ``--cache [DIR]`` memoizes per-file findings by content hash
  (default ``.nidtlint_cache/``, gitignored); ``--changed-files``
  restricts the per-file pass to files git reports as modified or
  untracked (falls back to linting everything when git is unavailable).
- ``--regen-compat`` rewrites the generated compatibility-matrix
  artifact (analysis/compat_matrix.py) and its ARCHITECTURE.md twin.
- ``--check-manifest FILE`` validates a health-rule JSON manifest's
  metric names against obs/names.py without importing the runtime —
  the script-start gate for run_chaos_smoke.sh / run_health_report.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Sequence

from neuroimagedisttraining_tpu.analysis import lint_paths
from neuroimagedisttraining_tpu.analysis.core import RULE_REGISTRY

DEFAULT_CACHE = ".nidtlint_cache"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m neuroimagedisttraining_tpu.analysis",
        description=("nidtlint: AST invariant checker for trace-safety, "
                     "engine contracts, lock discipline and determinism, "
                     "plus the --project whole-program contract pass"))
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as a JSON array")
    p.add_argument("--rules", default=None, metavar="ID[,ID...]",
                   help="only run the named rule ids")
    p.add_argument("--list-rules", action="store_true",
                   help="print every rule family and exit")
    p.add_argument("--project", action="store_true",
                   help="run the cross-file contract checker over the "
                        "package tree instead of the per-file pass")
    p.add_argument("--cache", nargs="?", const=DEFAULT_CACHE, default=None,
                   metavar="DIR",
                   help="memoize per-file findings by content hash "
                        f"(default dir: {DEFAULT_CACHE})")
    p.add_argument("--changed-files", action="store_true",
                   help="per-file pass: only lint files git reports as "
                        "changed/untracked (everything, if git fails)")
    p.add_argument("--regen-compat", action="store_true",
                   help="regenerate analysis/compat_matrix.py and the "
                        "ARCHITECTURE.md compat-matrix block, then exit")
    p.add_argument("--check-manifest", default=None, metavar="FILE",
                   help="validate a health-rule JSON manifest's metric "
                        "names against obs/names.py, then exit")
    return p


def _git_changed(repo_root: str) -> set[str] | None:
    """Absolute paths of modified + untracked files, or None when git
    is unusable (not a checkout, binary missing, ...)."""
    out: set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, cwd=repo_root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if res.returncode != 0:
            return None
        out |= {os.path.abspath(os.path.join(repo_root, line))
                for line in res.stdout.splitlines() if line.strip()}
    return out


def _check_manifest(path: str) -> int:
    """Metric-closure validation of a health-rule manifest: every rule's
    ``metric`` must be a value declared in obs/names.py. Static — the
    manifest is judged without importing the runtime (or jax)."""
    from neuroimagedisttraining_tpu.analysis.project import (
        build_model, default_root, names_table)
    try:
        with open(path, encoding="utf-8") as fh:
            rules = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read manifest {path}: {e}", file=sys.stderr)
        return 2
    if not isinstance(rules, list):
        print(f"error: manifest {path} must be a JSON array of rule "
              "objects", file=sys.stderr)
        return 2
    root, package = default_root()
    names_mod = build_model(root, package).find("obs/names.py")
    declared = ({v for v, _ in names_table(names_mod).values()}
                if names_mod else set())
    bad = 0
    for i, rule in enumerate(rules):
        if not isinstance(rule, dict):
            print(f"{path}: rule[{i}] is not an object", file=sys.stderr)
            bad += 1
            continue
        missing = [k for k in ("name", "metric", "op", "threshold")
                   if k not in rule]
        if missing:
            print(f"{path}: rule[{i}] ({rule.get('name', '?')}) lacks "
                  f"required keys: {', '.join(missing)}", file=sys.stderr)
            bad += 1
        metric = rule.get("metric")
        if metric is not None and metric not in declared:
            print(f"{path}: rule[{i}] ({rule.get('name', '?')}) watches "
                  f"undeclared metric {metric!r} — not in obs/names.py; "
                  "the rule would be permanently dark", file=sys.stderr)
            bad += 1
    if bad:
        print(f"nidtlint: manifest {path}: {bad} problem(s)",
              file=sys.stderr)
        return 1
    print(f"nidtlint: manifest {path}: {len(rules)} rule(s) OK, all "
          "metrics declared")
    return 0


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in RULE_REGISTRY.values():
            print(f"{', '.join(cls.rule_ids)}: {cls.description}")
        return 0
    if args.check_manifest is not None:
        return _check_manifest(args.check_manifest)
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if args.regen_compat:
        from neuroimagedisttraining_tpu.analysis.project import regen_compat
        py_path, md_path = regen_compat()
        print(f"regenerated {py_path}")
        print(f"regenerated {md_path} (compat-matrix block)")
        return 0
    if args.project:
        from neuroimagedisttraining_tpu.analysis.project import lint_project
        try:
            findings = lint_project(rules=rules)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        if not args.paths:
            print("error: no paths given (try --list-rules)",
                  file=sys.stderr)
            return 2
        paths = list(args.paths)
        if args.changed_files:
            changed = _git_changed(os.getcwd())
            if changed is not None:
                from neuroimagedisttraining_tpu.analysis.core import (
                    iter_py_files)
                paths = [fp for fp in iter_py_files(paths)
                         if os.path.abspath(fp) in changed]
                if not paths:
                    print("nidtlint: no changed .py files under the "
                          "given paths")
                    return 0
            else:
                print("nidtlint: git unavailable — linting everything",
                      file=sys.stderr)
        try:
            findings = lint_paths(paths, rules=rules, cache_dir=args.cache)
        except (FileNotFoundError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if args.as_json:
        print(json.dumps([f.as_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"nidtlint: {len(findings)} finding(s) "
                  f"across {len({f.path for f in findings})} file(s)",
                  file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover - exercised via __main__.py
    sys.exit(main())
