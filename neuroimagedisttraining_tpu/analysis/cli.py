"""``nidtlint`` command line: ``python -m neuroimagedisttraining_tpu.analysis``.

Exit codes: 0 clean, 1 findings, 2 usage error. Default output is one
``file:line rule-id message`` per finding; ``--json`` emits a machine-
readable report for CI annotation.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Sequence

from neuroimagedisttraining_tpu.analysis import lint_paths
from neuroimagedisttraining_tpu.analysis.core import RULE_REGISTRY


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m neuroimagedisttraining_tpu.analysis",
        description=("nidtlint: AST invariant checker for trace-safety, "
                     "engine contracts, lock discipline and determinism"))
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as a JSON array")
    p.add_argument("--rules", default=None, metavar="ID[,ID...]",
                   help="only run the named rule ids")
    p.add_argument("--list-rules", action="store_true",
                   help="print every rule family and exit")
    return p


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in RULE_REGISTRY.values():
            print(f"{', '.join(cls.rule_ids)}: {cls.description}")
        return 0
    if not args.paths:
        print("error: no paths given (try --list-rules)", file=sys.stderr)
        return 2
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        findings = lint_paths(args.paths, rules=rules)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps([f.as_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"nidtlint: {len(findings)} finding(s) "
                  f"across {len({f.path for f in findings})} file(s)",
                  file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover - exercised via __main__.py
    sys.exit(main())
